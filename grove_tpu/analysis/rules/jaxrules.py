"""GL005 JAX hygiene inside jitted kernels.

The packing kernel, the fair-share scan, and the ops package are the hot
compiled core; three classes of bug creep in silently during refactors:

- **Python side effects** traced into the jaxpr: `print(...)` runs at
  trace time only (lies during execution), `global` mutation desyncs
  host state from device state.
- **dtype creep**: a stray `float64` literal/dtype flips the whole
  lattice off the float32 contract the NumPy oracles are pinned against
  (bit-identical DRF ordering, packing parity) — and TPUs don't do f64.
- **Host round-trips**: `io_callback`/`pure_callback`/`jax.debug.*` and
  `.item()` force a device sync inside the compiled region.

Scope: `ops/`, `solver/kernel.py`, `quota/ordering.py` — functions
decorated with `jax.jit`/`partial(jax.jit, ...)` and everything nested
inside them (scan/cond bodies are closures).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from grove_tpu.analysis.engine import FileContext, Rule, Violation, dotted

_HOST_CALLBACKS = {"io_callback", "pure_callback", "print", "callback"}


def _is_jit_decorator(dec: ast.AST) -> bool:
    """jax.jit / jit / partial(jax.jit, ...) / jax.jit(...) shapes."""
    if isinstance(dec, ast.Call):
        name = dotted(dec.func)
        if name.endswith("jit"):
            return True
        if name in ("partial", "functools.partial") and dec.args:
            return dotted(dec.args[0]).endswith("jit")
        return False
    return dotted(dec).endswith("jit")


class JitHygieneRule(Rule):
    id = "GL005"
    name = "jit-hygiene"
    description = (
        "jitted kernels must be pure float32 device code: no print/global,"
        " no float64 literals or dtype creep, no host callbacks or .item()"
    )
    paths = (
        "grove_tpu/ops/",
        "grove_tpu/solver/kernel.py",
        "grove_tpu/quota/ordering.py",
    )

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        jitted: List[ast.AST] = []
        for fn in ctx.functions():
            if any(_is_jit_decorator(d) for d in fn.decorator_list):
                jitted.append(fn)
        seen: Set[int] = set()
        for fn in jitted:
            for node in ast.walk(fn):
                if id(node) in seen:
                    continue
                seen.add(id(node))
                msg = self._classify(node)
                if msg is not None:
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=node.lineno,
                        col=node.col_offset,
                        message=f"{msg} inside jitted `{fn.name}()`",
                    )

    @staticmethod
    def _classify(node: ast.AST):
        if isinstance(node, ast.Global):
            return "`global` mutation (host side effect traced away)"
        if isinstance(node, ast.Constant) and node.value == "float64":
            return "'float64' dtype literal (float32 contract; no f64 on TPU)"
        if isinstance(node, ast.Attribute) and node.attr == "float64":
            return (
                f"`{dotted(node)}` dtype (float32 contract; no f64 on TPU)"
            )
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id == "print":
                return "`print()` (trace-time only — use jax.debug outside the kernel)"
            if isinstance(fn, ast.Attribute):
                src = dotted(fn)
                if fn.attr == "item":
                    return "`.item()` host sync"
                if fn.attr in _HOST_CALLBACKS and (
                    "debug" in src or fn.attr in ("io_callback", "pure_callback")
                ):
                    return f"host callback `{src}()`"
                if src.startswith("time."):
                    return f"wall-clock `{src}()`"
        return None
