"""GL013 shard-internals encapsulation (docs/control-plane.md).

The keyspace-sharded store (runtime/shards.py) holds EVERY per-shard
structure — object maps, canonical blobs, label/namespace indices, the
shard's rv sequence and write lock, the per-shard system-watch fan-out
list, the level-1 pod aggregates — inside ``StoreShard``.
The invariants the router maintains (per-object optimistic concurrency
within exactly one shard, per-shard rv monotonicity, fan-out delivery
order, the S=1 byte-identity guarantee, GL011's logged-commit contract
carried per shard) all assume nobody ELSE touches those fields: a
consumer appending to a shard's ``system_watchers`` directly bypasses
the subscribe API's ordering contract, and reading ``shard.committed``
from a controller skips the readonly/materialize discipline the same way
reaching into ``store._committed`` did before GL004.

Flagged outside ``runtime/shards.py``, ``runtime/store.py`` and the
durability module (the three owners named in shards.py's contract):

- the store's shard-router privates (``store._shards``,
  ``store._shard_for(...)``, ``store._shard_of_obj(...)``,
  ``store._summary_tree``, ``store._single``)
- ``StoreShard`` fields accessed through a shard-named binding
  (``shard.committed``, ``shard.lock``, ``shard.rv``,
  ``shard.system_watchers``, ...)

Public surface stays public: ``store.num_shards``, ``shard_index()``,
``shard_resource_version()``, ``resource_version_vector()``,
``shard_census()``, ``shard_kinds()``/``shard_scan()``,
``subscribe_system(shard=k)`` and the ``shard_of`` keyspace map.
"""

from __future__ import annotations

import ast
from typing import Iterable

from grove_tpu.analysis.engine import FileContext, Rule, Violation, dotted

# the Store router's private sharding state (runtime/store.py)
_ROUTER_PRIVATE = {
    "_shards",
    "_shard_for",
    "_shard_of_obj",
    "_summary_tree",
    "_summary_tree_cached",
    "_summary_dirty",
    "_summary_dirty_cached",
    "_single",
}

# StoreShard's per-shard fields (runtime/shards.py __slots__, minus the
# public census handle `index`)
_SHARD_FIELDS = {
    "lock",
    "rv",
    "committed",
    "cache",
    "blob",
    "cache_blob",
    "label_index",
    "cache_label_index",
    "ns_index",
    "cache_ns_index",
    "system_watchers",
    "agg_committed",
    "agg_cached",
}


class ShardInternalsRule(Rule):
    id = "GL013"
    name = "shard-internals"
    description = (
        "a keyspace shard's internals (store._shards / StoreShard fields:"
        " per-shard locks, rv sequences, object maps,"
        " fan-out lists) are private to runtime/shards.py,"
        " runtime/store.py and the durability module — everything else"
        " goes through the Store router API"
    )
    # repo-wide like GL011: shard state corrupted from ANYWHERE breaks the
    # router's invariants
    paths = ("grove_tpu/",)
    exclude = (
        "grove_tpu/runtime/shards.py",
        "grove_tpu/runtime/store.py",
        "grove_tpu/durability/",
    )

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Attribute):
                continue
            if node.attr in _ROUTER_PRIVATE:
                base = dotted(node.value)
                leaf = base.split(".")[-1] if base else ""
                if "store" in leaf.lower():
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"shard-router private `{base}.{node.attr}`"
                            " accessed outside runtime/shards.py /"
                            " runtime/store.py / durability — use the"
                            " Store router API (shard_index,"
                            " shard_resource_version,"
                            " resource_version_vector, shard_scan,"
                            " subscribe_system(shard=k))"
                        ),
                    )
            elif node.attr in _SHARD_FIELDS:
                base = dotted(node.value)
                leaf = base.split(".")[-1] if base else ""
                # a shard-named binding carrying StoreShard state; plain
                # `self.lock` / `obj.cache` style fields elsewhere don't
                # match (their base isn't a shard)
                if "shard" in leaf.lower() and leaf.lower() != "num_shards":
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"StoreShard field `{base}.{node.attr}`"
                            " accessed outside the owning modules —"
                            " per-shard locks/buffers/maps are private"
                            " (GL013); route through the Store API"
                        ),
                    )
