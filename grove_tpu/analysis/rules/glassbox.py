"""GL015 glass-box state encapsulation (docs/observability.md).

The glass-box layer's honesty claims are invariants over PRIVATE state:

- the profiler's coverage arithmetic (self-times sum to outer wall)
  holds only if phases are opened/closed through ``PROFILER.phase()`` /
  ``.reconcile()`` — a call site that pokes ``PROFILER._hist`` or the
  per-thread ``_tls`` stack can make "coverage ≥ 95%" a lie;
- a journey's gap-free causal chain holds only if marks flow through the
  ``JOURNEYS.note_*`` API — writing ``_active``/``_done``/``_round``
  directly can fabricate or corrupt admission decompositions;
- the flight recorder's rings are evidence; out-of-band writes to
  ``_rings``/``_events``/``_errors`` would tamper with postmortems.

Flagged outside ``grove_tpu/observability/``: any WRITE (assignment,
augmented assignment, delete, or mutating call) to glass-box private
state reached through a glass-box-named binding (``PROFILER``,
``JOURNEYS``, ``FLIGHTREC``, or anything profiler/journey/flightrec-
named), plus direct writes to their ``enabled`` flags — arming goes
through ``enable()``/``disable()`` so sinks/hooks are installed and
removed consistently.
"""

from __future__ import annotations

import ast
from typing import Iterable

from grove_tpu.analysis.engine import FileContext, Rule, Violation, dotted

# private recording state across profile.py / journey.py / flightrec.py
_GLASS_PRIVATE = {
    "_hist",
    "_tls",
    "_toplevel_s",
    "_active",
    "_done",
    "_round",
    "_rings",
    "_events",
    "_errors",
    "_dump_seq",
    "_origin",
}
# arming must go through enable()/disable() (they install/remove the
# tracer FLIGHT_SINK and event-recorder sink atomically with the flag)
_GLASS_FLAGS = {"enabled"}

_GLASS_NAMES = ("profiler", "journey", "flightrec")

_MUTATORS = {"append", "add", "clear", "pop", "popitem", "update",
             "setdefault", "extend", "remove", "discard"}


def _glass_chain(base: str) -> bool:
    """The access chain runs through a glass-box-named binding
    (`PROFILER._hist`, `self.journeys._active`, `rec.flightrec._rings`)."""
    if not base:
        return False
    return any(
        any(g in seg.lower() for g in _GLASS_NAMES)
        for seg in base.split(".")
    )


class GlassBoxStateRule(Rule):
    id = "GL015"
    name = "glassbox-state"
    description = (
        "profiler/journey/flight-recorder recording state is private to"
        " grove_tpu/observability/ — instrument through phase()/note_*()/"
        "trigger(), arm through enable()/disable()"
    )
    paths = ("grove_tpu/",)
    exclude = (
        "grove_tpu/observability/profile.py",
        "grove_tpu/observability/journey.py",
        "grove_tpu/observability/flightrec.py",
    )

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        for node in ast.walk(ctx.tree):
            for name, base, lineno, col in self._written_attrs(node):
                if not _glass_chain(base):
                    continue
                if name in _GLASS_PRIVATE:
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=lineno,
                        col=col,
                        message=(
                            f"glass-box private state `{base}.{name}`"
                            " mutated outside grove_tpu/observability/ —"
                            " the coverage/journey/postmortem invariants"
                            " assume only the owning module writes it;"
                            " use the phase()/note_*()/trigger() API"
                            " (GL015)"
                        ),
                    )
                elif name in _GLASS_FLAGS:
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=lineno,
                        col=col,
                        message=(
                            f"`{base}.{name}` assigned directly — arm the"
                            " glass-box layer via enable()/disable() so"
                            " its tracer/event sinks install and remove"
                            " with the flag (GL015)"
                        ),
                    )

    @staticmethod
    def _written_attrs(node):
        """Every (attr, base, line, col) that `node` WRITES: assignment /
        augmented assignment / delete targets (tuple unpacking included),
        or a mutating method call on the attribute
        (`PROFILER._hist.clear()`)."""
        targets = ()
        if isinstance(node, (ast.Assign, ast.Delete)):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = (node.target,)
        for t in targets:
            elts = (
                t.elts if isinstance(t, (ast.Tuple, ast.List)) else (t,)
            )
            for elt in elts:
                inner = elt
                while isinstance(inner, ast.Subscript):
                    inner = inner.value
                if isinstance(inner, ast.Attribute):
                    yield (
                        inner.attr, dotted(inner.value), inner.lineno,
                        inner.col_offset,
                    )
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATORS
            and isinstance(node.func.value, ast.Attribute)
        ):
            owner = node.func.value
            yield (
                owner.attr, dotted(owner.value), owner.lineno,
                owner.col_offset,
            )
