"""GL012 dirty-mask registration for cluster-tensor inputs.

The incremental delta-solve state (solver/deltastate.py, docs/solver.md)
keeps the solver's cluster tensors — the free-capacity matrix, the node
encoding, the per-gang encoded specs — device-resident across ticks and
folds them from the store's watch stream plus a per-tick node signature.
That exactness argument has one blind spot: the **binding map**
(``SimCluster.bindings``). Store commits fire watch events and node
attribute changes are re-signed every tick, but ``bindings`` is a plain
dict — a direct write from outside its owner is invisible to BOTH
channels, so the maintained free rows silently drift until the periodic
audit catches them (and under the sanitizer, fails the run).

GL012 therefore flags, outside the owning modules:

- direct mutation of ``<cluster>.bindings`` (assignment, ``del``,
  in-place mutators) and writes to ``<cluster>.bindings_epoch`` — the
  epoch is ``rebuild_bindings``'s receipt, forging it fakes a resync;
- mutation of the delta state's private masks/tensors
  (``<delta>._free``, ``._dirty_nodes``, ``._specs``, ...) — the
  sanctioned registration API is ``invalidate()`` / ``mark_node_dirty()``
  / ``mark_gang_dirty()``.

A direct ``bindings`` write CAN be sound — when a store commit for the
same pod already fired (the event, not the dict, is the registration:
controller/nodehealth.py's eviction paths). Such sites carry the
mandatory-justification pragma; new ones must argue the same invariant.
"""

from __future__ import annotations

import ast
from typing import Iterable

from grove_tpu.analysis.engine import FileContext, Rule, Violation, dotted

# private delta-solve state: mutations outside solver/deltastate.py bypass
# the dirty-mask bookkeeping entirely (reads are fine)
_DELTA_PRIVATE = {
    "_free",
    "_enc_cache",
    "_node_pods",
    "_pod_node",
    "_dirty_nodes",
    "_dirty_gangs",
    "_specs",
    "_enc",
    "_node_sig",
    "_mirror_built",
    "_bindings_epoch",
}

_MUTATORS = {
    "append",
    "extend",
    "insert",
    "remove",
    "pop",
    "popitem",
    "clear",
    "update",
    "setdefault",
    "add",
    "discard",
}

_REGISTRATION_HINT = (
    " — register the mutation instead: commit through the store (the"
    " watch event IS the registration), bump via"
    " SimCluster.rebuild_bindings, or call the DeltaSolveState"
    " registration API (invalidate / mark_node_dirty / mark_gang_dirty)"
)


def _cluster_bindings(node: ast.AST):
    """(base, attr) when the attribute chain passes through
    ``<...cluster-ish>.bindings`` / ``.bindings_epoch``, else None."""
    probe = node
    while isinstance(probe, (ast.Attribute, ast.Subscript)):
        if isinstance(probe, ast.Attribute) and probe.attr in (
            "bindings",
            "bindings_epoch",
        ):
            base = dotted(probe.value)
            leaf = base.split(".")[-1] if base else ""
            if "cluster" in leaf.lower() or leaf == "self":
                return base, probe.attr
        probe = probe.value
    return None


def _delta_private(node: ast.AST):
    """(base, attr) when the chain passes through ``<...delta>.<_priv>``."""
    probe = node
    while isinstance(probe, (ast.Attribute, ast.Subscript)):
        if isinstance(probe, ast.Attribute) and probe.attr in _DELTA_PRIVATE:
            base = dotted(probe.value)
            leaf = base.split(".")[-1] if base else ""
            if "delta" in leaf.lower():
                return base, probe.attr
        probe = probe.value
    return None


class DirtyMaskRegistrationRule(Rule):
    id = "GL012"
    name = "dirty-mask-registration"
    description = (
        "writes to cluster-tensor inputs (the binding map, the delta"
        " state's masks/tensors) must go through a watched channel or the"
        " dirty-mask registration API — a bypassing write silently drifts"
        " the incremental solver state"
    )
    paths = ("grove_tpu/",)
    exclude = (
        # the owners: cluster.py maintains bindings under its own methods,
        # deltastate.py IS the mask bookkeeping
        "grove_tpu/sim/cluster.py",
        "grove_tpu/solver/deltastate.py",
    )

    def _hits(self, target: ast.AST):
        hit = _cluster_bindings(target)
        if hit is not None:
            return hit + ("binding map",)
        hit = _delta_private(target)
        if hit is not None:
            return hit + ("delta-solve state",)
        return None

    def _violation(
        self, ctx: FileContext, node, base, attr, kind, what
    ) -> Violation:
        return Violation(
            rule=self.id,
            path=ctx.rel,
            line=node.lineno,
            col=node.col_offset,
            message=(
                f"{what} of {kind} `{base}.{attr}` bypasses the dirty-mask"
                f" fold{_REGISTRATION_HINT}"
            ),
        )

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for target in targets:
                    hit = self._hits(target)
                    if hit is not None:
                        yield self._violation(
                            ctx, node, *hit, "direct assignment"
                        )
            elif isinstance(node, ast.Delete):
                for target in node.targets:
                    hit = self._hits(target)
                    if hit is not None:
                        yield self._violation(ctx, node, *hit, "`del`")
            elif isinstance(node, ast.Call):
                fn = node.func
                if isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS:
                    hit = self._hits(fn.value)
                    if hit is not None:
                        yield self._violation(
                            ctx,
                            node,
                            *hit,
                            f"in-place `.{fn.attr}()` mutation",
                        )
