"""GL020 process-boundary (docs/control-plane.md §5).

The worker-process control plane (runtime/procworkers.py) crosses its
process boundary ONLY through the wire codec: JSON envelopes over
``Connection.send_bytes``/``recv_bytes``. That is a semantic contract,
not a style preference — pickling a store object onto the channel would
ship live references (clock, subscriber lists, lock state) whose
unpickled twins silently diverge from the coordinator's, and the
serial-twin bit-identity argument (tests/test_procworkers.py) would rot
into "usually identical". The boundary also carries the durability
story: WAL records written by a worker must be byte-identical to the
serial run's, which only the deterministic wire encoding guarantees.

Scope: any module that imports ``multiprocessing`` owns a process
boundary, and inside it:

- ``import pickle`` / ``marshal`` / ``dill`` / ``shelve`` (and
  ``from pickle import ...``) are flagged — object serialization on a
  boundary module bypasses the codec (runtime/store.py's in-process
  canonical blobs are fine: that module never forks);
- ``conn.send(...)`` / ``conn.recv()`` — the PICKLING Connection
  methods — are flagged; the codec path is ``send_bytes``/
  ``recv_bytes`` around an explicit encode/decode;
- ``multiprocessing.Queue``/``SimpleQueue``/``JoinableQueue``/
  ``Manager``/``Pool`` are flagged: each is a transparently-pickling
  channel, invisible to the codec discipline.

A second tooth is tree-wide (like GL018's privacy tooth): the process
drain's channel/generation state (``_procs``/``_conns``/``_log``/
``_cursors``/``_rings``/``_ring_gate``/``_dead``/``_gen_active``/
``_epoch``) reached through a drain/workers-named binding takes no
foreign writer — a foreign ``_conns`` poke could tear a round's frame
sequence mid-generation. The documented chaos hook
(``chaos_kill_worker``) and the public surface (``enable_workers``,
``drain``, ``stats``, ``close``) pass anywhere.
"""

from __future__ import annotations

import ast
from typing import Iterable, Set

from grove_tpu.analysis.engine import FileContext, Rule, Violation, dotted

_BANNED_IMPORTS = {"pickle", "marshal", "dill", "shelve", "cPickle"}
_PICKLING_CHANNEL_CTORS = {
    "Queue",
    "SimpleQueue",
    "JoinableQueue",
    "Manager",
    "Pool",
}
_PICKLING_CONN_METHODS = {"send", "recv"}
# the process drain's channel/generation privates (runtime/procworkers.py
# owns them; reached through a drain/workers-named binding elsewhere they
# accept no foreign writer)
_DRAIN_PRIVATE = {
    "_procs",
    "_conns",
    "_log",
    "_cursors",
    "_rings",
    "_ring_gate",
    "_dead",
    "_gen_active",
    "_epoch",
}
_DRAIN_OWNER = "grove_tpu/runtime/procworkers.py"


def _mp_aliases(tree: ast.AST) -> Set[str]:
    """Local names bound to the multiprocessing module (handles `import
    multiprocessing as mp` and `get_context()` results are still reached
    via attribute calls on these)."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.split(".")[0] == "multiprocessing":
                    names.add(alias.asname or "multiprocessing")
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "").split(".")[0] == "multiprocessing":
                names.add("")  # marks the file as boundary-owning
    return names


class ProcessBoundaryRule(Rule):
    id = "GL020"
    name = "process-boundary"
    description = (
        "a module that forks worker processes crosses the boundary only"
        " through the wire codec: no pickle/marshal imports, no pickling"
        " Connection.send/recv (use send_bytes/recv_bytes around an"
        " explicit encode/decode), no transparently-pickling"
        " multiprocessing channels (Queue/Manager/Pool)"
    )
    # repo-wide: ANY module may decide to fork; the moment it imports
    # multiprocessing it inherits the codec discipline
    paths = ("grove_tpu/",)
    exclude = ()

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        if ctx.rel != _DRAIN_OWNER:
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Attribute):
                    continue
                base = dotted(node.value)
                leaf = (base.split(".")[-1] if base else "").lower()
                if node.attr in _DRAIN_PRIVATE and (
                    "drain" in leaf or "workers" in leaf
                ):
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"process-drain private `{base}.{node.attr}`"
                            " touched outside runtime/procworkers.py"
                            " (GL020 process-boundary) — the channel/"
                            "generation state takes no foreign writer;"
                            " go through the public drain API"
                        ),
                    )
        mp_names = _mp_aliases(ctx.tree)
        if not mp_names:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                mods = (
                    [a.name for a in node.names]
                    if isinstance(node, ast.Import)
                    else [node.module or ""]
                )
                for mod in mods:
                    if mod.split(".")[0] in _BANNED_IMPORTS:
                        yield Violation(
                            rule=self.id,
                            path=ctx.rel,
                            line=node.lineno,
                            col=node.col_offset,
                            message=(
                                f"`{mod}` imported in a process-boundary"
                                " module (GL020 process-boundary,"
                                " docs/control-plane.md §5) — objects"
                                " cross the worker boundary only through"
                                " the wire codec (api/wire.py +"
                                " durability envelopes)"
                            ),
                        )
            elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                base = dotted(node.func.value)
                root = base.split(".")[0] if base else ""
                attr = node.func.attr
                if attr in _PICKLING_CHANNEL_CTORS and root in mp_names:
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"`{base}.{attr}(...)` is a transparently-"
                            "pickling channel (GL020 process-boundary) —"
                            " worker traffic goes over Pipe connections"
                            " as wire-codec bytes"
                            " (send_bytes/recv_bytes)"
                        ),
                    )
                elif attr in _PICKLING_CONN_METHODS and (
                    "conn" in (base.split(".")[-1] if base else "").lower()
                ):
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"`{base}.{attr}(...)` pickles its argument"
                            " onto the process channel (GL020"
                            " process-boundary) — encode explicitly and"
                            f" use {attr}_bytes"
                        ),
                    )
