"""GL017 timeseries-state (docs/observability.md "SLO observatory").

The SLO observatory's honesty claims are invariants over private state,
exactly like the glass-box layer's (GL015):

- the windowed reducers are pinned bit-equal to a NumPy oracle — but
  only while ring cells are written through ``TIMESERIES.gauge()`` /
  ``.observe()`` and the sampling round; a foreign writer poking
  ``_series``/``_stamps``/``_values``/``_buckets`` can fabricate history
  the oracle never saw;
- an objective's attainment/budget/burn arithmetic and its edge-triggered
  breach state live in ``SLO._state`` — out-of-band writes could silence
  a breach (or fabricate a recovery) without any ``SloBreach`` event or
  flight bundle ever firing.

Flagged outside ``observability/timeseries.py`` + ``observability/
slo.py``: any WRITE (assignment, augmented assignment, delete, or
mutating call) to observatory private state reached through an
observatory-named binding (``TIMESERIES``, ``SLO``, anything
timeseries/sloengine-named), plus direct ``enabled`` writes (arming goes
through ``enable()``/``disable()``).

Second tooth: **Slo*-family event reasons must be registered.** The SLO
engine's alert surface is only auditable if every ``Slo``-prefixed
reason literal anywhere in the tree is a member of
``observability/events.py``'s ``REGISTERED_REASONS`` — GL006 catches
unregistered reasons at ``record()`` call sites; this closes the gap for
reason strings built or compared AWAY from the call site (breach
classifiers, dashboards, the flight-recorder trigger tag).
"""

from __future__ import annotations

import ast
from typing import Iterable, Set

from grove_tpu.analysis.engine import FileContext, Rule, Violation, dotted

# private ring/window/objective state across timeseries.py / slo.py
_OBS_PRIVATE = {
    "_series",
    "_collectors",
    "_tracked",
    "_stamps",
    "_values",
    "_counts",
    "_totals",
    "_maxes",
    "_buckets",
    "_state",
    "_now",
}
_OBS_FLAGS = {"enabled"}

# binding names that identify the observatory singletons/instances
_OBS_NAMES = ("timeseries", "sloengine", "slo_engine")

_MUTATORS = {"append", "add", "clear", "pop", "popitem", "update",
             "setdefault", "extend", "remove", "discard"}


def _obs_chain(base: str) -> bool:
    """The access chain runs through an observatory-named binding
    (``TIMESERIES._series``, ``self.slo._state``, ``eng.timeseries._now``).
    ``slo`` must match as a whole segment — substring matching would drag
    in every ``slot``-named local."""
    if not base:
        return False
    for seg in base.split("."):
        low = seg.lower()
        if low == "slo" or any(n in low for n in _OBS_NAMES):
            return True
    return False


class TimeSeriesStateRule(Rule):
    id = "GL017"
    name = "timeseries-state"
    description = (
        "SLO-observatory ring/window/objective state is private to"
        " observability/timeseries.py + slo.py — feed through gauge()/"
        "observe()/sample(), judge through SloEngine.add()/evaluate(),"
        " arm through enable()/disable(); Slo*-family event reasons must"
        " be registered in observability/events.py"
    )
    paths = ("grove_tpu/",)
    exclude = (
        "grove_tpu/observability/timeseries.py",
        "grove_tpu/observability/slo.py",
    )

    @staticmethod
    def _registered_reasons() -> Set[str]:
        """Registered reason values, lazily imported (the GL006 pattern —
        observability/events.py is jax-free and cheap)."""
        from grove_tpu.observability import events

        return {
            v
            for k, v in vars(events).items()
            if k.startswith("REASON_") and isinstance(v, str)
        }

    @staticmethod
    def _is_slo_reason_literal(node) -> bool:
        return (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and node.value.startswith("Slo")
            and node.value[3:4].isupper()
            and node.value.isalnum()
        )

    def _reason_literals(self, node):
        """Slo*-shaped literals in REASON POSITIONS: arguments of
        record()/trigger()-named calls, and operands compared against a
        ``reason``-named binding (``ev.reason == "SloBreach"``). Class
        names, wire kinds, and prose stay out of scope."""
        if isinstance(node, ast.Call) and isinstance(
            node.func, (ast.Attribute, ast.Name)
        ):
            fname = (
                node.func.attr
                if isinstance(node.func, ast.Attribute)
                else node.func.id
            ).lower()
            if "record" in fname or "trigger" in fname:
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    if self._is_slo_reason_literal(arg):
                        yield arg
        elif isinstance(node, ast.Compare):
            operands = [node.left] + list(node.comparators)
            if any(
                isinstance(op, (ast.Attribute, ast.Name))
                and dotted(op).split(".")[-1].lower() == "reason"
                for op in operands
            ):
                for op in operands:
                    if self._is_slo_reason_literal(op):
                        yield op

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        registered = self._registered_reasons()
        for node in ast.walk(ctx.tree):
            # tooth 2: Slo*-family reason literals must be registered
            for lit in self._reason_literals(node):
                if lit.value in registered:
                    continue
                yield Violation(
                    rule=self.id,
                    path=ctx.rel,
                    line=lit.lineno,
                    col=lit.col_offset,
                    message=(
                        f"Slo-family reason literal {lit.value!r} is not"
                        " registered in observability/events.py"
                        " (REASON_* / REGISTERED_REASONS) — the SLO alert"
                        " surface must stay auditable end to end (GL017)"
                    ),
                )
            for name, base, lineno, col in self._written_attrs(node):
                if not _obs_chain(base):
                    continue
                if name in _OBS_PRIVATE:
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=lineno,
                        col=col,
                        message=(
                            f"observatory private state `{base}.{name}`"
                            " mutated outside observability/"
                            "{timeseries,slo}.py — the NumPy-oracle"
                            " reducer pin and the breach state machine"
                            " assume only the owning modules write it;"
                            " use gauge()/observe()/sample()/add()/"
                            "evaluate() (GL017)"
                        ),
                    )
                elif name in _OBS_FLAGS:
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=lineno,
                        col=col,
                        message=(
                            f"`{base}.{name}` assigned directly — arm the"
                            " SLO observatory via enable()/disable() so"
                            " clock/capacity wiring stays consistent"
                            " (GL017)"
                        ),
                    )

    @staticmethod
    def _written_attrs(node):
        """Every (attr, base, line, col) that `node` WRITES — the GL015
        extraction: assignment / augmented assignment / delete targets
        (tuple unpacking and subscripts included), or a mutating method
        call on the attribute (``TIMESERIES._series.clear()``)."""
        targets = ()
        if isinstance(node, (ast.Assign, ast.Delete)):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = (node.target,)
        for t in targets:
            elts = (
                t.elts if isinstance(t, (ast.Tuple, ast.List)) else (t,)
            )
            for elt in elts:
                inner = elt
                while isinstance(inner, ast.Subscript):
                    inner = inner.value
                if isinstance(inner, ast.Attribute):
                    yield (
                        inner.attr, dotted(inner.value), inner.lineno,
                        inner.col_offset,
                    )
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATORS
            and isinstance(node.func.value, ast.Attribute)
        ):
            owner = node.func.value
            yield (
                owner.attr, dotted(owner.value), owner.lineno,
                owner.col_offset,
            )
