"""grovelint rule engine: per-file AST visitor dispatch, path-scoped rule
applicability, inline suppression pragmas, JSON + human output.

Design (mirroring `go vet`'s shape, the correctness tool the reference
operator leans on):

- A **Rule** declares path scope (`paths`/`exclude` prefixes relative to
  the repo root) and a `check(FileContext)` generator yielding Violations.
  Rules that need whole-repo state (lock-order cycles) accumulate in
  `check` and emit from `finalize()`.
- The **pragma contract**: ``# grovelint: disable=RULE -- reason`` on (or
  immediately above) the offending line suppresses that rule there. The
  justification is MANDATORY — a pragma without ``-- reason`` is itself a
  violation (``GL000``), so the suppression inventory stays reviewable.
- **Exit-code contract** (scripts/lint.py): 0 clean, 1 violations,
  2 internal/usage error.

The engine is stdlib-only (ast/re/json): `make lint` never imports jax.
Individual rules may import grove_tpu modules lazily (the event-reason
registry) — those imports are cheap and jax-free.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

BARE_PRAGMA_RULE = "GL000"

_PRAGMA_RE = re.compile(
    r"#\s*grovelint:\s*disable=([A-Za-z0-9_*,\-]+)\s*(?:--\s*(\S.*))?$"
)


@dataclass
class Violation:
    rule: str
    path: str  # repo-relative posix path
    line: int
    col: int
    message: str
    suppressed: bool = False
    justification: str = ""

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def as_dict(self) -> dict:
        doc = {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }
        if self.suppressed:
            doc["suppressed"] = True
            doc["justification"] = self.justification
        return doc


@dataclass
class Pragma:
    line: int
    rules: frozenset  # rule ids, or {"*"}
    reason: str

    def covers(self, rule: str) -> bool:
        return "*" in self.rules or rule in self.rules


class FileContext:
    """One parsed file handed to every applicable rule (parse once)."""

    def __init__(self, rel: str, source: str) -> None:
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        # line -> Pragma for that line AND the next (a pragma-only line
        # suppresses the statement below it)
        self.pragmas: Dict[int, Pragma] = {}
        for i, text in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(text)
            if m is None:
                continue
            pragma = Pragma(
                line=i,
                rules=frozenset(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                ),
                reason=(m.group(2) or "").strip(),
            )
            self.pragmas[i] = pragma
            # a comment-only pragma line governs the line it annotates
            if text.split("#", 1)[0].strip() == "":
                self.pragmas.setdefault(i + 1, pragma)

    def pragma_for(self, rule: str, line: int) -> Optional[Pragma]:
        p = self.pragmas.get(line)
        if p is not None and p.covers(rule):
            return p
        return None

    # -- shared AST helpers (used by several rules) ----------------------

    def functions(self) -> Iterator[ast.AST]:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node

    def enclosing_class(self, fn: ast.AST) -> Optional[str]:
        return getattr(fn, "_grovelint_class", None)

    def annotate_classes(self) -> None:
        """Stamp each function with its enclosing class name (one pass)."""
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                for child in ast.walk(node):
                    if isinstance(
                        child, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ) and not hasattr(child, "_grovelint_class"):
                        child._grovelint_class = node.name


def call_name(node: ast.Call) -> str:
    """Trailing identifier of a call target: f() -> f, a.b.f() -> f."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def dotted(node: ast.AST) -> str:
    """Best-effort dotted source of an expression (a.b.c)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Call):
        return dotted(node.func)
    return ""


# event-recorder call shapes shared by the GL006 rule and the inventory
# collectors (tests/test_docs_drift.py): attr name -> positional index of
# the reason argument. ONE definition, or the lint rule and the docs-drift
# inventory diverge — the drift class this subsystem exists to prevent.
_EVENT_RECORD_SHAPES = {"record": 2, "record_event": 1}


def event_record_reason(node: ast.Call) -> Optional[ast.AST]:
    """The reason-argument AST node of an event-recorder call
    (``EVENTS.record(ref, type, reason, msg)`` /
    ``ctx.record_event(kind, reason, msg, ...)``), or None when the call
    is not an event-recorder call."""
    if not isinstance(node.func, ast.Attribute):
        return None
    idx = _EVENT_RECORD_SHAPES.get(node.func.attr)
    if idx is None:
        return None
    # only event-recorder receivers (EVENTS.record, recorder.record,
    # ctx.record_event, self.ctx.record_event) — not dict.record etc.
    base = dotted(node.func.value).lower()
    if node.func.attr == "record" and not (
        "events" in base or "recorder" in base
    ):
        return None
    for kw in node.keywords:
        if kw.arg == "reason":
            return kw.value
    if len(node.args) > idx:
        return node.args[idx]
    return None


class Rule:
    """Base rule. Subclasses set id/name/description and path scope."""

    id = "GL???"
    name = "unnamed"
    description = ""
    paths: Tuple[str, ...] = ("grove_tpu/",)
    exclude: Tuple[str, ...] = ()

    def applies(self, rel: str) -> bool:
        if any(rel == e or rel.startswith(e) for e in self.exclude):
            return False
        return any(rel == p or rel.startswith(p) for p in self.paths)

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        raise NotImplementedError

    def finalize(self) -> Iterable[Violation]:
        """Whole-repo emission hook (after every file was checked)."""
        return ()

    def summary(self) -> Optional[dict]:
        """Optional machine-readable extra for the JSON report (e.g. the
        extracted lock partial order)."""
        return None


@dataclass
class LintReport:
    violations: List[Violation] = field(default_factory=list)
    suppressed: List[Violation] = field(default_factory=list)
    files_scanned: int = 0
    parse_errors: List[str] = field(default_factory=list)
    rule_summaries: Dict[str, dict] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.violations and not self.parse_errors

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for v in self.violations:
            out[v.rule] = out.get(v.rule, 0) + 1
        return out

    def as_json(self) -> dict:
        return {
            "ok": self.ok,
            "files_scanned": self.files_scanned,
            "violations": [v.as_dict() for v in self.violations],
            "suppressed": [v.as_dict() for v in self.suppressed],
            "counts": self.counts(),
            "suppression_count": len(self.suppressed),
            "parse_errors": self.parse_errors,
            "rules": self.rule_summaries,
        }

    def render_human(self) -> str:
        lines = [v.render() for v in self.violations]
        lines.extend(f"parse error: {e}" for e in self.parse_errors)
        lines.append(
            f"grovelint: {self.files_scanned} file(s), "
            f"{len(self.violations)} violation(s), "
            f"{len(self.suppressed)} suppression(s)"
        )
        return "\n".join(lines)


def default_rules() -> List[Rule]:
    from grove_tpu.analysis.rules import ALL_RULES

    return [cls() for cls in ALL_RULES]


def _apply_pragmas(
    ctx: FileContext, raw: Iterable[Violation]
) -> Tuple[List[Violation], List[Violation]]:
    live: List[Violation] = []
    suppressed: List[Violation] = []
    for v in raw:
        # GL000 is exempt from suppression: a bare `disable=*` pragma must
        # not be able to suppress the violation flagging its own bareness
        pragma = (
            ctx.pragma_for(v.rule, v.line)
            if v.rule != BARE_PRAGMA_RULE
            else None
        )
        if pragma is not None:
            v.suppressed = True
            v.justification = pragma.reason
            suppressed.append(v)
        else:
            live.append(v)
    return live, suppressed


def _bare_pragma_violations(ctx: FileContext) -> List[Violation]:
    out = []
    seen = set()
    for pragma in ctx.pragmas.values():
        if pragma.line in seen:
            continue
        seen.add(pragma.line)
        if not pragma.reason:
            out.append(
                Violation(
                    rule=BARE_PRAGMA_RULE,
                    path=ctx.rel,
                    line=pragma.line,
                    col=0,
                    message=(
                        "bare suppression: every `# grovelint: disable=...`"
                        " pragma must carry `-- <justification>`"
                    ),
                )
            )
    return out


def lint_source(
    source: str, rel: str, rules: Optional[List[Rule]] = None
) -> LintReport:
    """Lint one in-memory source blob as if it lived at repo path `rel`
    (fixture snippets in tests; single-file checks)."""
    rules = default_rules() if rules is None else rules
    report = LintReport(files_scanned=1)
    try:
        ctx = FileContext(rel, source)
    except SyntaxError as e:
        report.parse_errors.append(f"{rel}: {e}")
        return report
    raw: List[Violation] = list(_bare_pragma_violations(ctx))
    for rule in rules:
        if rule.applies(rel):
            raw.extend(rule.check(ctx))
    for rule in rules:
        raw.extend(rule.finalize())
        extra = rule.summary()
        if extra is not None:
            report.rule_summaries[rule.id] = extra
    live, suppressed = _apply_pragmas(ctx, raw)
    report.violations.extend(live)
    report.suppressed.extend(suppressed)
    _sort(report)
    return report


def lint_paths(
    root: Path,
    rel_paths: Iterable[str],
    rules: Optional[List[Rule]] = None,
) -> LintReport:
    rules = default_rules() if rules is None else rules
    report = LintReport()
    contexts: Dict[str, FileContext] = {}
    for rel in sorted(rel_paths):
        path = root / rel
        try:
            source = path.read_text()
        except OSError as e:
            report.parse_errors.append(f"{rel}: {e}")
            continue
        try:
            ctx = FileContext(rel, source)
        except SyntaxError as e:
            report.parse_errors.append(f"{rel}: {e}")
            continue
        contexts[rel] = ctx
        report.files_scanned += 1
        raw: List[Violation] = list(_bare_pragma_violations(ctx))
        for rule in rules:
            if rule.applies(rel):
                raw.extend(rule.check(ctx))
        live, suppressed = _apply_pragmas(ctx, raw)
        report.violations.extend(live)
        report.suppressed.extend(suppressed)
    for rule in rules:
        for v in rule.finalize():
            ctx = contexts.get(v.path)
            pragma = ctx.pragma_for(v.rule, v.line) if ctx else None
            if pragma is not None:
                v.suppressed = True
                v.justification = pragma.reason
                report.suppressed.append(v)
            else:
                report.violations.append(v)
        extra = rule.summary()
        if extra is not None:
            report.rule_summaries[rule.id] = extra
    _sort(report)
    return report


def _sort(report: LintReport) -> None:
    report.violations.sort(key=lambda v: (v.path, v.line, v.rule))
    report.suppressed.sort(key=lambda v: (v.path, v.line, v.rule))


def repo_python_files(root: Path) -> List[str]:
    """The lint universe: every .py under grove_tpu/ (generated protos
    excluded — machine output is not held to hand-written invariants)."""
    out = []
    for path in sorted((root / "grove_tpu").rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        if "__pycache__" in rel or "/protos/" in rel:
            continue
        out.append(rel)
    return out


def run_repo_lint(
    root: Optional[Path] = None, rules: Optional[List[Rule]] = None
) -> LintReport:
    """Lint the whole repo (the `make lint` / bench `"lint"` block core)."""
    if root is None:
        root = Path(__file__).resolve().parents[2]
    return lint_paths(root, repo_python_files(root), rules)


def main_json(report: LintReport) -> str:
    return json.dumps(report.as_json(), indent=2, sort_keys=True)
