"""AST inventory collectors: what the codebase actually emits.

Feeds tests/test_docs_drift.py (emitted event reasons ⊆ the
observability/events.py registry ⊆ the docs/observability.md catalog;
metric names in code ⇄ the docs table) and is reusable anywhere the
"what does the code emit" question comes up. Pure-AST — no imports of
the scanned modules, so collection can't be skewed by runtime state.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterable, Set, Tuple

from grove_tpu.analysis.engine import (
    dotted,
    event_record_reason,
    repo_python_files,
)

_METRIC_METHODS = {"inc", "set", "observe"}


def _metric_base(text: str) -> str:
    """Base metric name: everything before the `/label` and `@shard`
    suffixes (observability/metrics.py grammar)."""
    return text.split("/", 1)[0].split("@", 1)[0]


def _literal_prefix(node: ast.AST) -> str:
    """Literal text of a metric-name argument: a plain string, or the
    leading constant of an f-string (names label with `/{...}` and/or
    `@{...}` suffixes — the base name is everything before either)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return _metric_base(node.value)
    if isinstance(node, ast.JoinedStr) and node.values:
        head = node.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return _metric_base(head.value).rstrip("/@")
    return ""


def emitted_event_reasons(
    root: Path,
) -> Dict[str, Set[Tuple[str, int]]]:
    """reason -> {(path, line)} for every record()/record_event() call
    site with a resolvable reason (literal or REASON_ constant)."""
    out: Dict[str, Set[Tuple[str, int]]] = {}
    # resolve REASON_* constant values without importing
    events_src = (root / "grove_tpu/observability/events.py").read_text()
    constants: Dict[str, str] = {}
    for node in ast.walk(ast.parse(events_src)):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id.startswith("REASON_")
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            constants[node.targets[0].id] = node.value.value
    for rel in repo_python_files(root):
        tree = ast.parse((root / rel).read_text())
        for node in ast.walk(tree):
            # a REASON_* constant referenced anywhere outside events.py
            # counts as emittable: several sites thread reasons through an
            # `event_reason` parameter into one shared record() call
            if rel != "grove_tpu/observability/events.py":
                name = (
                    node.id
                    if isinstance(node, ast.Name)
                    else node.attr
                    if isinstance(node, ast.Attribute)
                    else None
                )
                if name in constants:
                    out.setdefault(constants[name], set()).add(
                        (rel, node.lineno)
                    )
            if not isinstance(node, ast.Call):
                continue
            reason_node = event_record_reason(node)
            if reason_node is None:
                continue
            value = None
            if isinstance(reason_node, ast.Constant) and isinstance(
                reason_node.value, str
            ):
                value = reason_node.value
            else:
                name = (
                    reason_node.id
                    if isinstance(reason_node, ast.Name)
                    else reason_node.attr
                    if isinstance(reason_node, ast.Attribute)
                    else None
                )
                if name in constants:
                    value = constants[name]
            if value is not None:
                out.setdefault(value, set()).add((rel, node.lineno))
    return out


def emitted_metric_names(root: Path) -> Dict[str, Set[Tuple[str, int]]]:
    """metric base name -> {(path, line)} for every METRICS.inc/set/
    observe call with a literal (or f-string-prefixed) name."""
    out: Dict[str, Set[Tuple[str, int]]] = {}
    for rel in repo_python_files(root):
        tree = ast.parse((root / rel).read_text())
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_METHODS
                and dotted(node.func.value).split(".")[-1].upper()
                == "METRICS"
                and node.args
            ):
                continue
            name = _literal_prefix(node.args[0])
            if name:
                out.setdefault(name, set()).add((rel, node.lineno))
    return out


def emitted_profile_phases(root: Path) -> Dict[str, Set[Tuple[str, int]]]:
    """phase name -> {(path, line)} for every ``PROFILER.phase("...")``
    call with a literal name, plus the implicit ``reconcile`` phase for
    ``PROFILER.reconcile(...)`` call sites. Feeds the docs-drift gate: an
    instrumented phase cannot ship outside the profile.py registry or the
    docs/observability.md "Wall-attribution profiler" table."""
    out: Dict[str, Set[Tuple[str, int]]] = {}
    for rel in repo_python_files(root):
        tree = ast.parse((root / rel).read_text())
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
            ):
                continue
            base = dotted(node.func.value).split(".")[-1].lower()
            if "profiler" not in base:
                continue
            if node.func.attr == "reconcile":
                out.setdefault("reconcile", set()).add((rel, node.lineno))
            elif node.func.attr == "phase" and node.args:
                arg = node.args[0]
                # literal, or a conditional between literals (the store's
                # status-write vs store-commit split)
                candidates = (
                    (arg.body, arg.orelse)
                    if isinstance(arg, ast.IfExp)
                    else (arg,)
                )
                for cand in candidates:
                    if isinstance(cand, ast.Constant) and isinstance(
                        cand.value, str
                    ):
                        out.setdefault(cand.value, set()).add(
                            (rel, node.lineno)
                        )
    return out


def all_string_literals(root: Path, rels: Iterable[str]) -> Set[str]:
    """Every string constant in the given files (docs→code direction of
    the metric drift check: a documented name must exist in code)."""
    out: Set[str] = set()
    for rel in rels:
        for node in ast.walk(ast.parse((root / rel).read_text())):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                out.add(node.value)
            elif isinstance(node, ast.JoinedStr):
                for part in node.values:
                    if isinstance(part, ast.Constant) and isinstance(
                        part.value, str
                    ):
                        out.add(part.value)
    return out
