"""grove-tpu CLI: apply manifests to the simulated control plane, inspect the
resource tree, validate manifests, and run the benchmark.

    python -m grove_tpu.cli apply samples/simple1.yaml
    python -m grove_tpu.cli validate samples/*.yaml
    python -m grove_tpu.cli tree samples/simple1.yaml --scale workers=3
    python -m grove_tpu.cli bench --small
"""

from __future__ import annotations

import argparse
import sys
from typing import List


def _cmd_validate(args) -> int:
    """Admission-check manifests of any webhook-validated kind: the same
    defaulting+validation the operator's webhooks run, offline
    (PodCliqueSet and ClusterTopology — mirroring the reference's two
    validating-webhook targets)."""
    from grove_tpu.admission.defaulting import (
        default_podcliqueset,
        default_queue,
    )
    from grove_tpu.admission.validation import (
        validate_cluster_topology,
        validate_podcliqueset,
        validate_queue,
    )
    from grove_tpu.api.load import load_manifest_objects
    from grove_tpu.api.topology import ClusterTopology
    from grove_tpu.api.types import PodCliqueSet, Queue

    failed = 0
    for path in args.manifests:
        with open(path) as f:
            try:
                objs = load_manifest_objects(f.read())
                for obj in objs:
                    if not isinstance(
                        obj, (PodCliqueSet, ClusterTopology, Queue)
                    ):
                        raise ValueError(
                            f"kind {obj.kind!r} has no admission validator"
                        )
            except Exception as exc:
                print(f"{path}: LOAD ERROR: {exc}")
                failed += 1
                continue
        for obj in objs:
            if isinstance(obj, ClusterTopology):
                res = validate_cluster_topology(obj)
            elif isinstance(obj, Queue):
                default_queue(obj)
                res = validate_queue(obj)
            else:
                default_podcliqueset(obj)
                res = validate_podcliqueset(obj, ClusterTopology())
            if res.ok:
                print(f"{path}: {obj.metadata.name}: OK")
                for w in res.warnings:
                    print(f"  warning: {w}")
            else:
                failed += 1
                print(f"{path}: {obj.metadata.name}: INVALID")
                for e in res.errors:
                    print(f"  {e}")
    return 1 if failed else 0


def _cmd_apply(args) -> int:
    if args.apiserver:
        return _wire_apply(args)
    _ensure_backend()
    from grove_tpu.sim.harness import SimHarness

    harness = SimHarness(num_nodes=args.nodes)
    for path in args.manifests:
        with open(path) as f:
            applied = harness.apply_yaml(f.read())
        print(f"applied {', '.join(p.metadata.name for p in applied)}")
    ticks = harness.converge()
    print(f"converged in {ticks} virtual ticks (t={harness.clock.now():.0f}s)\n")
    print(harness.tree(), end="")
    return 0


def _wire_client(url: str, watch_kinds=()):
    from grove_tpu.cluster.client import HttpStore

    if "://" not in url:
        url = f"http://{url}"  # kubectl-style bare host:port
    return HttpStore(url, watch_kinds=watch_kinds)


def _check_kind(kind: str, verb: str) -> bool:
    from grove_tpu.api.wire import KIND_REGISTRY

    if kind in KIND_REGISTRY:
        return True
    print(
        f"{verb}: unknown kind {kind!r} (known:"
        f" {', '.join(sorted(KIND_REGISTRY))})",
        file=sys.stderr,
    )
    return False


def _sim_from_manifests(args):
    """Converged sim harness from the command's manifest args (shared sim
    bootstrap of tree/get/describe)."""
    _ensure_backend()
    from grove_tpu.sim.harness import SimHarness

    harness = SimHarness(num_nodes=args.nodes)
    for path in args.manifests:
        with open(path) as f:
            harness.apply_yaml(f.read())
    harness.converge()
    return harness


def _wire_apply(args) -> int:
    """kubectl-style create-or-update against a LIVE apiserver: POST each
    manifest document; on 409 re-read the live object, carry its
    resourceVersion + finalizers, and PUT the new spec (the server's
    mutating/validating webhooks run on both paths)."""
    import yaml

    from grove_tpu.api.wire import decode_object
    from grove_tpu.runtime.errors import ERR_CONFLICT, GroveError

    store = _wire_client(args.apiserver)
    failed = 0
    for path in args.manifests:
        try:
            with open(path) as f:
                docs = [d for d in yaml.safe_load_all(f.read()) if d]
        except (OSError, yaml.YAMLError) as exc:
            print(f"{path}: LOAD ERROR: {exc}", file=sys.stderr)
            failed += 1
            continue
        for doc in docs:
            # kubectl -n semantics: the flag names the namespace for
            # manifests that don't carry one (decode_object would otherwise
            # default it before the CLI could tell the difference); tolerate
            # an explicit `metadata:` null the way decode_object does
            if isinstance(doc, dict):
                meta = doc.get("metadata") or {}
                meta.setdefault("namespace", args.namespace)
                doc["metadata"] = meta
            try:
                obj = decode_object(doc)
            except Exception as exc:
                print(f"{path}: DECODE ERROR: {exc}", file=sys.stderr)
                failed += 1
                continue
            try:
                created = store.create(obj)
                print(f"{obj.kind.lower()}/{created.metadata.name} created")
            except GroveError as e:
                if e.code != ERR_CONFLICT:
                    print(
                        f"{path}: {obj.metadata.name}: {e.message}",
                        file=sys.stderr,
                    )
                    failed += 1
                    continue

                # create-or-update: graft the manifest's desired state onto
                # whatever is live NOW, re-applied per conflict retry so a
                # racing writer is never clobbered
                def configure(live, manifest=obj):
                    live.spec = manifest.spec
                    live.metadata.labels = manifest.metadata.labels
                    live.metadata.annotations = manifest.metadata.annotations

                try:
                    updated = store.read_modify_write(
                        obj.kind,
                        obj.metadata.namespace,
                        obj.metadata.name,
                        configure,
                    )
                except GroveError as e2:
                    print(
                        f"{path}: {obj.metadata.name}: {e2.message}",
                        file=sys.stderr,
                    )
                    failed += 1
                    continue
                if updated is None:
                    print(
                        f"{path}: {obj.metadata.name}: conflict but object"
                        " not found",
                        file=sys.stderr,
                    )
                    failed += 1
                else:
                    print(
                        f"{obj.kind.lower()}/{obj.metadata.name} configured"
                    )
    return 1 if failed else 0


def _cmd_delete(args) -> int:
    """kubectl-style delete against a live apiserver (finalizers drain
    server-side; the controllers' delete flows run as in-cluster)."""
    from grove_tpu.runtime.errors import GroveError

    store = _wire_client(args.apiserver)
    failed = 0
    for name in args.names:
        try:
            store.delete(args.kind, args.namespace, name)
            print(f"{args.kind.lower()}/{name} deleted")
        except GroveError as e:
            print(f"delete {name}: {e.message}", file=sys.stderr)
            failed += 1
    return 1 if failed else 0


def _cmd_scale(args) -> int:
    """kubectl-style scale for PodCliqueSet / PodCliqueScalingGroup /
    PodClique replicas via read-modify-write on the live apiserver (the
    validation webhook enforces minAvailable and immutability rules; the
    mutation is re-applied per conflict retry so racing writers are never
    clobbered)."""
    from grove_tpu.runtime.errors import GroveError

    store = _wire_client(args.apiserver)
    seen = {}

    def set_replicas(live):
        spec = getattr(live, "spec", None)
        if spec is None or not hasattr(spec, "replicas"):
            raise _NotScalable(args.kind)
        seen["old"] = spec.replicas
        spec.replicas = args.replicas

    try:
        updated = store.read_modify_write(
            args.kind, args.namespace, args.name, set_replicas
        )
    except _NotScalable:
        print(f"scale: kind {args.kind} is not scalable", file=sys.stderr)
        return 1
    except GroveError as e:
        print(f"scale {args.name}: {e.message}", file=sys.stderr)
        return 1
    if updated is None:
        print(
            f"scale: {args.kind.lower()}/{args.name} not found",
            file=sys.stderr,
        )
        return 1
    print(
        f"{args.kind.lower()}/{args.name} scaled: replicas {seen['old']} ->"
        f" {args.replicas}"
    )
    return 0


class _NotScalable(Exception):
    pass


def _cmd_tree(args) -> int:
    if args.apiserver:
        # live-cluster tree: pure reads, no sim, no jax
        from grove_tpu.api.inspect import render_tree
        from grove_tpu.runtime.errors import GroveError

        if args.manifests or args.scale:
            print(
                "tree: --apiserver renders live objects; manifests/--scale"
                " do not apply (use apply/scale verbs instead)",
                file=sys.stderr,
            )
            return 2
        try:
            print(
                render_tree(_wire_client(args.apiserver), args.namespace),
                end="",
            )
        except GroveError as e:
            print(f"tree: {args.apiserver}: {e.message}", file=sys.stderr)
            return 1
        return 0
    if not args.manifests:
        print(
            "tree: provide manifests to simulate, or --apiserver URL to"
            " render a live cluster",
            file=sys.stderr,
        )
        return 2
    harness = _sim_from_manifests(args)
    for spec in args.scale or []:
        name, sep, replicas_str = spec.partition("=")
        if not sep or not replicas_str.isdigit():
            print(
                f"--scale expects GROUP=REPLICAS (a non-negative integer),"
                f" got {spec!r}",
                file=sys.stderr,
            )
            return 2
        replicas = int(replicas_str)
        matched = [
            g
            for g in harness.store.list("PodCliqueScalingGroup")
            if g.metadata.name.endswith(f"-{name}") or g.metadata.name == name
        ]
        if not matched:
            print(f"no scaling group matches {name!r}", file=sys.stderr)
            return 1
        for pcsg in matched:
            if replicas < pcsg.spec.min_available:
                print(
                    f"{pcsg.metadata.name}: replicas {replicas} below"
                    f" minAvailable {pcsg.spec.min_available}",
                    file=sys.stderr,
                )
                return 1
            pcsg.spec.replicas = replicas
            harness.store.update(pcsg)
    harness.converge()
    print(harness.tree(args.namespace), end="")
    return 0


def _cmd_get(args) -> int:
    import yaml

    from grove_tpu.api.serialize import export_object

    if args.apiserver and args.manifests:
        print(
            "get: --apiserver reads live objects; manifests are not applied"
            " (POST them to the apiserver instead)",
            file=sys.stderr,
        )
        return 2
    if not args.apiserver and not args.manifests:
        print(
            "get: provide manifests to simulate, or --apiserver URL to read"
            " a live cluster",
            file=sys.stderr,
        )
        return 2
    if args.watch and not args.apiserver:
        print("get: --watch requires --apiserver", file=sys.stderr)
        return 2

    if not _check_kind(args.kind, "get"):
        return 2

    if args.apiserver:
        # kubectl-style read against a LIVE apiserver (no sim, no jax)
        from grove_tpu.runtime.errors import GroveError

        if args.watch:
            return _watch_kind(args)
        try:
            objs = _wire_client(args.apiserver).list(args.kind, args.namespace)
        except GroveError as e:
            print(f"get: {args.apiserver}: {e.message}", file=sys.stderr)
            return 1
    else:
        harness = _sim_from_manifests(args)
        objs = harness.store.list(args.kind, args.namespace)

    if not objs:
        print(f"no {args.kind} objects", file=sys.stderr)
        return 1
    print(
        yaml.safe_dump_all(
            [export_object(o) for o in objs], sort_keys=False
        ),
        end="",
    )
    return 0


def _watch_kind(args) -> int:
    """kubectl get --watch: stream Added/Modified/Deleted events for one
    kind from the live apiserver until interrupted."""
    import threading

    from grove_tpu.runtime.errors import GroveError

    store = _wire_client(args.apiserver, watch_kinds=(args.kind,))
    try:
        # preflight: the watch loop retries connection errors silently by
        # design (informer semantics) — an unreachable/wrong server must
        # fail the command up front like the non-watch path does
        store.list(args.kind, args.namespace)
    except GroveError as e:
        print(f"get: {args.apiserver}: {e.message}", file=sys.stderr)
        return 1

    done = threading.Event()

    def on_event(ev):
        obj = ev.obj
        if args.namespace and obj.metadata.namespace != args.namespace:
            return
        status = getattr(obj, "status", None)
        phase = getattr(status, "phase", "") or ""
        try:
            print(
                f"{ev.type:<9} {obj.kind.lower()}/{obj.metadata.name}"
                f" rv={obj.metadata.resource_version}"
                + (f" phase={phase}" if phase else ""),
                flush=True,
            )
        except (BrokenPipeError, OSError):
            # stdout is gone (e.g. `... --watch | head`): end the watch
            # instead of letting the client's reconnect loop re-list the
            # snapshot against the apiserver forever
            done.set()

    store.subscribe(on_event)
    store.start()
    try:
        print(
            f"watching {args.kind} on {store.base_url} (Ctrl-C to stop)",
            flush=True,
        )
    except (BrokenPipeError, OSError):
        done.set()
    try:
        while not done.is_set():
            # short slices keep Ctrl-C responsive on every platform (a long
            # main-thread Event.wait is not SIGINT-interruptible on Windows)
            done.wait(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        store.stop()
    return 0


def _cmd_describe(args) -> int:
    """kubectl-describe-style view: metadata, status counters, conditions,
    typed lastErrors, and the object's Events — live (--apiserver) or after
    simulating manifests."""
    from grove_tpu.api.inspect import render_describe

    if not _check_kind(args.kind, "describe"):
        return 2
    if args.apiserver:
        if args.manifests:
            print(
                "describe: --apiserver reads live objects; manifests are"
                " not applied (use the apply verb instead)",
                file=sys.stderr,
            )
            return 2
        from grove_tpu.runtime.errors import GroveError

        try:
            out = render_describe(
                _wire_client(args.apiserver),
                args.kind,
                args.namespace,
                args.name,
            )
        except GroveError as e:
            print(f"describe: {args.apiserver}: {e.message}", file=sys.stderr)
            return 1
    else:
        if not args.manifests:
            print(
                "describe: provide manifests to simulate, or --apiserver URL",
                file=sys.stderr,
            )
            return 2
        harness = _sim_from_manifests(args)
        out = render_describe(
            harness.store, args.kind, args.namespace, args.name
        )
    if not out:
        print(
            f"describe: {args.kind.lower()}/{args.name} not found",
            file=sys.stderr,
        )
        return 1
    print(out, end="")
    return 0


def _print_trace_summary(summary: dict, top: int) -> None:
    """Render a /debug/traces-shaped summary as an aligned table, widest
    total first."""
    spans = summary.get("spans", {})
    if not spans:
        print("no spans recorded (tracing enabled?)")
        return
    rows = sorted(spans.items(), key=lambda kv: -kv[1]["total_s"])[:top]
    name_w = max(len(n) for n, _ in rows)
    print(
        f"{'span':<{name_w}}  {'count':>7}  {'total_s':>9}  {'p50_s':>9}"
        f"  {'p99_s':>9}  {'max_s':>9}"
    )
    for name, agg in rows:
        print(
            f"{name:<{name_w}}  {agg['count']:>7}  {agg['total_s']:>9.4f}"
            f"  {agg['p50_s']:>9.6f}  {agg['p99_s']:>9.6f}"
            f"  {agg['max_s']:>9.6f}"
        )
    dropped = summary.get("dropped", 0)
    if dropped:
        print(f"({dropped} oldest spans dropped by the bounded buffer)")


def _cmd_trace(args) -> int:
    """Span-level latency view: pretty-print the top-N slowest span names —
    from a live apiserver's /debug/traces (--apiserver), or by running the
    manifests through a traced sim. --chrome writes the Chrome trace_event
    JSON for chrome://tracing / Perfetto."""
    import json as _json

    if args.apiserver:
        import urllib.request

        url = args.apiserver
        if "://" not in url:
            url = f"http://{url}"
        try:
            with urllib.request.urlopen(f"{url}/debug/traces", timeout=10) as r:
                summary = _json.loads(r.read())
            if args.chrome:
                with urllib.request.urlopen(
                    f"{url}/debug/traces/chrome", timeout=30
                ) as r:
                    with open(args.chrome, "wb") as f:
                        f.write(r.read())
                print(f"chrome trace written to {args.chrome}")
        except (OSError, ValueError) as e:
            # ValueError covers json.JSONDecodeError: a 200 from something
            # that is not this apiserver (proxy page, wrong port) must fail
            # with the friendly message, not a traceback
            print(f"trace: {url}: {e}", file=sys.stderr)
            return 1
        if not summary.get("enabled", False):
            print(
                "note: tracing is disabled on the server"
                " (set GROVE_TPU_TRACE=1)",
                file=sys.stderr,
            )
        _print_trace_summary(summary, args.top)
        return 0

    if not args.manifests:
        print(
            "trace: provide manifests to simulate, or --apiserver URL to"
            " read a live operator's traces",
            file=sys.stderr,
        )
        return 2
    from grove_tpu.observability.tracing import TRACER

    TRACER.enable()
    TRACER.reset()
    harness = _sim_from_manifests(args)
    _print_trace_summary(TRACER.summary_json(), args.top)
    print()
    print(f"top {args.top} slowest spans:")
    for sp in TRACER.slowest(args.top):
        attrs = " ".join(
            f"{k}={v}" for k, v in sp.attrs.items() if k != "vt"
        )
        print(f"  {sp.dur_us / 1e6:>9.6f}s  {sp.name}  {attrs}")
    if args.chrome:
        with open(args.chrome, "w") as f:
            _json.dump(TRACER.chrome_trace(), f)
        print(f"\nchrome trace written to {args.chrome}")
    # keep the harness alive through the export (watch threads etc.)
    del harness
    return 0


def _print_profile_report(doc: dict, top: int) -> None:
    """Render the wall-attribution report (docs/observability.md): top
    phase sinks by total self-time, per-controller roll-up, coverage."""
    if not doc.get("enabled", False):
        print(
            "note: the wall-attribution profiler is disabled on the server"
            " (set GROVE_TPU_PROFILE=1)",
            file=sys.stderr,
        )
    print(
        f"attributed {doc.get('attributed_seconds', 0.0):.3f}s over"
        f" {doc.get('covered_wall_seconds', 0.0):.3f}s of covered wall"
        + (
            f" (coverage {doc['coverage']:.1%})"
            if "coverage" in doc
            else ""
        )
    )
    rows = [
        (
            ph["controller"],
            str(ph["shard"]) if ph["shard"] >= 0 else "-",
            ph["phase"],
            str(ph["count"]),
            f"{ph['total_s']:.4f}",
            f"{ph['p50_s'] * 1e6:.0f}",
            f"{ph['p99_s'] * 1e6:.0f}",
        )
        for ph in doc.get("phases", [])[:top]
    ]
    if rows:
        _print_table(
            ("CONTROLLER", "SHARD", "PHASE", "COUNT", "TOTAL-S", "P50-µS",
             "P99-µS"),
            rows,
        )
    by_ctrl = doc.get("by_controller") or {}
    if by_ctrl:
        print()
        print(
            "per controller: "
            + "  ".join(
                f"{c}={s:.3f}s"
                for c, s in sorted(by_ctrl.items(), key=lambda kv: -kv[1])
            )
        )


def _cmd_profile(args) -> int:
    """Wall-attribution view (docs/observability.md): where control-plane
    seconds went, per (controller, shard, phase) — from a live apiserver's
    GET /debug/profile, or by converging manifests under a profiled sim."""
    if args.apiserver:
        doc = _fetch_server_json(args.apiserver, "/debug/profile", "profile")
        if doc is None:
            return 1
        _print_profile_report(doc, args.top)
        return 0

    if not args.manifests:
        print(
            "profile: provide manifests to simulate, or --apiserver URL to"
            " read a live operator's attribution report",
            file=sys.stderr,
        )
        return 2
    from grove_tpu.observability.profile import PROFILER

    PROFILER.enable()
    PROFILER.reset()
    # no coverage claim here: the sim bootstrap (harness build, manifest
    # apply) is outside the attribution window by design — the gated
    # coverage measurement lives in `make profile-smoke` / the bench
    harness = _sim_from_manifests(args)
    _print_profile_report(PROFILER.report(), args.top)
    del harness
    return 0


def _print_slo_report(doc: dict) -> None:
    """Render one SloReport (docs/observability.md "SLO observatory"):
    a table of objectives, then the non-internal series appendix."""
    objectives = doc.get("objectives") or []
    if not objectives:
        print(
            "no SLO objectives defined"
            + ("" if doc.get("enabled") else " (engine disabled)")
        )
    for row in objectives:
        att = row.get("attainment")
        budget = row.get("budget_remaining")
        print(
            f"{row['name']}: {row['state'].upper()}  attainment="
            + (f"{att:.4f}" if att is not None else "-")
            + "  budget_remaining="
            + (f"{budget:.2%}" if budget is not None else "-")
            + f"  burn fast/slow={row['burn_rate_fast']:g}x/"
            f"{row['burn_rate_slow']:g}x  breaches={row['breaches']}"
            f" recoveries={row['recoveries']}"
        )
        print(f"    {row['spec']}")
    series = doc.get("series") or {}
    shown = 0
    for name in sorted(series):
        if name.startswith("slo:"):
            continue  # engine-internal good/bad indicator series
        win = series[name]
        if win.get("kind") == "dist":
            print(
                f"  {name}: n={win.get('count', 0)}"
                + (
                    f" p50={win['p50']:.4f} p99={win['p99']:.4f}"
                    f" max={win['max']:.4f}"
                    if win.get("count")
                    else ""
                )
            )
        elif win.get("kind") == "gauge" and win.get("n"):
            print(
                f"  {name}: n={win['n']} last={win['last']:.4f}"
                f" mean={win['mean']:.4f} min={win['min']:.4f}"
                f" max={win['max']:.4f}"
            )
        shown += 1
        if shown >= 24:
            print("  ...")
            break


def _cmd_slo(args) -> int:
    """SLO observatory report: per-objective attainment, error budget,
    burn rates, breach state — from a live apiserver's GET /debug/slo
    (the engine runs in the operator process)."""
    if not args.apiserver:
        print(
            "slo: --apiserver URL required (the SLO engine lives in the"
            " operator process; arm it with GROVE_TPU_TIMESERIES=1"
            " GROVE_TPU_SLO=1)",
            file=sys.stderr,
        )
        return 2
    doc = _fetch_server_json(
        args.apiserver, f"/debug/slo?window={args.window}", "slo"
    )
    if doc is None:
        return 1
    _print_slo_report(doc)
    return 0


def _print_federation_status(doc: dict) -> None:
    print(
        f"federation: {len(doc.get('clusters', []))} cluster(s),"
        f" spillovers={doc.get('spillovers', 0)}"
        f" reroutes={doc.get('reroutes', 0)}"
        f" decisions={doc.get('decisions', 0)}"
    )
    rows = [("REGION", "STATE", "PHASE", "PLACEMENTS", "PENDING", "NODES")]
    for cl in doc.get("clusters", []):
        rows.append(
            (
                cl.get("region", "?"),
                cl.get("state", "?"),
                f"{cl.get('phaseOffset', 0.0):g}s",
                str(cl.get("placements", 0)),
                str(cl.get("pendingGangs", "-")),
                str(cl.get("nodes", "-")),
            )
        )
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    for row in rows:
        print("  " + "  ".join(c.ljust(w) for c, w in zip(row, widths)))
    usage = doc.get("globalUsage") or {}
    for queue in sorted(usage):
        vec = ", ".join(
            f"{r}={usage[queue][r]:g}" for r in sorted(usage[queue])
        )
        print(f"  queue {queue}: {vec or 'idle'}")


def _cmd_federation(args) -> int:
    """Federation registry + routing ledger roll-up: per-region state,
    placements, spillover/re-route counters, and the global (level-3
    fold) per-queue usage — from a live apiserver's GET /federation."""
    if not args.apiserver:
        print(
            "federation: --apiserver URL required (the router lives in"
            " the operator process; single-cluster deployments serve"
            " 404 here)",
            file=sys.stderr,
        )
        return 2
    doc = _fetch_server_json(args.apiserver, "/federation", "federation")
    if doc is None:
        return 1
    if args.output == "json":
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 0
    _print_federation_status(doc)
    return 0


def _print_forecast_report(doc: dict) -> None:
    state = "enabled" if doc.get("enabled") else "disabled"
    print(
        f"forecaster: {state}, period={doc.get('period_s', 0):g}s"
        f" horizon={doc.get('horizon_s', 0):g}s"
        f" history={doc.get('history_s', 0):g}s"
    )
    forecasts = doc.get("forecasts", [])
    if not forecasts:
        print(
            "  no series (watch some via FORECASTER.watch() or pass"
            " --series)"
        )
        return
    for fc in forecasts:
        line = (
            f"  {fc['series']}: model={fc.get('model', '?')}"
            f" n={fc.get('n', 0)}"
        )
        if "last" in fc:
            line += f" last={fc['last']:.4f} sigma={fc.get('sigma', 0):.4f}"
        if "skill" in fc:
            line += (
                f" mae={fc['mae']:.4f} vs naive={fc['persistence_mae']:.4f}"
                f" skill={fc['skill']:+.4f}"
            )
        print(line)
        peak = fc.get("peak")
        if peak is not None:
            print(
                f"    peak {peak['mean']:.4f} at t={peak['at_s']:.0f}s;"
                f" {len(fc.get('points', []))} point(s), band ±"
                f"{2.0 * fc.get('sigma', 0.0):.4f}"
            )


def _cmd_forecast(args) -> int:
    """Per-series horizon forecasts with confidence bands + skill vs the
    persistence baseline — from a live apiserver's GET /debug/forecast
    (the forecaster reads the operator process's time-series rings)."""
    if not args.apiserver:
        print(
            "forecast: --apiserver URL required (the forecaster lives in"
            " the operator process; arm it with GROVE_TPU_TIMESERIES=1"
            " GROVE_TPU_FORECAST=1)",
            file=sys.stderr,
        )
        return 2
    query = "&".join(f"series={s}" for s in (args.series or []))
    if args.horizon:
        query += ("&" if query else "") + f"horizon={args.horizon}"
    doc = _fetch_server_json(
        args.apiserver,
        "/debug/forecast" + (f"?{query}" if query else ""),
        "forecast",
    )
    if doc is None:
        return 1
    _print_forecast_report(doc)
    return 0


def _print_ledger_report(doc: dict) -> None:
    state = "enabled" if doc.get("enabled") else "disabled"
    flip = doc.get("flip_confirmed_rate")
    delta = doc.get("mean_budget_delta")
    print(
        f"ledger: {state}, {doc.get('recorded_total', 0)} recorded"
        f" ({doc.get('retained', 0)} retained),"
        f" {doc.get('executed', 0)} executed /"
        f" {doc.get('skipped', 0)} skipped"
        + (f", flip-confirmed {flip:.0%}" if flip is not None else "")
        + (
            f", mean budget delta {delta:+.4f}"
            if delta is not None
            else ""
        )
    )
    rows = []
    for e in doc.get("entries", []):
        eff = e.get("effect") or {}
        d = eff.get("budget_delta")
        rows.append(
            (
                str(e["id"]),
                f"{e['vt']:g}",
                e["trigger"]["kind"],
                e["action"]["kind"],
                e["action"].get("target", "") or "-",
                e["outcome"],
                f"{d:+.4f}" if d is not None else (e.get("reason") or "-"),
            )
        )
    if rows:
        _print_table(
            ("ID", "VT", "TRIGGER", "ACTION", "TARGET", "OUTCOME",
             "ΔBUDGET/REASON"),
            rows,
        )


def _cmd_ledger(args) -> int:
    """The causal decision→effect ledger: every remediation the
    controller considered, as trigger→diagnosis→simulation→action→effect
    chains — from a live apiserver's GET /debug/ledger."""
    if not args.apiserver:
        print(
            "ledger: --apiserver URL required (the ledger lives in the"
            " operator process; arm it with GROVE_TPU_LEDGER=1)",
            file=sys.stderr,
        )
        return 2
    doc = _fetch_server_json(args.apiserver, "/debug/ledger", "ledger")
    if doc is None:
        return 1
    _print_ledger_report(doc)
    return 0


def _print_journey(doc: dict) -> None:
    name = f"{doc.get('namespace')}/{doc.get('name')}"
    state = "complete" if doc.get("complete") else "in flight"
    extra = ""
    if "partition" in doc:
        part = doc["partition"]
        extra = f", frontier partition {part}" if part >= 0 else ", residual"
    print(f"PodGang {name}: {state}, {doc.get('rounds', 0)} solve round(s){extra}")
    rows = [
        (
            ph["phase"],
            f"+{ph['t_s']:.6f}s",
            f"vt={ph['vt']:g}" if "vt" in ph else "-",
        )
        for ph in doc.get("phases", [])
    ]
    if rows:
        _print_table(("PHASE", "T", "VIRTUAL"), rows)
    if doc.get("segments"):
        print()
        print(
            "admission decomposition: "
            + "  ".join(
                f"{k}={v:.6f}s" for k, v in doc["segments"].items()
            )
            + f"  (total {doc.get('total_s', 0.0):.6f}s)"
        )


def _cmd_journey(args) -> int:
    """One PodGang's causal admission timeline (docs/observability.md
    "Gang journeys"): created → first-scan → encode → solve → commit →
    scheduled, with the queue-wait/service/solver split — from a live
    apiserver's GET /gangs/{ns}/{name}/journey, or by converging manifests
    under a journey-traced sim."""
    if args.apiserver:
        if not args.gang:
            print(
                "journey: --apiserver mode needs --gang NAME"
                " (and --namespace)",
                file=sys.stderr,
            )
            return 2
        doc = _fetch_server_json(
            args.apiserver,
            f"/gangs/{args.namespace}/{args.gang}/journey",
            "journey",
        )
        if doc is None:
            return 1
        _print_journey(doc)
        return 0

    if not args.manifests:
        print(
            "journey: provide manifests to simulate, or --apiserver URL to"
            " read a live operator's journeys",
            file=sys.stderr,
        )
        return 2
    from grove_tpu.observability.journey import JOURNEYS

    JOURNEYS.enable()
    JOURNEYS.reset()
    harness = _sim_from_manifests(args)
    if args.gang:
        doc = JOURNEYS.journey(args.namespace, args.gang)
        if doc is None:
            print(
                f"journey: no journey recorded for PodGang"
                f" {args.namespace}/{args.gang}",
                file=sys.stderr,
            )
            return 1
        _print_journey(doc)
    else:
        # no gang named: every PodGang the converge admitted, worst last
        gangs = sorted(
            (j.as_dict() for j in JOURNEYS.completed()),
            key=lambda d: d.get("total_s", 0.0),
        )
        for doc in gangs:
            _print_journey(doc)
            print()
        summary = JOURNEYS.decomposition()
        print(
            f"{summary['journeys']} journeys: admission p50"
            f" {summary['admission_p50_s']:.6f}s / p99"
            f" {summary['admission_p99_s']:.6f}s"
        )
    del harness
    return 0


def _print_explain(doc: dict) -> None:
    """Render one GangExplain verdict (docs/observability.md "Admission
    explain"): headline, then the constraint-elimination funnel."""
    head = f"{doc.get('namespace')}/{doc.get('name')}: "
    state = doc.get("state")
    if state == "scheduled":
        print(head + "SCHEDULED (nothing to explain)")
        return
    if doc.get("fits_now"):
        print(head + "FITS NOW — " + doc.get("message", ""))
    else:
        slug = doc.get("detail") or "?"
        print(
            head
            + f"BLOCKED on {doc.get('binding_constraint')} ({slug}): "
            + (doc.get("detail_text") or doc.get("message") or "")
        )
    funnel = doc.get("funnel") or []
    if funnel:
        rows = [
            (
                ("✗ " if not f.get("ok") else "  ") + f["stage"],
                str(f.get("surviving_nodes", "")),
                f.get("detail", ""),
            )
            for f in funnel
        ]
        _print_table(("STAGE", "NODES", "DETAIL"), rows)
    q = doc.get("queue") or {}
    if q.get("ahead"):
        print(
            f"ahead in order ({q.get('ahead_count')}):"
            f" {', '.join(q['ahead'])}"
        )
    if "partition" in doc:
        print(f"frontier partition: {doc['partition']}")


def _print_capacity(doc: dict) -> None:
    print(
        f"{doc.get('nodes')} schedulable of {doc.get('totalNodes')} nodes;"
        f" total free: {_fmt_resource_map(doc.get('totalFree', {}))}"
    )
    if doc.get("superDomainLevel"):
        print(f"super-domain level: {doc['superDomainLevel']}")
    rows = []
    for lvl in doc.get("levels", []):
        rows.append(
            (
                lvl.get("domain", lvl["key"]),
                str(lvl.get("domainCount", 0)),
                _fmt_resource_map(lvl.get("fragmentation", {})),
                _fmt_resource_map(lvl.get("largestDomainFree", {})),
            )
        )
    if rows:
        _print_table(
            ("LEVEL", "DOMAINS", "FRAGMENTATION", "LARGEST-FREE"), rows
        )


def _cmd_explain(args) -> int:
    """Admission explain verdict for one PodGang — from a live
    apiserver's GET /gangs/{ns}/{name}/explain, or after simulating
    manifests (the still-pending gangs are the interesting ones)."""
    if args.apiserver:
        if not args.gang:
            print(
                "explain: --apiserver mode needs --gang NAME"
                " (and --namespace)",
                file=sys.stderr,
            )
            return 2
        doc = _fetch_server_json(
            args.apiserver,
            f"/gangs/{args.namespace}/{args.gang}/explain",
            "explain",
        )
        if doc is None:
            return 1
        _print_explain(doc)
        return 0
    if not args.manifests:
        print(
            "explain: provide manifests to simulate, or --apiserver URL"
            " to query a live operator",
            file=sys.stderr,
        )
        return 2
    harness = _sim_from_manifests(args)
    gangs = (
        [args.gang]
        if args.gang
        else [
            g.metadata.name
            for g in harness.store.list("PodGang", args.namespace)
        ]
    )
    for i, gang in enumerate(gangs):
        doc = harness.explain.explain(args.namespace, gang)
        if doc is None:
            print(
                f"explain: PodGang {args.namespace}/{gang} not found",
                file=sys.stderr,
            )
            return 1
        if i:
            print()
        _print_explain(doc)
    return 0


def _cmd_capacity(args) -> int:
    """Capacity & fragmentation introspection — GET /debug/capacity on a
    live apiserver, or after simulating manifests."""
    if args.apiserver:
        doc = _fetch_server_json(
            args.apiserver, "/debug/capacity", "capacity"
        )
        if doc is None:
            return 1
        _print_capacity(doc)
        return 0
    _ensure_backend()
    from grove_tpu.sim.harness import SimHarness

    harness = SimHarness(num_nodes=args.nodes)
    for path in args.manifests:
        with open(path) as f:
            harness.apply_yaml(f.read())
    if args.manifests:
        harness.converge()
    _print_capacity(harness.explain.capacity())
    return 0


def _whatif_body(args) -> dict:
    actions = []
    for node in args.drain or []:
        actions.append({"action": "drain-node", "node": node})
    for node in args.remove or []:
        actions.append({"action": "remove-node", "node": node})
    if args.add_nodes:
        actions.append(
            {
                "action": "add-nodes",
                "count": args.add_nodes,
                "like": args.like,
            }
        )
    if args.set_queue:
        act = {"action": "set-queue", "queue": args.set_queue}
        for field_name, raw in (
            ("deserved", args.deserved),
            ("ceiling", args.ceiling),
        ):
            if raw:
                try:
                    act[field_name] = {
                        k: float(v)
                        for k, _, v in (
                            part.partition("=") for part in raw.split(",")
                        )
                    }
                except ValueError:
                    raise _BadResourceMap(field_name, raw)
        actions.append(act)
    return {
        "gang": {"namespace": args.namespace, "name": args.gang},
        "actions": actions,
    }


class _BadResourceMap(Exception):
    def __init__(self, field_name: str, raw: str) -> None:
        self.field_name = field_name
        self.raw = raw


def _cmd_whatif(args) -> int:
    """Hypothetical trial solve: would the gang fit if N nodes were
    drained/removed/added or a queue's entitlement changed? POST
    /debug/whatif on a live apiserver, or against a simulated cluster.
    Commits nothing either way."""
    try:
        body = _whatif_body(args)
    except _BadResourceMap as e:
        print(
            f"whatif: --{e.field_name} expects RES=VALUE[,RES=VALUE],"
            f" got {e.raw!r}",
            file=sys.stderr,
        )
        return 2
    if not body["actions"]:
        print(
            "whatif: give at least one action (--drain/--remove/"
            "--add-nodes --like/--set-queue)",
            file=sys.stderr,
        )
        return 2
    if args.apiserver:
        doc = _post_server_json_body(
            args.apiserver, "/debug/whatif", body, "whatif"
        )
        if doc is None:
            return 1
    else:
        if not args.manifests:
            print(
                "whatif: provide manifests to simulate, or --apiserver"
                " URL for a live operator",
                file=sys.stderr,
            )
            return 2
        harness = _sim_from_manifests(args)
        try:
            doc = harness.explain.whatif(body)
        except ValueError as e:
            print(f"whatif: {e}", file=sys.stderr)
            return 1
    before, after = doc.get("before", {}), doc.get("after", {})
    print(
        f"before: fits_now={before.get('fits_now')}"
        f" (binding: {before.get('binding_constraint')})"
    )
    print(
        f"after:  fits_now={after.get('fits_now')}"
        f" (binding: {after.get('binding_constraint')})"
    )
    print(
        "verdict FLIPS under this hypothetical"
        if doc.get("flipped")
        else "verdict unchanged"
    )
    return 0


def _post_server_json_body(apiserver: str, path: str, body: dict, label: str):
    """POST a JSON document to a live apiserver; returns the JSON response
    or None after printing the error."""
    import json as _json
    import urllib.error
    import urllib.request

    url = apiserver if "://" in apiserver else f"http://{apiserver}"
    req = urllib.request.Request(
        f"{url}{path}",
        data=_json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return _json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            doc = _json.loads(e.read())
            msg = doc.get("message", str(e))
        except ValueError:
            msg = str(e)
        print(f"{label}: {url}: {msg}", file=sys.stderr)
        return None
    except (OSError, ValueError) as e:
        print(f"{label}: {url}: {e}", file=sys.stderr)
        return None


def _fmt_resource_map(m: dict) -> str:
    return ",".join(f"{k}={g:g}" for k, g in sorted(m.items())) or "-"


def _print_table(headers: tuple, rows: list) -> None:
    """Aligned-column table (kubectl-get style); shared by the queue and
    node views."""
    widths = [
        max(len(headers[c]), max(len(r[c]) for r in rows))
        for c in range(len(headers))
    ]
    print("  ".join(h.ljust(w) for h, w in zip(headers, widths)))
    for r in rows:
        print("  ".join(v.ljust(w) for v, w in zip(r, widths)))


def _fetch_server_json(apiserver: str, path: str, label: str):
    """GET a JSON document from a live apiserver (scheme-defaulted);
    returns None after printing the error."""
    import json as _json
    import urllib.request

    url = apiserver if "://" in apiserver else f"http://{apiserver}"
    try:
        with urllib.request.urlopen(f"{url}{path}", timeout=10) as r:
            return _json.loads(r.read())
    except (OSError, ValueError) as e:
        print(f"{label}: {url}: {e}", file=sys.stderr)
        return None


def _print_queue_table(items: list) -> None:
    if not items:
        print("no queues (and no queue-attributed usage)")
        return
    rows = [
        (
            it["name"] + ("" if it.get("defined", True) else " (implicit)"),
            _fmt_resource_map(it.get("deserved", {})),
            _fmt_resource_map(it.get("ceiling", {})),
            _fmt_resource_map(it.get("usage", {})),
            f"{it.get('dominantShare', 0.0):.3f}",
            str(it.get("admittedGangs", 0)),
            str(it.get("pendingGangs", 0)),
        )
        for it in items
    ]
    _print_table(
        ("NAME", "DESERVED", "CEILING", "USAGE", "SHARE", "ADMITTED",
         "PENDING"),
        rows,
    )


def _cmd_queues(args) -> int:
    """Per-queue quota summary (docs/quota.md): deserved/ceiling/usage,
    dominant share, admitted/pending gangs — from a live apiserver's
    GET /queues, or after simulating manifests (Queue + PodCliqueSet docs)."""
    if args.apiserver:
        doc = _fetch_server_json(args.apiserver, "/queues", "queues")
        if doc is None:
            return 1
        _print_queue_table(doc.get("items", []))
        return 0

    if not args.manifests:
        print(
            "queues: provide manifests to simulate (Queue + PodCliqueSet"
            " docs), or --apiserver URL to read a live cluster",
            file=sys.stderr,
        )
        return 2
    _ensure_backend()
    from grove_tpu.api.load import load_manifest_objects
    from grove_tpu.quota.manager import quota_snapshot
    from grove_tpu.sim.harness import SimHarness

    from grove_tpu.api.types import PodCliqueSet, Queue

    harness = SimHarness(num_nodes=args.nodes)
    for path in args.manifests:
        with open(path) as f:
            for obj in load_manifest_objects(f.read()):
                if not isinstance(obj, (PodCliqueSet, Queue)):
                    print(
                        f"queues: {path}: kind {obj.kind!r} is not"
                        " simulated here (Queue / PodCliqueSet only)",
                        file=sys.stderr,
                    )
                    return 2
                harness.apply(obj)
    harness.converge()
    _print_queue_table(quota_snapshot(harness.store))
    return 0


def _print_node_table(items: list) -> None:
    if not items:
        print("no nodes")
        return
    rows = [
        (
            it.get("name", "?"),
            it.get("state", "?")
            + (" (cordoned)" if it.get("cordoned") else ""),
            it.get("drain") or "-",
            f"{it.get('heartbeatAgeSeconds', 0.0):.1f}s",
            str(it.get("boundPods", 0)),
            _fmt_resource_map(it.get("capacity", {})),
        )
        for it in items
    ]
    _print_table(
        ("NAME", "STATE", "DRAIN", "HEARTBEAT-AGE", "PODS", "CAPACITY"), rows
    )


def _cmd_nodes(args) -> int:
    """Node health table (docs/robustness.md): lifecycle state, heartbeat
    age, bound pods, capacity — from a live apiserver's GET /nodes, or
    after simulating manifests on a synthetic cluster."""
    if args.apiserver:
        doc = _fetch_server_json(args.apiserver, "/nodes", "nodes")
        if doc is None:
            return 1
        _print_node_table(doc.get("items", []))
        return 0

    _ensure_backend()
    from grove_tpu.sim.harness import SimHarness

    harness = SimHarness(num_nodes=args.nodes)
    for path in args.manifests:
        with open(path) as f:
            harness.apply_yaml(f.read())
    if args.manifests:
        harness.converge()
    _print_node_table(harness.node_monitor.node_snapshot())
    return 0


def _post_server_json(apiserver: str, path: str, label: str):
    """POST (no body) to a live apiserver; returns the JSON document or
    None after printing the error."""
    import json as _json
    import urllib.error
    import urllib.request

    url = apiserver if "://" in apiserver else f"http://{apiserver}"
    req = urllib.request.Request(f"{url}{path}", data=b"", method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return _json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            doc = _json.loads(e.read())
            msg = doc.get("message", str(e))
        except ValueError:
            msg = str(e)
        print(f"{label}: {url}: {msg}", file=sys.stderr)
        return None
    except (OSError, ValueError) as e:
        print(f"{label}: {url}: {e}", file=sys.stderr)
        return None


def _cmd_drain(args) -> int:
    """Gang-aware node drain (docs/robustness.md): cordon the node and
    evict its gangs whole, budget-checked, with trial-solved pre-placement
    — POST /nodes/{name}/drain on a live apiserver."""
    doc = _post_server_json(
        args.apiserver, f"/nodes/{args.node}/drain", "drain"
    )
    if doc is None:
        return 1
    print(
        f"node {doc.get('name', args.node)} draining; watch progress with"
        f" `cli nodes --apiserver {args.apiserver}` (DRAIN column)"
    )
    return 0


def _cmd_uncordon(args) -> int:
    """Return a drained/cordoned node to service — POST
    /nodes/{name}/uncordon on a live apiserver."""
    doc = _post_server_json(
        args.apiserver, f"/nodes/{args.node}/uncordon", "uncordon"
    )
    if doc is None:
        return 1
    print(f"node {doc.get('name', args.node)} uncordoned")
    return 0


def _cmd_bench(args) -> int:
    import subprocess

    cmd = [sys.executable, "bench.py"]
    if args.small:
        cmd.append("--small")
    return subprocess.call(cmd)


def _cmd_crds(args) -> int:
    from grove_tpu.cluster.crdgen import render_crds, write_crds

    if args.output_dir:
        for path in write_crds(args.output_dir):
            print(path)
        return 0
    print(render_crds(), end="")
    return 0


def _cmd_detect_topology(args) -> int:
    """Automatic topology detection (reference roadmap item, shipped here):
    infer the ClusterTopology CR from node labels and print it."""
    import yaml

    from grove_tpu.admission.validation import validate_cluster_topology
    from grove_tpu.api.serialize import export_object
    from grove_tpu.cluster.autotopo import (
        TopologyDetectionError,
        detect_topology,
        load_nodes_file,
    )

    if args.file:
        nodes = load_nodes_file(args.file)
    else:
        from grove_tpu.sim.cluster import make_nodes

        nodes = make_nodes(args.sim_nodes)
    try:
        topo = detect_topology(nodes, name=args.name)
    except TopologyDetectionError as e:
        print(f"detect-topology: {e}", file=sys.stderr)
        return 1
    res = validate_cluster_topology(topo)
    if not res.ok:  # defensive: detection guarantees a valid CR
        print(f"detect-topology: invalid result: {res.errors}", file=sys.stderr)
        return 1
    print(yaml.safe_dump(export_object(topo), sort_keys=False), end="")
    return 0


def _cmd_api_docs(args) -> int:
    from grove_tpu.cluster.apidocs import render_api_reference, write_api_reference

    if args.write:
        print(write_api_reference(args.write))
        return 0
    print(render_api_reference(), end="")
    return 0


def _cmd_run(args) -> int:
    """Run the real-cluster operator: embedded apiserver (or external via
    --apiserver), webhook server, controllers, solver-backed scheduler.
    Serves /healthz /readyz /metrics; optional leader-election lock."""
    _ensure_backend()
    import threading

    from grove_tpu.cluster.manager import start_operator
    from grove_tpu.config.operator import load_operator_configuration_file
    from grove_tpu.sim.cluster import make_nodes

    config = (
        load_operator_configuration_file(args.config) if args.config else None
    )
    nodes = make_nodes(args.nodes)
    topology = None
    if args.auto_detect_topology:
        from grove_tpu.cluster.autotopo import TopologyDetectionError, detect_topology

        try:
            topology = detect_topology(nodes)
        except TopologyDetectionError as exc:
            print(f"error: topology detection failed: {exc}", file=sys.stderr)
            return 1
        print(
            "detected topology: "
            + " > ".join(lvl.domain for lvl in topology.spec.levels)
        )
    if args.leader_election and not args.apiserver:
        # election on a PRIVATE embedded apiserver is vacuous: each replica
        # would win its own lease and all of them would lead. HA requires
        # every replica to elect on ONE shared apiserver.
        print(
            "warning: --leader-election without --apiserver elects on this"
            " process's own embedded apiserver — replicas must share one"
            " apiserver (--apiserver URL) for the election to exclude them",
            file=sys.stderr,
        )
    if args.durability_dir and args.apiserver:
        print(
            "warning: --durability-dir applies to the EMBEDDED apiserver's"
            " store; an external apiserver owns its own durability —"
            " ignoring it",
            file=sys.stderr,
        )
    rt = start_operator(
        nodes=nodes,
        topology=topology,
        config=config,
        with_tls=args.tls,
        with_authorizer=args.authorizer,
        threaded=args.threaded,
        apiserver_url=args.apiserver,
        leader_lock_path=args.leader_lock,
        leader_election=True if args.leader_election else None,
        durability_dir=args.durability_dir,
    )
    if rt.apiserver is not None:
        print(f"apiserver:  {rt.apiserver.address}")
    if args.durability_dir:
        print(f"durability: {args.durability_dir} (WAL + snapshots)")
    if rt.webhooks is not None:
        print(f"webhooks:   {rt.webhooks.address}")
    print("operator running; Ctrl-C to stop", flush=True)
    stop = threading.Event()
    try:
        rt.run(stop)
    except KeyboardInterrupt:
        pass
    finally:
        rt.shutdown()
    return 0


def _cmd_config_check(args) -> int:
    from grove_tpu.config.operator import load_operator_configuration_file

    try:
        cfg = load_operator_configuration_file(args.config)
    except Exception as exc:
        print(f"INVALID: {exc}")
        return 1
    print(
        f"OK: logLevel={cfg.log_level} solver.chunkSize={cfg.solver.chunk_size}"
        f" authorizer.enabled={cfg.authorizer.enabled}"
    )
    return 0


def _ensure_backend() -> None:
    """Sim-backed commands run the placement solver; a wedged accelerator
    link must degrade to CPU instead of hanging the CLI. Lazy + memoized —
    pure-CPU commands (validate/config-check/bench-subprocess) never pay."""
    from grove_tpu.utils.platform import ensure_healthy_backend

    note = ensure_healthy_backend(timeout_s=45.0)
    if note != "default":
        print(f"note: {note}", file=sys.stderr)


def main(argv: List[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="grove-tpu", description=__doc__)
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("validate", help="admission-check manifests")
    p.add_argument("manifests", nargs="+")
    p.set_defaults(fn=_cmd_validate)

    p = sub.add_parser(
        "apply",
        help=(
            "apply manifests — to the simulated control plane, or to a live"
            " apiserver with --apiserver URL (create-or-update)"
        ),
    )
    p.add_argument("manifests", nargs="+")
    p.add_argument("--nodes", type=int, default=32)
    p.add_argument("--apiserver", help="apply to a live apiserver instead")
    p.add_argument("--namespace", default="default")
    p.set_defaults(fn=_cmd_apply)

    p = sub.add_parser("delete", help="delete objects on a live apiserver")
    p.add_argument("names", nargs="+")
    p.add_argument("--apiserver", required=True)
    p.add_argument("--kind", default="PodCliqueSet")
    p.add_argument("--namespace", default="default")
    p.set_defaults(fn=_cmd_delete)

    p = sub.add_parser(
        "scale", help="set replicas on a live apiserver (read-modify-write)"
    )
    p.add_argument("name")
    p.add_argument("--replicas", type=int, required=True)
    p.add_argument("--apiserver", required=True)
    p.add_argument("--kind", default="PodCliqueSet")
    p.add_argument("--namespace", default="default")
    p.set_defaults(fn=_cmd_scale)

    p = sub.add_parser(
        "tree",
        help=(
            "dump the pcs>pclq/pcsg>pg>pod tree — simulated (apply"
            " manifests first) or live with --apiserver URL"
        ),
    )
    p.add_argument("manifests", nargs="*")
    p.add_argument("--nodes", type=int, default=32)
    p.add_argument("--scale", action="append", metavar="GROUP=REPLICAS")
    p.add_argument("--apiserver", help="render a live apiserver instead")
    p.add_argument("--namespace", default="default")
    p.set_defaults(fn=_cmd_tree)

    p = sub.add_parser(
        "get",
        help=(
            "export live objects as YAML — from a real apiserver"
            " (--apiserver URL) or after applying manifests to a sim"
        ),
    )
    p.add_argument("manifests", nargs="*")
    p.add_argument("--kind", default="PodGang")
    p.add_argument("--nodes", type=int, default=32)
    p.add_argument("--apiserver", help="read from a live apiserver instead")
    p.add_argument(
        "--namespace",
        default=None,
        help="filter to one namespace (default: all namespaces)",
    )
    p.add_argument(
        "--watch",
        action="store_true",
        help="stream Added/Modified/Deleted events (requires --apiserver)",
    )
    p.set_defaults(fn=_cmd_get)

    p = sub.add_parser(
        "describe",
        help=(
            "kubectl-describe one object (conditions, lastErrors, events)"
            " — live with --apiserver or after simulating manifests"
        ),
    )
    p.add_argument("name")
    p.add_argument("manifests", nargs="*")
    p.add_argument("--kind", default="PodCliqueSet")
    p.add_argument("--nodes", type=int, default=32)
    p.add_argument("--apiserver", help="read from a live apiserver instead")
    p.add_argument("--namespace", default="default")
    p.set_defaults(fn=_cmd_describe)

    p = sub.add_parser(
        "queues",
        help=(
            "per-queue quota summary (deserved/usage/share, gang counts) —"
            " live with --apiserver URL or after simulating manifests"
        ),
    )
    p.add_argument("manifests", nargs="*")
    p.add_argument("--nodes", type=int, default=32)
    p.add_argument("--apiserver", help="read GET /queues from a live server")
    p.set_defaults(fn=_cmd_queues)

    p = sub.add_parser(
        "nodes",
        help=(
            "node health table (state, heartbeat age, bound pods) — live"
            " with --apiserver URL or after simulating manifests"
        ),
    )
    p.add_argument("manifests", nargs="*")
    p.add_argument("--nodes", type=int, default=32)
    p.add_argument("--apiserver", help="read GET /nodes from a live server")
    p.set_defaults(fn=_cmd_nodes)

    p = sub.add_parser(
        "drain",
        help=(
            "drain a node on a live apiserver: cordon + budget-checked"
            " gang-whole eviction with pre-placement (docs/robustness.md)"
        ),
    )
    p.add_argument("node", help="node name")
    p.add_argument(
        "--apiserver", required=True, help="apiserver URL (host:port)"
    )
    p.set_defaults(fn=_cmd_drain)

    p = sub.add_parser(
        "uncordon",
        help="return a drained/cordoned node to service on a live apiserver",
    )
    p.add_argument("node", help="node name")
    p.add_argument(
        "--apiserver", required=True, help="apiserver URL (host:port)"
    )
    p.set_defaults(fn=_cmd_uncordon)

    p = sub.add_parser("bench", help="run the stress benchmark")
    p.add_argument("--small", action="store_true")
    p.set_defaults(fn=_cmd_bench)

    p = sub.add_parser(
        "trace",
        help=(
            "pretty-print the slowest trace spans — from a live apiserver"
            " (--apiserver URL) or by running manifests through a traced sim"
        ),
    )
    p.add_argument("manifests", nargs="*")
    p.add_argument("--nodes", type=int, default=32)
    p.add_argument("--apiserver", help="read /debug/traces from a live server")
    p.add_argument("--top", type=int, default=15, help="span rows to show")
    p.add_argument(
        "--chrome",
        metavar="PATH",
        help="also write the Chrome trace_event JSON (chrome://tracing)",
    )
    p.set_defaults(fn=_cmd_trace)

    p = sub.add_parser(
        "profile",
        help=(
            "wall-attribution report: where control-plane seconds went per"
            " (controller, shard, phase) — from a live apiserver"
            " (--apiserver URL) or a profiled sim converge"
        ),
    )
    p.add_argument("manifests", nargs="*")
    p.add_argument("--nodes", type=int, default=32)
    p.add_argument(
        "--apiserver", help="read /debug/profile from a live server"
    )
    p.add_argument("--top", type=int, default=15, help="phase rows to show")
    p.set_defaults(fn=_cmd_profile)

    p = sub.add_parser(
        "journey",
        help=(
            "one PodGang's admission timeline (created → scanned → encoded"
            " → solved → committed → scheduled) with the queue-wait/"
            "service/solver split — from a live apiserver or a sim"
        ),
    )
    p.add_argument("manifests", nargs="*")
    p.add_argument("--nodes", type=int, default=32)
    p.add_argument(
        "--apiserver",
        help="read /gangs/{ns}/{name}/journey from a live server",
    )
    p.add_argument("--namespace", default="default")
    p.add_argument(
        "--gang",
        help="PodGang name (sim mode defaults to every admitted gang)",
    )
    p.set_defaults(fn=_cmd_journey)

    p = sub.add_parser(
        "slo",
        help=(
            "SLO observatory report: per-objective attainment, error"
            " budget, burn rates, breach state (GET /debug/slo)"
        ),
    )
    p.add_argument(
        "--apiserver", help="read /debug/slo from a live server"
    )
    p.add_argument(
        "--window",
        type=float,
        default=300.0,
        help="series-appendix window in seconds (default 300)",
    )
    p.set_defaults(fn=_cmd_slo)

    p = sub.add_parser(
        "federation",
        help=(
            "multi-cluster federation status: per-region state and"
            " placements, spillover/re-route counters, global per-queue"
            " usage (GET /federation)"
        ),
    )
    p.add_argument(
        "--apiserver", help="read /federation from a live server"
    )
    p.add_argument(
        "-o",
        "--output",
        choices=("table", "json"),
        default="table",
        help="output format (default table)",
    )
    p.set_defaults(fn=_cmd_federation)

    p = sub.add_parser(
        "forecast",
        help=(
            "per-series horizon forecasts: diurnal+trend predictions with"
            " confidence bands and skill vs the persistence baseline"
            " (GET /debug/forecast)"
        ),
    )
    p.add_argument(
        "--apiserver", help="read /debug/forecast from a live server"
    )
    p.add_argument(
        "--series",
        action="append",
        help="series to forecast (repeatable; default: the watched set)",
    )
    p.add_argument(
        "--horizon",
        type=float,
        default=0.0,
        help="forecast horizon in seconds (default: the forecaster's)",
    )
    p.set_defaults(fn=_cmd_forecast)

    p = sub.add_parser(
        "ledger",
        help=(
            "causal decision→effect ledger: every remediation considered,"
            " as trigger→diagnosis→simulation→action→effect chains"
            " (GET /debug/ledger)"
        ),
    )
    p.add_argument(
        "--apiserver", help="read /debug/ledger from a live server"
    )
    p.set_defaults(fn=_cmd_ledger)

    p = sub.add_parser(
        "explain",
        help=(
            "why is this PodGang Pending, and what binds it — the"
            " constraint-elimination funnel (node health → capacity →"
            " topology → quota → disruption → solve) from a live"
            " apiserver or a sim"
        ),
    )
    p.add_argument("manifests", nargs="*")
    p.add_argument("--nodes", type=int, default=32)
    p.add_argument(
        "--apiserver",
        help="read /gangs/{ns}/{name}/explain from a live server",
    )
    p.add_argument("--namespace", default="default")
    p.add_argument(
        "--gang",
        help="PodGang name (sim mode defaults to every gang)",
    )
    p.set_defaults(fn=_cmd_explain)

    p = sub.add_parser(
        "capacity",
        help=(
            "per-topology-level free capacity + the fragmentation"
            " statistic (largest contiguous domain vs total free)"
        ),
    )
    p.add_argument("manifests", nargs="*")
    p.add_argument("--nodes", type=int, default=32)
    p.add_argument(
        "--apiserver", help="read /debug/capacity from a live server"
    )
    p.set_defaults(fn=_cmd_capacity)

    p = sub.add_parser(
        "whatif",
        help=(
            "hypothetical trial solve: would the gang fit if nodes were"
            " drained/removed/added or a queue's entitlement changed?"
            " Commits nothing"
        ),
    )
    p.add_argument("manifests", nargs="*")
    p.add_argument("--nodes", type=int, default=32)
    p.add_argument(
        "--apiserver", help="POST /debug/whatif to a live server"
    )
    p.add_argument("--namespace", default="default")
    p.add_argument("--gang", required=True, help="target PodGang name")
    p.add_argument(
        "--drain", action="append", metavar="NODE",
        help="hypothetically drain NODE (gang-whole relocation)",
    )
    p.add_argument(
        "--remove", action="append", metavar="NODE",
        help="hypothetically remove NODE (capacity only)",
    )
    p.add_argument(
        "--add-nodes", type=int, metavar="N",
        help="hypothetically add N nodes cloned from --like",
    )
    p.add_argument(
        "--like", metavar="NODE",
        help="template node for --add-nodes (capacity + topology)",
    )
    p.add_argument(
        "--set-queue", metavar="QUEUE",
        help="hypothetically rewrite QUEUE's entitlement",
    )
    p.add_argument(
        "--deserved", metavar="RES=V[,RES=V]",
        help="deserved shares for --set-queue",
    )
    p.add_argument(
        "--ceiling", metavar="RES=V[,RES=V]",
        help="ceiling for --set-queue",
    )
    p.set_defaults(fn=_cmd_whatif)

    p = sub.add_parser("config-check", help="validate an operator config file")
    p.add_argument("config")
    p.set_defaults(fn=_cmd_config_check)

    p = sub.add_parser("crds", help="print or write the CRD manifests")
    p.add_argument("--output-dir", metavar="DIR")
    p.set_defaults(fn=_cmd_crds)

    p = sub.add_parser(
        "api-docs", help="render the API reference from the typed model"
    )
    p.add_argument("--write", metavar="PATH", help="write to PATH instead of stdout")
    p.set_defaults(fn=_cmd_api_docs)

    p = sub.add_parser(
        "detect-topology",
        help="infer the ClusterTopology CR from node labels",
    )
    p.add_argument(
        "--file",
        metavar="NODES_YAML",
        help="node list (k8s NodeList, Node manifests, or [{name, labels}])",
    )
    p.add_argument(
        "--sim-nodes",
        type=int,
        default=16,
        help="detect from a synthetic sim cluster of N nodes (demo)",
    )
    p.add_argument("--name", default="default", help="CR name")
    p.set_defaults(fn=_cmd_detect_topology)

    p = sub.add_parser(
        "run", help="run the operator against a real (HTTP) apiserver"
    )
    p.add_argument("--config", help="operator configuration file")
    p.add_argument("--nodes", type=int, default=32)
    p.add_argument(
        "--apiserver", help="external apiserver URL (default: embedded)"
    )
    p.add_argument("--tls", action="store_true", help="TLS webhook serving")
    p.add_argument(
        "--authorizer", action="store_true", help="enable the authorizer webhook"
    )
    p.add_argument("--leader-lock", help="leader-election lock file path")
    p.add_argument(
        "--leader-election",
        action="store_true",
        help="lease-based leader election over the apiserver "
        "(coordination.k8s.io/v1 Lease; HA across hosts)",
    )
    p.add_argument(
        "--threaded",
        action="store_true",
        default=None,  # tri-state: unset defers to GROVE_TPU_CP_WORKERS
        # (docs/control-plane.md §5 — cluster mode maps the parallel-CP
        # opt-in onto threaded reconciles); the flag pins True
        help="run concurrent reconciles in real threads (concurrentSyncs)",
    )
    p.add_argument(
        "--durability-dir",
        help="durable control plane (docs/robustness.md): recover the"
        " embedded apiserver's store from this directory's snapshot+WAL"
        " at boot and log every commit to it (WAL + periodic snapshots,"
        " background group-commit thread)",
    )
    p.add_argument(
        "--auto-detect-topology",
        action="store_true",
        help="infer the ClusterTopology from node labels at startup",
    )
    p.set_defaults(fn=_cmd_run)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
