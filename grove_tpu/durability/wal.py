"""Write-ahead log: append-only, CRC-framed record of every store commit.

Record stream
-------------

Each group-commit batch becomes ONE framed entry (the batch is already
the atomicity unit — one fsync covers it — so the CRC frame and the JSON
encoder invocation are per batch, not per record):

    [u32 payload_len][u32 crc32(payload)][payload]

with a UTF-8 JSON payload that is an ARRAY of record docs::

    [{"seq": n, "op": "put"|"patch"|"delete", "rv": resourceVersion,
      "kind": ..., "ns": ..., "name": ..., "dt": deletionTimestamp|null,
      "obj": <wire doc>}                       # "put" only
      ... "gen": N, "status"/"spec"/"meta": <subtree doc>}, ...]  # "patch"

Docs are the ``api/serialize.py`` wire export (camelCase, the same codec
the HTTP apiserver speaks — GL004 bans pickle on the control-plane write
path, and a pickled log would tie recovery to one code version). The
envelope carries ``ns``/``dt`` explicitly because the wire export drops
empty values: a cluster-scoped object's ``namespace: ""`` and a deletion
at virtual t=0.0 must round-trip exactly.

**Patch records** are the cost story: the store's copy-on-write commits
STRUCTURALLY SHARE untouched subtrees with the previous committed object
(runtime/store.py ``commit_cow``), so an ``is``-identity check on the
watch event's old/new pair proves which subtrees changed — in O(1),
before any serialization. A pod status write then logs ~350 bytes of
status instead of ~1.6 KB of whole pod, which is what keeps WAL overhead
inside the cp-bench budget. Replay applies patches onto the prior state
of the key (the base always exists: every object's first record is its
full create).

Ack contract (group commit)
---------------------------

``note_event`` only *buffers* a reference to the immutable committed
object — no serialization, no I/O — so the commit path (reconcile
bodies; GL008) stays non-blocking. A later ``flush()`` — the background
committer in real-cluster mode, the per-tick pump in sims — serializes
the batch, appends, and fsyncs once for the whole group. A commit is
**durable (acked)** only once ``flush()`` returned with its record on
disk: ``durable_rv`` names the highest resourceVersion the log
guarantees to survive a crash. Everything after it is the crash-lossable
tail, and recovery (``recovery.py``) rolls the store back to exactly the
durable prefix.

Torn tails
----------

A crash mid-write leaves a torn final frame (short header, short
payload, or CRC mismatch). Readers stop at the first bad frame and
truncate there — records past a torn frame are unordered garbage by
definition. Segments rotate at ``segment_max_bytes``; snapshots
(``snapshot.py``) truncate the fully-covered ones.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

from grove_tpu.api.serialize import (
    export_object,
    export_object_shared,
    to_dict,
)
from grove_tpu.observability.metrics import METRICS
from grove_tpu.observability.profile import PROFILER

_HEADER = struct.Struct("<II")  # payload length, crc32(payload)

SEGMENT_PREFIX = "wal-"
SEGMENT_SUFFIX = ".seg"

# per-shard WAL layout (docs/control-plane.md): a sharded store's
# durability directory holds one subdirectory per keyspace shard, each a
# complete single-writer WAL+snapshot stream for that shard's slice.
# The UNSHARDED layout (segments directly in the directory) is untouched
# — S=1 stays byte-identical on disk.
SHARD_DIR_PREFIX = "shard-"


def shard_dir_name(index: int) -> str:
    return f"{SHARD_DIR_PREFIX}{index:03d}"


def list_shard_dirs(directory: str) -> List[Tuple[int, str]]:
    """(shard index, absolute path) of every per-shard WAL dir, ordered.
    Empty for an unsharded layout — the caller's sharded/unsharded probe."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        if not name.startswith(SHARD_DIR_PREFIX):
            continue
        path = os.path.join(directory, name)
        if not os.path.isdir(path):
            continue
        try:
            out.append((int(name[len(SHARD_DIR_PREFIX):]), path))
        except ValueError:
            continue
    out.sort()
    return out


def _segment_name(index: int) -> str:
    return f"{SEGMENT_PREFIX}{index:08d}{SEGMENT_SUFFIX}"


def segment_index(filename: str) -> Optional[int]:
    if not (
        filename.startswith(SEGMENT_PREFIX)
        and filename.endswith(SEGMENT_SUFFIX)
    ):
        return None
    try:
        return int(filename[len(SEGMENT_PREFIX) : -len(SEGMENT_SUFFIX)])
    except ValueError:
        return None


def list_segments(directory: str) -> List[Tuple[int, str]]:
    """(index, absolute path) of every segment file, index-ordered."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        idx = segment_index(name)
        if idx is not None:
            out.append((idx, os.path.join(directory, name)))
    out.sort()
    return out


# ---------------------------------------------------------------------------
# envelope codec (shared with snapshot.py)
# ---------------------------------------------------------------------------


def object_envelope(obj) -> dict:
    """Wire envelope of one committed object: the serialize.py export plus
    the identity fields the export would drop when empty."""
    meta = obj.metadata
    return {
        "rv": meta.resource_version,
        "kind": obj.kind,
        "ns": meta.namespace,
        "name": meta.name,
        "dt": meta.deletion_timestamp,
        "obj": export_object(obj),
    }


def decode_envelope(env: dict):
    """Envelope → typed object with exact identity restored."""
    from grove_tpu.api.wire import decode_object

    obj = decode_object(env["obj"])
    # the wire export drops empty values; the envelope is authoritative
    # for the fields whose empty forms are semantically load-bearing
    obj.metadata.namespace = env["ns"]
    obj.metadata.name = env["name"]
    obj.metadata.deletion_timestamp = env.get("dt")
    return obj


@dataclass
class WalRecord:
    seq: int
    op: str  # "put" | "patch" | "delete"
    rv: int
    kind: str
    namespace: str
    name: str
    envelope: Optional[dict]  # full envelope for "put"; None otherwise
    patch: Optional[dict] = None  # raw payload doc for "patch"

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.kind, self.namespace, self.name)


def _decode_frame(payload: bytes) -> List[WalRecord]:
    """One CRC-framed payload → its batch of records (legacy single-doc
    payloads decode as a batch of one)."""
    doc = json.loads(payload.decode("utf-8"))
    docs = doc if isinstance(doc, list) else [doc]
    return [_decode_record_doc(d) for d in docs]


def _decode_record_doc(doc: dict) -> WalRecord:
    env = None
    if doc["op"] == "put":
        env = {
            "rv": doc["rv"],
            "kind": doc["kind"],
            "ns": doc["ns"],
            "name": doc["name"],
            "dt": doc.get("dt"),
            "obj": doc["obj"],
        }
    return WalRecord(
        seq=doc.get("seq", 0),
        op=doc["op"],
        rv=doc["rv"],
        kind=doc["kind"],
        namespace=doc["ns"],
        name=doc["name"],
        envelope=env,
        patch=doc if doc["op"] == "patch" else None,
    )


def apply_record(state: dict, rec: WalRecord) -> None:
    """Fold one replayed record into the key→envelope state map (the ONE
    application semantics recovery and the acked-prefix auditor share)."""
    if rec.op == "delete":
        state.pop(rec.key, None)
        return
    if rec.op == "put":
        state[rec.key] = rec.envelope
        return
    # patch: subtree replacement onto the key's prior state. The base
    # always exists (first record per key is its full create; snapshots
    # hold full envelopes) — a missing base means corruption upstream of
    # the CRC layer, surfaced by the acked-prefix audit rather than here.
    env = state.get(rec.key)
    if env is None:
        return
    patch = rec.patch
    doc = env["obj"]
    meta = doc.setdefault("metadata", {})
    if "meta" in patch:
        doc["metadata"] = meta = patch["meta"]
    meta["resourceVersion"] = rec.rv
    if patch.get("gen"):
        meta["generation"] = patch["gen"]
    for subtree in ("status", "spec"):
        if subtree in patch:
            if patch[subtree]:
                doc[subtree] = patch[subtree]
            else:
                doc.pop(subtree, None)
    env["rv"] = rec.rv
    env["dt"] = patch.get("dt")


def read_segment(path: str) -> Tuple[List[WalRecord], Optional[int]]:
    """Decode one segment. Returns (records, torn_offset): torn_offset is
    the byte offset of the first bad frame (None when the file is clean) —
    the truncation point the torn-tail policy cuts at."""
    records: List[WalRecord] = []
    with open(path, "rb") as fh:
        data = fh.read()
    offset = 0
    total = len(data)
    while offset < total:
        header = data[offset : offset + _HEADER.size]
        if len(header) < _HEADER.size:
            return records, offset  # torn header
        length, crc = _HEADER.unpack(header)
        start = offset + _HEADER.size
        payload = data[start : start + length]
        if len(payload) < length or zlib.crc32(payload) != crc:
            return records, offset  # torn/corrupt payload
        try:
            records.extend(_decode_frame(payload))
        except (ValueError, KeyError):
            return records, offset  # undecodable payload: treat as torn
        offset = start + length
    return records, None


class WriteAheadLog:
    """Segmented append-only log with group-commit fsync batching.

    One writer per directory: the store process owns its WAL the way an
    etcd member owns its data dir. ``note_event`` may be called from any
    commit site (it only buffers); ``flush``/``snapshot-truncate`` are
    serialized by ``_io_lock``.
    """

    def __init__(
        self, directory: str, segment_max_bytes: int = 4 * 2**20
    ) -> None:
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.segment_max_bytes = segment_max_bytes
        # owning keyspace shard of this stream (StoreDurability stamps it
        # on sharded stores) — wall-attribution rows then split per shard
        self.shard = 0
        # _lock guards the buffer/seq; _io_lock serializes flush and
        # truncation (lock order: _io_lock -> _lock, never inverted)
        self._lock = threading.Lock()
        self._io_lock = threading.Lock()
        self._buffer: List[tuple] = []  # (seq, op, committed obj)
        self._seq = 0
        self._dead = False  # simulate_crash: the process is gone
        # worker-process backend (runtime/procworkers.py): a stream whose
        # shard is owned by ANOTHER process is marked remote — that
        # process appends to the same directory (one writer per stream
        # still holds; this handle just goes inert, keeping watermarks the
        # owner ships back). Flipped off on repatriation after a worker
        # crash, when the coordinator takes the stream back.
        self.remote = False
        # gray-failure injection (docs/robustness.md "Gray failures"):
        # chaos faults set these; the degradation ladder in
        # StoreDurability reads the symptoms and steps rungs. Both
        # default off — the healthy flush path is byte-identical.
        # fault_slow_fsync models N seconds of extra fsync latency (the
        # fail-slow disk): flush still succeeds, the modeled lag lands
        # in last_fsync_lag for the ladder's SLO compare. No real sleep
        # — determinism and test wall-time both forbid it.
        self.fault_slow_fsync = 0.0
        # fault_disk_full makes flush raise ENOSPC with the batch still
        # BUFFERED — nothing acked, nothing lost; the ladder's read-only
        # rung decides what the store does about it.
        self.fault_disk_full = False
        self.last_fsync_lag = 0.0
        self.durable_seq = 0
        self.durable_rv = 0
        self.flushed_bytes = 0
        self.flushed_records = 0
        # resume AFTER any existing segments (a recovered store re-attaches
        # to the same directory; old segments stay readable behind us)
        existing = list_segments(directory)
        self._segment_index = (existing[-1][0] + 1) if existing else 0
        self._segment_bytes = 0
        self._fh = None  # opened lazily on first flush

    # -- write path ------------------------------------------------------

    def note_event(self, ev) -> None:
        """Buffer one committed watch event (Added/Modified/Deleted). The
        payload objects (new AND old committed state) are immutable, so
        serialization — and the old/new subtree-sharing comparison that
        turns a commit into a small patch record — is safely deferred to
        flush()."""
        if self._dead or self.remote:
            return
        if ev.kind == "Event":
            # fire-and-forget Event objects are best-effort by contract
            # (real etcd TTLs them away); they are outside the durability
            # guarantee and would be ~12% of record volume
            return
        op = "delete" if ev.type == "Deleted" else "put"
        with self._lock:
            self._seq += 1
            self._buffer.append((self._seq, op, ev.obj, ev.old))

    def pending(self) -> int:
        with self._lock:
            return len(self._buffer)

    @staticmethod
    def _meta_unchanged(meta, old_meta) -> bool:
        """True when metadata differs from the previous commit only in the
        version bookkeeping commit_cow restamps. Identity checks carry the
        proof: the cow path shallow-copies metadata, so the mutable
        members are the SAME objects unless a caller replaced them."""
        return (
            meta.labels is old_meta.labels
            and meta.annotations is old_meta.annotations
            and meta.finalizers is old_meta.finalizers
            and meta.owner_references is old_meta.owner_references
            and meta.name == old_meta.name
            and meta.namespace == old_meta.namespace
            and meta.uid == old_meta.uid
            and meta.deletion_timestamp == old_meta.deletion_timestamp
        )

    def _encode(self, seq: int, op: str, obj, old, memo: dict) -> dict:
        """One buffered event → its record doc (framing happens per batch)."""
        meta = obj.metadata
        doc = {
            "seq": seq,
            "op": op,
            "rv": meta.resource_version,
            "kind": obj.kind,
            "ns": meta.namespace,
            "name": meta.name,
        }
        if op == "put":
            # copy-on-write commits share untouched subtrees with the old
            # committed object BY IDENTITY — log only what changed
            spec_shared = old is not None and getattr(
                obj, "spec", None
            ) is getattr(old, "spec", None)
            status_shared = old is not None and getattr(
                obj, "status", None
            ) is getattr(old, "status", None)
            if old is not None and (spec_shared or status_shared):
                doc["op"] = "patch"
                doc["gen"] = meta.generation
                doc["dt"] = meta.deletion_timestamp
                if not self._meta_unchanged(meta, old.metadata):
                    doc["meta"] = to_dict(meta)
                if not status_shared:
                    status = getattr(obj, "status", None)
                    doc["status"] = to_dict(status) if status is not None else {}
                if not spec_shared:
                    doc["spec"] = to_dict(obj.spec)
            else:
                # batch-scoped memo: sibling creates from one desired-state
                # template share subtree identity — serialize each shared
                # spec once per flush, not once per pod
                doc["dt"] = meta.deletion_timestamp
                doc["obj"] = export_object_shared(obj, memo)
        return doc

    def _ensure_segment(self):
        if self._fh is None:
            path = os.path.join(
                self.directory, _segment_name(self._segment_index)
            )
            self._fh = open(path, "ab")
            self._segment_bytes = self._fh.tell()
            METRICS.inc("wal_segments_total")
        return self._fh

    def _rotate(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        self._segment_index += 1
        self._segment_bytes = 0

    def flush(self) -> int:
        """Group commit: serialize the buffered batch, append, fsync ONCE,
        then advance the durable watermark. Returns records flushed."""
        # wall attribution (observability/profile.py): the flush IS the
        # durability layer's share of control-plane wall — one row per
        # shard stream. Disabled profiling costs this one boolean check.
        prof = (
            PROFILER.phase("wal-flush", controller="wal", shard=self.shard)
            if PROFILER.enabled
            else None
        )
        try:
            with self._io_lock:
                return self._flush_locked()
        finally:
            if prof is not None:
                prof.end()

    @staticmethod
    def _coalesce(batch: List[tuple]) -> List[tuple]:
        """Per-key last-write-wins within one group-commit batch.

        A batch is durable atomically (one fsync covers it all), so only
        each key's FINAL state matters to recovery — a pod created and
        status-patched three times in one tick needs one record, not
        four. Kept per key: the LAST object (final state) and the FIRST
        old (the pre-batch committed state the patch-vs-put identity
        check must compare against — cow subtree identity is transitive
        across the intermediate commits). delete→recreate degrades to a
        full put; anything→delete ends as the delete."""
        coalesced: dict = {}
        order: List[tuple] = []
        for seq, op, obj, old in batch:
            meta = obj.metadata
            key = (obj.kind, meta.namespace, meta.name)
            prev = coalesced.get(key)
            if prev is None:
                coalesced[key] = [seq, op, obj, old]
                order.append(key)
            elif op == "delete":
                prev[0], prev[1], prev[2], prev[3] = seq, op, obj, None
            elif prev[1] == "delete":
                # deleted then re-created within the batch: the base is
                # gone — full put of the new object
                prev[0], prev[1], prev[2], prev[3] = seq, op, obj, None
            else:
                prev[0], prev[2] = seq, obj  # keep the FIRST old
        if len(order) == len(batch):
            return batch
        return [tuple(coalesced[key]) for key in order]

    def _flush_locked(self) -> int:
        if self._dead or self.remote:
            return 0
        if self.fault_disk_full:
            # the batch stays buffered: nothing was acked, so nothing is
            # lost — the ladder turns this into read-only, not a crash
            raise OSError(28, "No space left on device (injected)")
        with self._lock:
            batch, self._buffer = self._buffer, []
        if not batch:
            return 0
        t_flush = time.perf_counter()
        last_seq = batch[-1][0]
        batch = self._coalesce(batch)
        memo: dict = {}  # one per batch: the buffer pins the objects alive
        docs = [
            self._encode(seq, op, obj, old, memo)
            for seq, op, obj, old in batch
        ]
        payload = json.dumps(docs, separators=(",", ":")).encode("utf-8")
        data = _HEADER.pack(len(payload), zlib.crc32(payload)) + payload
        t0 = time.perf_counter()
        fh = self._ensure_segment()
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
        fsync_lag = time.perf_counter() - t0
        if self.fault_slow_fsync > 0.0:
            # the fail-slow disk: model the extra latency (observed, not
            # slept) so the ladder's SLO compare sees the symptom
            fsync_lag += self.fault_slow_fsync
        self.last_fsync_lag = fsync_lag
        METRICS.observe("wal_fsync_seconds", fsync_lag)
        METRICS.inc("wal_flushed_bytes_total", len(data))
        METRICS.inc("wal_records_total", len(batch))
        self._segment_bytes += len(data)
        self.flushed_bytes += len(data)
        self.flushed_records += len(batch)
        self.durable_seq = last_seq
        self.durable_rv = max(
            self.durable_rv,
            max(obj.metadata.resource_version for _s, _o, obj, _old in batch),
        )
        if self._segment_bytes >= self.segment_max_bytes:
            self._rotate()
        # whole group-commit cost (coalesce + encode + write + fsync):
        # what "WAL enabled" adds to the control plane's wall clock
        METRICS.observe(
            "wal_flush_seconds", time.perf_counter() - t_flush
        )
        return len(batch)

    def truncate_segments_through(self, last_index: int) -> int:
        """Delete every closed segment with index <= last_index (snapshot
        log truncation). The caller must hold no records beyond the
        snapshot in those segments — snapshot.py flushes first and cuts at
        the current segment boundary."""
        removed = 0
        for idx, path in list_segments(self.directory):
            if idx <= last_index:
                try:
                    os.unlink(path)
                    removed += 1
                except OSError:
                    pass
        return removed

    def cut_segment(self) -> int:
        """Close the current segment and start a fresh one; returns the
        index of the last CLOSED segment (snapshot truncation boundary)."""
        with self._io_lock:
            self._flush_locked()
            closed = self._segment_index
            self._rotate()
            return closed

    # -- crash simulation (chaos harness / tests) ------------------------

    def simulate_crash(self, torn_tail_bytes: int = 0) -> int:
        """Model the store process dying NOW: the unflushed buffer is lost
        with the process, and (optionally) the final disk write is torn —
        ``torn_tail_bytes`` of a half-written frame land after the last
        durable record. Returns the number of records lost."""
        with self._io_lock:
            with self._lock:
                lost = len(self._buffer)
                self._buffer = []
                self._dead = True
            if torn_tail_bytes > 0:
                fh = self._ensure_segment()
                # a plausible torn frame: a valid-looking header promising
                # more payload than ever hit the disk
                frame = _HEADER.pack(torn_tail_bytes + 64, 0xDEADBEEF)
                frame += b"\x00" * torn_tail_bytes
                fh.write(frame)
                fh.flush()
            if self._fh is not None:
                self._fh.close()
                self._fh = None
        return lost

    def close(self) -> None:
        with self._io_lock:
            self._flush_locked()
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            self._dead = True


def replay(
    directory: str, min_segment: int = -1
) -> Tuple[List[WalRecord], bool, int]:
    """Read the durable record stream: every record in segments with
    index > min_segment (the snapshot's coverage boundary — deletes carry
    no fresh resourceVersion, so the cut is positional, not rv-based), in
    log order, truncating at the first bad frame (torn-tail policy: a torn
    frame ends the replayable prefix — later segments, if any, postdate
    the tear and are discarded too). Returns (records, torn, truncated_files).
    Truncation REWRITES the torn segment to its good prefix and removes
    later segments, so a recovered store that re-attaches appends after a
    clean tail."""
    out: List[WalRecord] = []
    torn = False
    truncated = 0
    segments = list_segments(directory)
    for pos, (idx, path) in enumerate(segments):
        if idx <= min_segment:
            continue
        records, torn_offset = read_segment(path)
        out.extend(records)
        if torn_offset is not None:
            torn = True
            with open(path, "rb+") as fh:
                fh.truncate(torn_offset)
            truncated += 1
            for _later_idx, later_path in segments[pos + 1 :]:
                try:
                    os.unlink(later_path)
                    truncated += 1
                except OSError:
                    pass
            break
    return out, torn, truncated


def _iter_durable_state(
    directory: str,
) -> Iterator[Tuple[Tuple[str, str, str], Optional[dict]]]:
    """(key, envelope|None) pairs of the durable prefix: snapshot base plus
    replayed records, last-write-wins per key (None = deleted). Shared by
    recovery and the acked-prefix verifier."""
    from grove_tpu.durability.snapshot import load_latest_snapshot

    snap = load_latest_snapshot(directory)
    state: dict = {}
    min_segment = -1
    if snap is not None:
        min_segment = snap.get("wal_seg", -1)
        for env in snap["objects"]:
            state[(env["kind"], env["ns"], env["name"])] = env
    records, _torn, _truncated = replay(directory, min_segment=min_segment)
    present = {k for k, v in state.items() if v is not None}
    live: dict = {k: v for k, v in state.items()}
    for rec in records:
        apply_record(live, rec)
    # normalize: deleted keys read as None so callers can distinguish
    # "durably deleted" from "never existed"
    out = {k: None for k in present if k not in live}
    out.update(live)
    return iter(sorted(out.items()))
