"""Crash-restart recovery + the store↔WAL attachment.

``recover_store`` rebuilds a Store from a durability directory: load the
newest valid snapshot, replay the WAL tail (truncating at the first bad
CRC — the torn-tail policy), decode the surviving envelopes through the
wire codec, and bulk-load them with identity preserved
(``Store.restore_objects`` restores resourceVersion/generation
monotonicity). The recovered store then converges like a failover does:
the caller runs the PR-5 resync machinery — ``engine.requeue_all()``,
``cluster.rebuild_bindings()``, ``monitor.resync()``, fresh
broker/drainer (``SimHarness.cold_restart`` packages exactly that).

``StoreDurability`` is the live attachment: it subscribes to the store's
system watch fanout (the same channel kubelets use — zero new code on
the commit path), buffers records, and group-commits them off the
reconcile path via ``pump()`` (sim tick boundary) or a background
committer thread (real-cluster mode).

``verify_acked_prefix`` is the independent auditor behind the chaos
harness's *no-acked-commit-lost* invariant: it re-reads the durable
prefix from disk and demands the recovered store match it exactly.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import nullcontext
from dataclasses import dataclass, field
from typing import List, Optional

from grove_tpu.durability.snapshot import write_snapshot
from grove_tpu.durability.wal import (
    WriteAheadLog,
    _iter_durable_state,
    apply_record,
    decode_envelope,
    list_segments,
    list_shard_dirs,
    replay,
    shard_dir_name,
)
from grove_tpu.observability.events import (
    EVENTS,
    REASON_RECOVERY_COMPLETED,
    REASON_SNAPSHOT_TAKEN,
    REASON_WAL_DEGRADED,
    REASON_WAL_RECOVERED,
    REASON_WAL_TORN_TAIL,
    TYPE_NORMAL,
    TYPE_WARNING,
)
from grove_tpu.observability.metrics import METRICS
from grove_tpu.observability.tracing import TRACER
from grove_tpu.runtime.errors import ERR_CONFLICT, GroveError

# the EVENTS ref durability events attach to: the store has no CR of its
# own (it IS the apiserver), so the recorder gets a synthetic singleton
_STORE_REF = ("Store", "", "durability")


@dataclass
class RecoveryReport:
    snapshot_rv: int = 0
    replayed_records: int = 0
    restored_objects: int = 0
    resource_version: int = 0
    torn_tail: bool = False
    wall_seconds: float = 0.0

    def as_dict(self) -> dict:
        return {
            "snapshot_rv": self.snapshot_rv,
            "replayed_records": self.replayed_records,
            "restored_objects": self.restored_objects,
            "resource_version": self.resource_version,
            "torn_tail": self.torn_tail,
            "wall_seconds": round(self.wall_seconds, 4),
            "replay_records_per_sec": round(
                self.replayed_records / self.wall_seconds, 1
            )
            if self.wall_seconds > 0
            else 0.0,
        }


def recover_store(
    directory: str, clock=None, cache_lag: bool = False
):
    """Rebuild a Store from its durability directory.

    Returns ``(store, RecoveryReport)``. The store holds exactly the
    durable prefix: snapshot base + replayed WAL tail, last-write-wins
    per key, torn tail truncated at the first bad CRC. Empty/missing
    directories recover to an empty store (a first boot)."""
    from grove_tpu.durability.snapshot import load_latest_snapshot
    from grove_tpu.runtime.store import Store

    report = RecoveryReport()
    t0 = time.perf_counter()
    with TRACER.span("recovery.replay", directory=directory) as span:
        # sharded layout probe (docs/control-plane.md): per-shard WAL dirs
        # mean a sharded store wrote this directory — recover each shard's
        # self-contained stream and merge; the dir count fixes the shard
        # count (the keyspace map is deterministic, so every object lands
        # back on the shard whose stream carried it). A dir with segments
        # or a snapshot directly inside is the legacy unsharded layout and
        # pins S=1 whatever the ambient knob says (the disk wins). A dir
        # with NEITHER is a first boot: nothing on disk constrains the
        # shape, so the store follows the configured shard count
        # (GROVE_TPU_STORE_SHARDS) — the real-cluster operator boots
        # through recovery even on an empty data dir, and pinning S=1
        # there would silently disable sharding forever.
        from grove_tpu.durability.snapshot import list_snapshots

        shard_dirs = list_shard_dirs(directory)
        # existence probe only (filename scan) — loading the snapshot here
        # would CRC-parse the whole store state twice per recovery
        legacy_layout = bool(list_segments(directory)) or bool(
            list_snapshots(directory)
        )
        if shard_dirs:
            num_shards = shard_dirs[-1][0] + 1
            if len(shard_dirs) != num_shards:
                # a GAP in the shard-NNN sequence means a shard's whole
                # stream is gone (partial copy, external deletion) —
                # recovering it as "empty" would silently drop its acked
                # commits and the audit could never see them
                present = [i for i, _ in shard_dirs]
                raise GroveError(
                    ERR_CONFLICT,
                    f"per-shard WAL layout has gaps: dirs {present} imply"
                    f" {num_shards} shards but only {len(shard_dirs)}"
                    " stream(s) are on disk — refusing to recover with a"
                    " missing shard stream",
                    "recover",
                )
            streams = shard_dirs
        elif legacy_layout:
            num_shards = 1
            streams = [(0, directory)]
        else:
            # first boot: env-/default-configured shape (num_shards=None →
            # the Store constructor's GROVE_TPU_STORE_SHARDS default), no
            # streams to read
            num_shards = None
            streams = []
        state: dict = {}
        shard_rvs: dict = {}
        for shard_idx, stream_dir in streams:
            snap = load_latest_snapshot(stream_dir)
            max_rv = 0
            min_segment = -1
            if snap is not None:
                # scalar report field follows the store's merge rule:
                # per-shard watermarks SUM to the store-level rv
                report.snapshot_rv += snap["rv"]
                max_rv = snap["rv"]
                min_segment = snap.get("wal_seg", -1)
                for env in snap["objects"]:
                    state[(env["kind"], env["ns"], env["name"])] = env
            records, torn, _truncated = replay(
                stream_dir, min_segment=min_segment
            )
            report.torn_tail = report.torn_tail or torn
            report.replayed_records += len(records)
            for rec in records:
                max_rv = max(max_rv, rec.rv)
                apply_record(state, rec)
            shard_rvs[shard_idx] = max_rv
        torn = report.torn_tail
        store = Store(clock, cache_lag=cache_lag, num_shards=num_shards)
        rv_vector = [
            shard_rvs.get(i, 0) for i in range(store.num_shards)
        ]
        objects = [
            decode_envelope(env)
            for _key, env in sorted(state.items())
            if env is not None
        ]
        report.restored_objects = store.restore_objects(
            objects,
            rv=rv_vector[0],
            rv_vector=tuple(rv_vector) if store.num_shards > 1 else None,
        )
        report.resource_version = store.resource_version
        span.set("replayed", report.replayed_records)
        span.set("restored", report.restored_objects)
        span.set("torn_tail", torn)
        span.set("shards", store.num_shards)
    report.wall_seconds = time.perf_counter() - t0
    METRICS.observe("recovery_seconds", report.wall_seconds)
    METRICS.set("recovery_replayed_records", report.replayed_records)
    if torn:
        METRICS.inc("wal_torn_tails_total")
        EVENTS.record(
            _STORE_REF,
            TYPE_WARNING,
            REASON_WAL_TORN_TAIL,
            "torn WAL tail truncated at the first bad CRC during replay",
        )
    EVENTS.record(
        _STORE_REF,
        TYPE_NORMAL,
        REASON_RECOVERY_COMPLETED,
        f"recovered {report.restored_objects} object(s) at rv"
        f" {report.resource_version} (snapshot rv {report.snapshot_rv},"
        f" {report.replayed_records} WAL record(s) replayed"
        f"{', torn tail' if torn else ''})",
    )
    return store, report


def verify_acked_prefix(directory: str, store) -> List[str]:
    """Audit a just-recovered store against the durable prefix on disk.

    Independent of ``recover_store``'s in-memory state: re-reads the
    snapshot + records and demands exact agreement — every acked commit
    present at its exact resourceVersion (*no acked commit lost*), no
    object the log never acked (*no phantom state*), and the store's
    version counter at or past the durable watermark (monotonicity).
    Call it BEFORE new commits land; afterwards the store legitimately
    runs ahead of the log's unflushed buffer."""
    problems: List[str] = []
    seen = set()
    shard_dirs = list_shard_dirs(directory)
    streams = shard_dirs if shard_dirs else [(None, directory)]
    if shard_dirs:
        present = [i for i, _ in shard_dirs]
        if present != list(range(getattr(store, "num_shards", 1))):
            # covers both count mismatch and a GAP in the sequence (a
            # missing stream means lost acked commits the per-stream scan
            # below could never see)
            problems.append(
                f"per-shard WAL layout mismatch: dirs {present} on disk,"
                f" store has {getattr(store, 'num_shards', 1)} shard(s)"
            )
            return problems
    for shard_idx, stream_dir in streams:
        where = "" if shard_idx is None else f" (shard {shard_idx})"
        durable_rv = 0
        for key, env in _iter_durable_state(stream_dir):
            kind, ns, name = key
            if env is None:
                continue  # durably deleted: absence is checked via `seen`
            seen.add(key)
            durable_rv = max(durable_rv, env["rv"])
            obj = store.get(kind, ns, name, readonly=True)
            if obj is None:
                problems.append(
                    f"acked commit lost: {kind} {ns}/{name} rv {env['rv']}"
                    " is durable on disk but missing from the recovered"
                    f" store{where}"
                )
            elif obj.metadata.resource_version != env["rv"]:
                problems.append(
                    f"acked commit diverged: {kind} {ns}/{name} recovered at"
                    f" rv {obj.metadata.resource_version}, durable rv is"
                    f" {env['rv']}{where}"
                )
        # monotonicity per rv sequence: one scalar for the unsharded
        # store, each shard's own watermark when sharded (the scalar sum
        # would mask a single shard's regression)
        watermark = (
            store.resource_version
            if shard_idx is None
            else store.shard_resource_version(shard_idx)
        )
        if watermark < durable_rv:
            problems.append(
                f"resourceVersion regressed{where}: store at {watermark},"
                f" durable watermark {durable_rv}"
            )
    for kind in store.kinds():
        if kind == "Event":
            continue  # fire-and-forget: outside the durability contract
        for obj in store.scan(kind):
            key = (kind, obj.metadata.namespace, obj.metadata.name)
            if key not in seen:
                problems.append(
                    f"phantom object after recovery: {kind}"
                    f" {key[1]}/{key[2]} is in the store but not in the"
                    " durable prefix"
                )
    return problems


class StoreDurability:
    """Live WAL + snapshot attachment for one Store.

    With no attachment the store is byte-identical to an undurable one
    (the subscription is the only coupling). ``pump()`` is the
    off-reconcile-path committer: flush the group-commit buffer, then
    snapshot when enough bytes accumulated since the last one. Sims call
    it at tick boundaries (deterministic); real-cluster mode runs it on
    the background committer thread."""

    def __init__(
        self,
        store,
        directory: str,
        segment_max_bytes: int = 4 * 2**20,
        snapshot_every_bytes: int = 32 * 2**20,
        lock=None,
    ) -> None:
        self.store = store
        # backref for the worker-process backend (runtime/procworkers.py):
        # the drain splits WAL stream ownership across processes and needs
        # the live attachment, which nothing else hangs off the store
        store._durability = self
        self.directory = directory
        # sharded stores (docs/control-plane.md) get one self-contained
        # WAL stream PER KEYSPACE SHARD, each subscribed to that shard's
        # fan-out (never filtering — or waiting on — another shard's
        # traffic) and writing its own shard-NNN/ subdirectory. The
        # unsharded store keeps the single WAL in `directory` itself:
        # S=1 is byte-identical on disk and over the wire.
        self.num_shards = max(1, getattr(store, "num_shards", 1))
        if self.num_shards > 1:
            self.wals = [
                WriteAheadLog(
                    os.path.join(directory, shard_dir_name(i)),
                    segment_max_bytes=segment_max_bytes,
                )
                for i in range(self.num_shards)
            ]
            for i, wal in enumerate(self.wals):
                wal.shard = i  # wall-attribution row per shard stream
                store.subscribe_system(wal.note_event, shard=i)
        else:
            self.wals = [
                WriteAheadLog(directory, segment_max_bytes=segment_max_bytes)
            ]
            store.subscribe_system(self.wals[0].note_event)
        # `wal` stays the single-stream handle (the whole pre-sharding
        # API; shard 0 when sharded — chaos knob tweaks and stats read it)
        self.wal = self.wals[0]
        self.snapshot_every_bytes = snapshot_every_bytes
        # external serialization for the snapshot's store scan (the
        # embedded apiserver's request lock in threaded real-cluster mode;
        # None in single-threaded sims)
        self._store_lock = lock
        self._flushed_at_last_snapshot = 0
        self.snapshots_taken = 0
        self._committer: Optional[threading.Thread] = None
        self._committer_stop: Optional[threading.Event] = None
        # the degradation ladder (docs/robustness.md "Gray failures"):
        # ok -> degraded (fsync latency over SLO: loud, still durable)
        # -> read-only (disk full: mutations rejected via the store's
        # error injectors, deletes still allowed — they free space).
        # Every rung transition emits a registered WalDegraded /
        # WalRecovered event; healthy stores never enter this code.
        self.degraded_mode = "ok"  # ok | degraded | read-only
        self.fsync_slo_seconds = 0.5
        self._saved_injectors: dict = {}

    # -- committer --------------------------------------------------------

    def pump(self) -> int:
        """One group-commit round: flush (fsync) the buffered batch of
        every shard stream, then snapshot + truncate when due. Returns
        records made durable.

        Worker-process backend (runtime/procworkers.py): worker
        generations are drain-scoped — each generation final-flushes the
        streams it owns and ships the watermarks back before the drain
        returns, so by the time the tick-boundary pump runs here every
        stream is local again and nothing special happens. The one
        defensive gate: if a pump ever races a live generation (a
        background committer misconfigured alongside process workers),
        remote streams no-op their flush and auto-snapshot is parked — a
        snapshot would truncate segments another process still holds a
        stale segment index into."""
        flushed = 0
        flush_failed = False
        why = ""
        for wal in self.wals:
            try:
                flushed += wal.flush()
            except OSError as exc:
                # records stay buffered in the stream (nothing acked,
                # nothing lost) — step to read-only instead of crashing
                flush_failed = True
                why = str(exc)
        if flush_failed:
            self._set_degraded_mode("read-only", why)
            return flushed
        lag = max((w.last_fsync_lag for w in self.wals), default=0.0)
        if lag > self.fsync_slo_seconds:
            # durable but SLOW (the fail-slow disk): loud rung — acks
            # still land, operators get the signal before it tips over
            self._set_degraded_mode(
                "degraded",
                f"fsync latency {lag:.3f}s over SLO"
                f" {self.fsync_slo_seconds:.3f}s",
            )
        elif self.degraded_mode != "ok":
            self._set_degraded_mode(
                "ok", "flush healthy; retained buffer drained"
            )
        drain = getattr(self.store, "_process_drain", None)
        if drain is not None and drain.active:
            return flushed
        if self.degraded_mode != "ok":
            # snapshots write to the same sick disk — park auto-snapshot
            # until the ladder steps back to ok
            return flushed
        if (
            sum(w.flushed_bytes for w in self.wals)
            - self._flushed_at_last_snapshot
            >= self.snapshot_every_bytes
        ):
            self.snapshot()
        return flushed

    # -- degradation ladder ----------------------------------------------

    _LADDER = ("ok", "degraded", "read-only")

    def _set_degraded_mode(self, mode: str, why: str) -> None:
        """One rung transition: gauge + registered event + (for the
        read-only rung) the store-side write fence. Idempotent — pump
        calls it every round; same-rung calls are free."""
        if mode == self.degraded_mode:
            return
        prev = self.degraded_mode
        self.degraded_mode = mode
        METRICS.set(
            "wal_degraded_mode", float(self._LADDER.index(mode))
        )
        if mode == "read-only":
            self._fence_writes()
        elif prev == "read-only":
            self._unfence_writes()
        if mode == "ok":
            EVENTS.record(
                _STORE_REF,
                TYPE_NORMAL,
                REASON_WAL_RECOVERED,
                f"WAL recovered from {prev}: {why}",
            )
        else:
            METRICS.inc("wal_degraded_total")
            EVENTS.record(
                _STORE_REF,
                TYPE_WARNING,
                REASON_WAL_DEGRADED,
                f"WAL {mode} (was {prev}): {why}",
            )

    def _fence_writes(self) -> None:
        """Read-only rung: reject create/update through the store's
        fault-injection seam (the one hook every write path already
        runs). Deletes stay allowed — they free the space that got us
        here, same as etcd's NOSPACE alarm semantics."""

        def _reject(_obj):
            METRICS.inc("wal_read_only_writes_rejected_total")
            return GroveError(
                ERR_CONFLICT,
                "store is read-only: WAL cannot make writes durable"
                " (disk full); retry after the disk recovers",
                "wal-read-only",
            )

        self._saved_injectors = {}
        for op in ("create", "update"):
            self._saved_injectors[op] = self.store.error_injectors.get(
                op
            )
            self.store.error_injectors[op] = _reject

    def _unfence_writes(self) -> None:
        for op, prev in self._saved_injectors.items():
            if prev is None:
                self.store.error_injectors.pop(op, None)
            else:
                self.store.error_injectors[op] = prev
        self._saved_injectors = {}

    def snapshot(self) -> str:
        """Snapshot now (scan serialized against concurrent writers when a
        store lock was provided) and truncate the covered WAL segments.
        Sharded: one snapshot per shard stream, each covering exactly its
        shard's objects at the shard's own rv watermark."""
        with self._store_lock if self._store_lock is not None else nullcontext():
            if self.num_shards > 1:
                for i, wal in enumerate(self.wals):
                    path = write_snapshot(
                        wal.directory, self.store, wal, shard=i
                    )
            else:
                path = write_snapshot(self.directory, self.store, self.wal)
            rv = self.store.resource_version
        self._flushed_at_last_snapshot = sum(
            w.flushed_bytes for w in self.wals
        )
        self.snapshots_taken += 1
        EVENTS.record(
            _STORE_REF,
            TYPE_NORMAL,
            REASON_SNAPSHOT_TAKEN,
            f"store snapshot at rv {rv}; WAL truncated",
        )
        return path

    def start_committer(self, interval_s: float = 0.05) -> None:
        """Background group-commit thread (real-cluster mode): acks flow
        to disk every ``interval_s`` without ever blocking a reconcile."""
        if self._committer is not None:
            return
        stop = threading.Event()

        def loop() -> None:
            while not stop.is_set():
                self.pump()
                stop.wait(interval_s)
            self.pump()  # final drain on clean shutdown

        self._committer_stop = stop
        self._committer = threading.Thread(
            target=loop, name="grove-wal-committer", daemon=True
        )
        self._committer.start()

    def stop_committer(self) -> None:
        if self._committer is None:
            return
        self._committer_stop.set()
        self._committer.join(timeout=5.0)
        self._committer = None
        self._committer_stop = None

    def close(self) -> None:
        self.stop_committer()
        for wal in self.wals:
            wal.close()

    # -- crash simulation -------------------------------------------------

    def simulate_crash(self, torn_tail_bytes: int = 0) -> int:
        """The store process dies: committer stops, the unflushed buffer
        is lost, and optionally a torn frame lands on disk (the write the
        crash interrupted). Returns records lost with the process."""
        # kill the WAL first: _dead turns any in-flight or final committer
        # pump into a no-op, so the thread cannot flush the buffer we are
        # about to lose (its shutdown path drains the buffer on purpose —
        # that drain models a CLEAN stop, not a crash). Sharded: every
        # stream dies with the one process; the torn frame lands on shard
        # 0's stream (always carries traffic — cluster-scoped keys pin
        # there), the others crash with clean tails.
        # worker-process backend: the whole control plane dies as one
        # failure domain — SIGKILL the worker processes FIRST so their
        # buffered (never-acked) records die with them, exactly like the
        # coordinator's own buffer below. kill_all repatriates the
        # streams (remote -> local) so the _dead marking lands on live
        # handles.
        drain = getattr(self.store, "_process_drain", None)
        if drain is not None and drain.active:
            drain.kill_all()
        lost = 0
        for i, wal in enumerate(self.wals):
            lost += wal.simulate_crash(
                torn_tail_bytes=torn_tail_bytes if i == 0 else 0
            )
        if self._committer is not None:
            self._committer_stop.set()
            self._committer.join(timeout=5.0)
            self._committer = None
            self._committer_stop = None
        return lost

    # -- stats ------------------------------------------------------------

    def stats(self) -> dict:
        # scalar durable_rv follows the store's rv merge rule (per-shard
        # watermarks sum); at S=1 both forms collapse to the legacy scalar
        return {
            "durable_rv": sum(w.durable_rv for w in self.wals),
            "flushed_records": sum(w.flushed_records for w in self.wals),
            "flushed_bytes": sum(w.flushed_bytes for w in self.wals),
            "pending_records": sum(w.pending() for w in self.wals),
            "segments_on_disk": sum(
                len(list_segments(w.directory)) for w in self.wals
            ),
            "snapshots_taken": self.snapshots_taken,
            "shards": self.num_shards,
            "degraded_mode": self.degraded_mode,
        }
