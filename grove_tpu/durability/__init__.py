"""Durability layer under the in-memory store (docs/robustness.md).

The reference operator is stateless because etcd holds every object it
owns; our ``runtime/store.py`` *is* the etcd stand-in, so this package is
its disk: an append-only, CRC-framed write-ahead log of every commit
(``wal.py``), periodic full snapshots with log truncation
(``snapshot.py``), and the crash-restart recovery path that rebuilds a
Store from disk tolerating a torn tail (``recovery.py``).

Everything is opt-in: a Store without an attached ``StoreDurability`` is
byte-identical to today's (the WAL observes commits through the same
``subscribe_system`` watch fanout every other consumer uses — zero new
code on the write path).
"""

from grove_tpu.durability.recovery import (
    RecoveryReport,
    StoreDurability,
    recover_store,
    verify_acked_prefix,
)
from grove_tpu.durability.snapshot import load_latest_snapshot, write_snapshot
from grove_tpu.durability.wal import WriteAheadLog

__all__ = [
    "RecoveryReport",
    "StoreDurability",
    "WriteAheadLog",
    "load_latest_snapshot",
    "recover_store",
    "verify_acked_prefix",
    "write_snapshot",
]
