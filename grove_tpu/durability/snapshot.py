"""Periodic full-store snapshots + WAL truncation.

A snapshot is the whole committed object population at one
resourceVersion, wire-serialized (the same envelope the WAL frames
carry) and CRC-guarded:

    [u32 crc32(body)][body]        body = JSON {"rv": N, "objects": [env...]}

Written atomically (temp file + rename) so a crash mid-snapshot leaves
the previous snapshot intact; a CRC mismatch at load time falls back to
the next-older snapshot (and ultimately to an empty base — the WAL still
replays from rv 0 in that case). After a successful snapshot every WAL
segment it covers is deleted and older snapshots are pruned: the log
stays bounded by write volume between snapshots, not by uptime.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import List, Optional, Tuple

from grove_tpu.durability.wal import WriteAheadLog, object_envelope
from grove_tpu.observability.metrics import METRICS

_CRC = struct.Struct("<I")

SNAPSHOT_PREFIX = "snapshot-"
SNAPSHOT_SUFFIX = ".snap"


def _snapshot_name(rv: int) -> str:
    return f"{SNAPSHOT_PREFIX}{rv:016d}{SNAPSHOT_SUFFIX}"


def list_snapshots(directory: str) -> List[Tuple[int, str]]:
    """(rv, absolute path) of every snapshot file, rv-ordered."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        if not (
            name.startswith(SNAPSHOT_PREFIX)
            and name.endswith(SNAPSHOT_SUFFIX)
        ):
            continue
        try:
            rv = int(name[len(SNAPSHOT_PREFIX) : -len(SNAPSHOT_SUFFIX)])
        except ValueError:
            continue
        out.append((rv, os.path.join(directory, name)))
    out.sort()
    return out


def write_snapshot(
    directory: str,
    store,
    wal: Optional[WriteAheadLog] = None,
    shard: Optional[int] = None,
) -> str:
    """Snapshot the store's committed state and truncate the WAL behind it.

    Ordering: flush + cut the WAL segment FIRST, so every record covered
    by the snapshot sits in a closed segment; then write the snapshot
    atomically; only then delete the covered segments and older
    snapshots. A crash between any two steps leaves a recoverable
    directory (at worst both the snapshot and the log cover the same
    records — replay is idempotent last-write-wins).

    With ``shard=k`` (sharded stores, docs/control-plane.md) the snapshot
    covers ONE keyspace shard — its objects via the store's per-shard
    scan, its rv watermark from the shard's own sequence — and lands in
    that shard's WAL directory: each shard's stream stays a
    self-contained single-writer WAL+snapshot pair, recovered and merged
    by ``recover_store``."""
    closed_through = wal.cut_segment() if wal is not None else -1
    objects = []
    kinds = store.kinds() if shard is None else store.shard_kinds(shard)
    for kind in kinds:
        if kind == "Event":
            # fire-and-forget Events are outside the durability contract
            # (the WAL skips them; real etcd TTLs them away) — a snapshot
            # that carried them would resurrect stale Events on recovery
            continue
        scan = (
            store.scan(kind) if shard is None else store.shard_scan(shard, kind)
        )
        for obj in scan:
            objects.append(object_envelope(obj))
    rv = (
        store.resource_version
        if shard is None
        else store.shard_resource_version(shard)
    )
    # "wal_seg": the last WAL segment this snapshot covers — replay resumes
    # at the NEXT segment. Positional, not rv-based: delete records carry
    # the deleted object's (old) resourceVersion, so an rv cut would drop
    # them and resurrect deleted objects.
    body = json.dumps(
        {"rv": rv, "wal_seg": closed_through, "objects": objects},
        separators=(",", ":"),
    ).encode("utf-8")
    path = os.path.join(directory, _snapshot_name(rv))
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(_CRC.pack(zlib.crc32(body)))
        fh.write(body)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    if wal is not None:
        wal.truncate_segments_through(closed_through)
    for old_rv, old_path in list_snapshots(directory):
        if old_rv < rv:
            try:
                os.unlink(old_path)
            except OSError:
                pass
    METRICS.inc("wal_snapshots_total")
    return path


def load_snapshot_file(path: str) -> Optional[dict]:
    """One snapshot file → {"rv", "objects"} or None when CRC-corrupt."""
    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except OSError:
        return None
    if len(data) < _CRC.size:
        return None
    (crc,) = _CRC.unpack(data[: _CRC.size])
    body = data[_CRC.size :]
    if zlib.crc32(body) != crc:
        return None
    try:
        doc = json.loads(body.decode("utf-8"))
    except ValueError:
        return None
    if not isinstance(doc, dict) or "rv" not in doc:
        return None
    return doc


def load_latest_snapshot(directory: str) -> Optional[dict]:
    """Newest CRC-valid snapshot (corrupt ones are skipped, newest first)."""
    for _rv, path in reversed(list_snapshots(directory)):
        doc = load_snapshot_file(path)
        if doc is not None:
            return doc
    return None
