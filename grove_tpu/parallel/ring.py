"""Explicit-collective (shard_map) tier of the multi-chip solver.

`parallel/sharded.py` lets GSPMD partition the kernel mechanically; this
module is the HAND-SCHEDULED counterpart for the solver's hot aggregation —
the node-axis prefix sums and per-domain boundary gathers behind every
candidate-feasibility decision (`ops/packing.py::_aggregate_tables`) —
written as explicit ring collectives over the mesh:

- a RING exclusive prefix-sum of per-shard totals (`lax.ppermute` around the
  tp axis, tp-1 hops over ICI — the same ring-pipelining shape ring
  attention uses for sequence parallelism, applied to the cluster's node
  axis), turning local cumsums into global prefix sums without ever
  materializing the full node axis on one chip;
- an owner-computes boundary gather: each shard contributes the global
  prefix values for the domain boundaries that fall inside its slab, and a
  single `lax.psum` assembles the [L, D] aggregate tables everywhere.

Per-domain aggregates then cost O(local nodes + L*D) per chip with exactly
tp-1 ppermute hops + 2 psums — communication that rides ICI neighbor links
instead of all-to-all. Kept as the reference implementation for multi-host
scale-out (DCN boundaries want explicit schedules) and parity-tested
against the host computation; on single-host meshes XLA's GSPMD partitioning
of the jit path remains the default (measured no worse for these shapes).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from grove_tpu.ops.packing import _pods_fit_per_node

# jax moved shard_map out of experimental in 0.5; this image ships 0.4.x
# where only the experimental spelling exists
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:
    from jax.experimental.shard_map import shard_map as _shard_map


def _ring_exclusive_shard_prefix(v: jnp.ndarray, axis: str, size: int):
    """Exclusive prefix sum of per-shard values around the ring: after hop s
    each device holds the value of the device s positions back; accumulate
    the hops that belong to our prefix. tp-1 neighbor ppermutes."""
    idx = jax.lax.axis_index(axis)
    acc = jnp.zeros_like(v)
    carry = v
    perm = [(j, (j + 1) % size) for j in range(size)]
    for s in range(1, size):
        carry = jax.lax.ppermute(carry, axis, perm)
        acc = acc + jnp.where(idx >= s, carry, jnp.zeros_like(carry))
    return acc


def domain_aggregates_ring(
    mesh: Mesh,
    capacity: np.ndarray,  # [N, R]
    topo: np.ndarray,  # [N, L] (unused directly; bounds encode the slabs)
    seg_starts: np.ndarray,  # [L, D]
    seg_ends: np.ndarray,  # [L, D]
    demand: np.ndarray,  # [P, R] one gang's per-pod demands
    count: np.ndarray,  # [P]
):
    """Per-level, per-domain aggregates for ONE gang against the sharded
    cluster: K[l, p, d] = pods of group p fitting in domain d of level l,
    free_agg[l, d, r] = free capacity — the feasibility tables of
    gang_select_* computed with explicit collectives.

    Returns numpy (K [L, P, D], free_agg [L, D, R]).
    """
    axis = mesh.axis_names[-1]
    size = mesh.devices.shape[-1]
    n = capacity.shape[0]
    if n % size:
        raise ValueError(f"node axis {n} not divisible by mesh size {size}")
    levels, d_max = seg_starts.shape
    p_dim = demand.shape[0]

    # flat boundary index list: starts and ends of every (level, domain)
    bounds = np.concatenate(
        [seg_starts.reshape(-1), seg_ends.reshape(-1)]
    ).astype(np.int32)  # [2*L*D]

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=(P(axis, None), P(), P(), P()),
        out_specs=(P(), P()),
    )
    def body(cap_shard, dem, cnt, bidx):
        n_local = cap_shard.shape[0]
        my_lo = jax.lax.axis_index(axis) * n_local

        # local fit counts + inclusive cumsums along the local slab
        k = jax.vmap(lambda d: _pods_fit_per_node(cap_shard, d))(dem)  # [P,nl]
        k = jnp.minimum(k, cnt[:, None]).astype(jnp.float32)
        cs_k_local = jnp.cumsum(k, axis=1)  # [P, nl] inclusive
        cs_free_local = jnp.cumsum(cap_shard, axis=0)  # [nl, R] inclusive

        # ring exclusive prefix of shard totals → global base per shard
        base_k = _ring_exclusive_shard_prefix(
            cs_k_local[:, -1], axis, size
        )  # [P]
        base_free = _ring_exclusive_shard_prefix(
            cs_free_local[-1, :], axis, size
        )  # [R]

        # owner-computes boundary gather: exclusive global prefix at global
        # index i = base + local inclusive cs[i - lo - 1] (or base at the
        # slab start); index n (the far end) is the global total, which
        # device 0 contributes as base-of-ring-total
        rel = bidx - my_lo  # [B]
        own = (rel >= 0) & (rel < n_local)
        rel_c = jnp.clip(rel - 1, 0, n_local - 1)

        def at_bounds(cs_local, base, width):
            # cs_local [*, nl] inclusive; returns [B, width]
            vals = jnp.where(
                own[:, None],
                jnp.where(
                    rel[:, None] == 0,
                    jnp.broadcast_to(base[None, :], (bidx.shape[0], width)),
                    cs_local[:, rel_c].T + base[None, :],
                ),
                0.0,
            )
            total = cs_local[:, -1] + base  # global total on the LAST shard
            is_last = jax.lax.axis_index(axis) == size - 1
            vals = vals + jnp.where(
                (bidx[:, None] == n) & is_last,
                jnp.broadcast_to(total[None, :], (bidx.shape[0], width)),
                0.0,
            )
            return jax.lax.psum(vals, axis)

        cs_k_at = at_bounds(cs_k_local, base_k, p_dim)  # [B, P]
        cs_free_at = at_bounds(cs_free_local.T, base_free, cap_shard.shape[1])
        return cs_k_at, cs_free_at

    cap_sharded = jax.device_put(
        jnp.asarray(capacity), NamedSharding(mesh, P(axis, None))
    )
    cs_k_at, cs_free_at = body(
        cap_sharded,
        jnp.asarray(demand.astype(np.float32)),
        jnp.asarray(count.astype(np.int32)),
        jnp.asarray(bounds),
    )
    cs_k_at = np.asarray(cs_k_at)  # [2LD, P]
    cs_free_at = np.asarray(cs_free_at)  # [2LD, R]
    ld = levels * d_max
    starts_k, ends_k = cs_k_at[:ld], cs_k_at[ld:]
    starts_f, ends_f = cs_free_at[:ld], cs_free_at[ld:]
    K = (ends_k - starts_k).reshape(levels, d_max, p_dim).transpose(0, 2, 1)
    free_agg = (ends_f - starts_f).reshape(levels, d_max, -1)
    return K, free_agg
