"""Multi-host solver deployment: jax.distributed over ICI + DCN.

The single-host path (`parallel/sharded.py`) shards scenarios over `dp` and
the node axis over `tp` within one process. Scaling the control plane across
HOSTS (the reference's NCCL/MPI-backend analogue, SURVEY §2.7) uses the same
code under `jax.distributed`: every host runs this module's `initialize()`,
builds the same global mesh, and feeds its shard of the scenario batch;
in-mesh collectives ride ICI within a slice and DCN across slices — XLA picks
the transport per mesh axis exactly as for training workloads.

This box has one chip, so the multi-host path is exercised as N processes ×
1 virtual device via `spawn_local_cluster` (tests) — the same code path that
runs on a real multi-host TPU pod slice.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the distributed runtime. Arguments default to the standard
    JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID env vars
    (auto-populated on GKE TPU slices)."""
    # CPU multiprocess needs an explicit collectives backend: jax's default
    # is 'none' and the first cross-process collective then dies with
    # "Multiprocess computations aren't implemented on the CPU backend".
    # Earlier images exported JAX_CPU_COLLECTIVES_IMPLEMENTATION=gloo;
    # don't depend on the ambient env for correctness — pin it here,
    # BEFORE the backend client is created (env override still wins).
    if os.environ.get("JAX_PLATFORMS", "") in ("", "cpu") and not os.environ.get(
        "JAX_CPU_COLLECTIVES_IMPLEMENTATION"
    ):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):  # pragma: no cover — older jax
            pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address
        or os.environ.get("JAX_COORDINATOR_ADDRESS"),
        num_processes=num_processes
        or int(os.environ.get("JAX_NUM_PROCESSES", "0") or 0) or None,
        process_id=process_id
        if process_id is not None
        else (
            int(os.environ["JAX_PROCESS_ID"])
            if "JAX_PROCESS_ID" in os.environ
            else None
        ),
    )


def global_solver_mesh():
    """The (dp, tp) mesh over ALL processes' devices — identical call on
    every host after initialize()."""
    from grove_tpu.parallel.sharded import make_solver_mesh

    return make_solver_mesh(len(jax.devices()))


# ---------------------------------------------------------------------------
# local multi-process harness (tests / CI without a real pod slice)
# ---------------------------------------------------------------------------

_WORKER_SNIPPET = """
import os
import numpy as np
import jax
from grove_tpu.parallel import multihost
multihost.initialize(
    coordinator_address=os.environ["COORD"],
    num_processes=int(os.environ["NPROC"]),
    process_id=int(os.environ["PID_IDX"]),
)
mesh = multihost.global_solver_mesh()
assert mesh.devices.size == int(os.environ["NPROC"]), mesh
import jax.numpy as jnp
from jax.experimental import multihost_utils
# one cross-process collective proves the DCN-analogue transport works
x = jnp.ones((4,)) * (int(os.environ["PID_IDX"]) + 1)
gathered = multihost_utils.process_allgather(x)
assert gathered.shape[0] == int(os.environ["NPROC"]), gathered.shape

# the flagship path across PROCESS boundaries: one placement problem whose
# node axis is sharded over every process's devices (every process feeds
# the same global arrays; XLA partitions the wave loop over the mesh) —
# admissions must be bit-identical to this process's local single-device
# solve, proving sharding never changes semantics across hosts either
from jax.sharding import Mesh
from grove_tpu.models import build_stress_problem
from grove_tpu.parallel.sharded import solve_stress_sharded
n_nodes = int(os.environ.get("SHAPE_NODES", "0")) or 16 * mesh.devices.size
n_gangs = int(os.environ.get("SHAPE_GANGS", "0")) or 32
problem = build_stress_problem(n_nodes, n_gangs)
sharded = solve_stress_sharded(mesh, problem, chunk_size=16, max_waves=8)
local_mesh = Mesh(
    np.array(jax.local_devices()[:1]).reshape(1, 1), ("dp", "tp")
)
local = solve_stress_sharded(local_mesh, problem, chunk_size=16, max_waves=8)
assert sharded["admitted"].any(), "cross-process solve placed nothing"
np.testing.assert_array_equal(sharded["admitted"], local["admitted"])
np.testing.assert_array_equal(sharded["placed"], local["placed"])
print("MULTIHOST_OK", mesh.axis_names, tuple(mesh.devices.shape),
      int(sharded["admitted"].sum()), "/", len(sharded["admitted"]))
"""


def spawn_local_cluster(
    num_processes: int = 2,
    port: int = 12765,
    n_nodes: int = 0,
    n_gangs: int = 0,
    timeout: float = 120.0,
) -> bool:
    """Spawn N single-device CPU processes that form one distributed mesh.
    Returns True when every worker reports the global mesh. ``n_nodes``/
    ``n_gangs`` override the worker's solve shape (0 = tiny default)."""
    import pathlib
    import subprocess
    import sys

    from grove_tpu.utils.platform import cpu_subprocess_env

    repo = pathlib.Path(__file__).resolve().parents[2]
    procs = []
    try:
        for pid in range(num_processes):
            env = cpu_subprocess_env(n_devices=None)  # one device per process
            env.update(
                COORD=f"127.0.0.1:{port}",
                NPROC=str(num_processes),
                PID_IDX=str(pid),
                SHAPE_NODES=str(n_nodes),
                SHAPE_GANGS=str(n_gangs),
            )
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-c", _WORKER_SNIPPET],
                    env=env,
                    cwd=repo,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )
        ok = True
        for proc in procs:
            try:
                out, _ = proc.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                ok = False
                continue
            if proc.returncode != 0 or "MULTIHOST_OK" not in out:
                ok = False
                print(out)
        return ok
    finally:
        # never leak workers (a hung peer would hold the coordinator port
        # and wedge every subsequent run)
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
