"""Multi-chip sharded solve: device-mesh parallelism for the placement kernel.

The framework's "model" is the packing solver; its two parallelizable axes map
onto a 2-D device mesh exactly like data/tensor parallelism in a training
stack (jax-ml.github.io/scaling-book recipe: pick a mesh, annotate shardings,
let XLA GSPMD insert the collectives over ICI):

- ``dp`` — scenario/data parallelism: independent placement problems (e.g.
  per-cluster or per-namespace scheduling domains, or what-if simulations)
  batched on the leading axis; zero communication between them.
- ``tp`` — cluster-tensor parallelism: the NODE axis is sharded, so each chip
  holds a slab of the cluster's capacity/topology tensors. Prefix sums,
  boundary gathers, and reductions over nodes become XLA-partitioned ops with
  collective-permutes/all-reduces over ICI.

This module uses jit + NamedSharding (GSPMD) rather than hand-written
shard_map collectives: the kernel's math (cumsum / gather / argmin over the
node axis) partitions mechanically, and XLA's choices beat hand-rolled
psum/ppermute schedules for these shapes.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from grove_tpu.ops.packing import solve_packing


def make_solver_mesh(n_devices: Optional[int] = None) -> Mesh:
    """2-D (dp, tp) mesh over the available devices."""
    devices = jax.devices()
    n = n_devices or len(devices)
    dp = 1
    for cand in (4, 2):
        if n % cand == 0 and n >= cand * 2:
            dp = cand
            break
    tp = n // dp
    mesh_devices = mesh_utils.create_device_mesh((dp, tp), devices[:n])
    return Mesh(mesh_devices, ("dp", "tp"))


def make_node_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-axis node-sharding mesh over ALL requested devices.

    The single-problem stress solve has exactly one shardable tensor axis
    (nodes), so every device goes on one ``tp`` axis — 8-way at the bench
    shape, not the 2-way slice the (dp=4, tp=2) solver mesh used to give
    it. The 1-axis shape is also a CORRECTNESS requirement on this image's
    XLA rev: under a mesh with an idle axis, the partitioner's
    partial-replication bookkeeping miscompiles the kernel's node-axis
    prefix sums — every element comes back multiplied by the idle axis
    size (dp=4), which is what drove the sharded-vs-single-device
    alloc/score divergence (PARITY.md). With no idle axis there is nothing
    to mis-account, and the wave loop is bit-identical to the
    single-device run (tests/test_solver.py::TestMultiChip)."""
    devices = jax.devices()
    n = n_devices or len(devices)
    return Mesh(
        mesh_utils.create_device_mesh((n,), devices[:n]), ("tp",)
    )


def _as_node_mesh(mesh: Mesh) -> Mesh:
    """Flatten any mesh into the 1-axis node mesh over the same devices
    (same order), so callers holding a (dp, tp) solver mesh — every
    pre-existing entry point — get the idle-axis-free shape the stress
    solve requires (see make_node_mesh)."""
    if len(mesh.axis_names) == 1 and mesh.axis_names[0] == "tp":
        return mesh
    return Mesh(mesh.devices.reshape(-1), ("tp",))


def batch_solve_sharded(
    mesh: Mesh,
    capacity: np.ndarray,  # [S, N, R] — S scenarios
    topo: np.ndarray,  # [S, N, L]
    seg_starts: np.ndarray,  # [S, L, D]
    seg_ends: np.ndarray,  # [S, L, D]
    demand: np.ndarray,  # [S, G, P, R]
    count: np.ndarray,  # [S, G, P]
    min_count: np.ndarray,  # [S, G, P]
    req_level: np.ndarray,  # [S, G]
    pref_level: np.ndarray,  # [S, G]
):
    """Solve S independent placement scenarios across the mesh: scenarios
    sharded over ``dp``, each scenario's node axis sharded over ``tp``."""

    def shard(spec: P):
        return NamedSharding(mesh, spec)

    in_shardings = (
        shard(P("dp", "tp", None)),  # capacity
        shard(P("dp", "tp", None)),  # topo
        shard(P("dp", None, None)),  # seg_starts (small, replicated over tp)
        shard(P("dp", None, None)),  # seg_ends
        shard(P("dp", None, None, None)),  # demand
        shard(P("dp", None, None)),  # count
        shard(P("dp", None, None)),  # min_count
        shard(P("dp", None)),  # req_level
        shard(P("dp", None)),  # pref_level
    )

    @jax.jit
    def run(cap, tp_, ss, se, dem, cnt, mn, rq, pf):
        return jax.vmap(
            lambda *xs: solve_packing(*xs, with_alloc=False)
        )(cap, tp_, ss, se, dem, cnt, mn, rq, pf)

    args = [
        jax.device_put(jnp.asarray(a), s)
        for a, s in zip(
            (
                capacity,
                topo,
                seg_starts,
                seg_ends,
                demand,
                count,
                min_count,
                req_level,
                pref_level,
            ),
            in_shardings,
        )
    ]
    out = run(*args)
    return {k: np.asarray(v) for k, v in out.items() if v is not None}


def solve_stress_sharded(
    mesh: Mesh,
    problem,
    chunk_size: int = 128,
    max_waves: int = 32,
):
    """ONE large placement problem with the NODE axis sharded across EVERY
    device of the mesh — the flagship multi-chip path: each chip holds a
    slab of the 5k-node cluster's capacity/topology tensors and the whole
    device-resident wave loop (lax.while_loop over chunked vmap+commit
    waves) runs under GSPMD, with XLA inserting the ICI collectives for
    the node-axis prefix sums, boundary gathers, and reductions.

    The given mesh is flattened to the 1-axis node mesh over the same
    devices (``_as_node_mesh``): an idle mesh axis miscompiles the
    node-axis prefix sums on this XLA rev, and the single tensor axis
    wants all the chips anyway (8-way at the bench shape).

    Deterministic: admissions, allocations (placed), score, and free_after
    are all BIT-identical to the single-device solve_waves_device run at
    matched wave budget (tests/test_solver.py::TestMultiChip), so sharding
    is purely a throughput/memory choice, never a semantics one — the
    kernel's prefix sums use the fixed-association segmented scan
    (ops.packing._seg_cumsum) whose per-shard reduce no mesh shape can
    reassociate.
    """
    from grove_tpu.ops.packing import solve_waves_device
    from grove_tpu.solver.kernel import (
        dedup_extra_args,
        level_widths_of,
        pad_problem_for_waves,
    )

    mesh = _as_node_mesh(mesh)
    g = problem.num_gangs
    raw_args, n_chunks, grouped, pinned, spread, uniform = (
        pad_problem_for_waves(problem, chunk_size)
    )
    node_sh = NamedSharding(mesh, P("tp", None))
    rep = NamedSharding(mesh, P())
    # capacity and topo carry the node axis (sharded); everything else
    # (domain bounds + gang tensors) is replicated
    shardings = (node_sh, node_sh) + (rep,) * (len(raw_args) - 2)
    placed = [
        jax.device_put(jnp.asarray(a), s)
        for a, s in zip(raw_args, shardings)
    ]
    # demand dedup (exact — admissions stay bit-identical, see kernel.py);
    # the shared capped-fit table carries the node axis so its cumsum and
    # boundary gathers shard/communicate exactly like capacity's
    extra = dedup_extra_args(
        raw_args[4], raw_args[5], n_chunks, pinned,
        place=lambda a: jax.device_put(jnp.asarray(a), rep),
    )
    with mesh:
        out = solve_waves_device(
            *placed,
            **extra,
            n_chunks=n_chunks,
            max_waves=max_waves,
            grouped=grouped,
            pinned=pinned,
            spread=spread,
            uniform=uniform,
            lazy_rescue=uniform,
            # ragged candidate scan (same bit-exact win as the single-chip
            # path); the narrow levels' bounds are replicated scalars-wise,
            # so the slicing doesn't change the node-axis sharding story
            level_widths=level_widths_of(problem),
        )

    if jax.process_count() > 1:
        # outputs may span devices owned by OTHER processes (multi-host
        # mesh): reshard the whole output pytree to fully-replicated in ONE
        # program, then read the local replica of each leaf
        replicated = jax.jit(
            lambda t: t, out_shardings=NamedSharding(mesh, P())
        )(out)
        fetch = lambda x: np.asarray(x.addressable_data(0))
        out = {k: replicated[k] for k in out}
    else:
        fetch = np.asarray
    return {
        "admitted": fetch(out["admitted"])[:g],
        "placed": fetch(out["placed"])[:g],
        "score": fetch(out["score"])[:g],
        "chosen_level": fetch(out["chosen_level"])[:g],
        "free_after": fetch(out["free_after"]),
        "pending": fetch(out["pending"])[:g],
        "waves": int(fetch(out["waves"])),
    }


def make_example_batch(
    n_scenarios: int, n_nodes: int = 32, n_gangs: int = 16
) -> Tuple[np.ndarray, ...]:
    """Tiny stacked scenario batch for dry runs/tests."""
    from grove_tpu.api.topology import ClusterTopology
    from grove_tpu.sim.cluster import make_nodes
    from grove_tpu.solver.encode import build_problem

    rng = np.random.default_rng(0)
    problems = []
    for s in range(n_scenarios):
        nodes = make_nodes(n_nodes, capacity={"cpu": 8.0, "tpu": 4.0})
        gangs = []
        for i in range(n_gangs):
            gangs.append(
                {
                    "name": f"s{s}-g{i}",
                    "groups": [
                        {
                            "name": f"s{s}-g{i}-a",
                            "demand": {"tpu": float(rng.integers(1, 3))},
                            "count": int(rng.integers(1, 4)),
                            "min_count": int(rng.integers(1, 2)),
                        }
                    ],
                    "required_key": None,
                    "preferred_key": None,
                    "priority": 0,
                }
            )
        problems.append(build_problem(nodes, gangs, ClusterTopology()))
    stack = lambda attr: np.stack([getattr(p, attr) for p in problems])
    return (
        stack("capacity"),
        stack("topo"),
        stack("seg_starts"),
        stack("seg_ends"),
        stack("demand"),
        stack("count"),
        stack("min_count"),
        stack("req_level"),
        stack("pref_level"),
    )
