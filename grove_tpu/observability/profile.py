"""Wall-attribution profiler: where a control-plane second actually goes.

The tracer (tracing.py) answers "how long did span X take"; the sampling
profiler (apiserver ``/debug/profile?seconds=N``) answers "which frames
are hot right now". Neither can answer the ROADMAP's question — *of the
964 s the control plane burned converging the 100k-node shape, how many
went to dequeue vs reconcile compute vs store commits vs status writes
vs WAL fsync, per controller, per keyspace shard?* — because spans are
bounded samples and stack sampling has no phase semantics.

This module is the ledger for that question:

- ``PROFILER.phase(name)`` opens a *phase* — a timed interval attributed
  to a ``(controller, shard, phase)`` key. Phases nest via a per-thread
  stack and account **exclusive (self) time**: when a child phase opens,
  the parent stops accumulating, so the sum of all recorded self-times
  equals the wall of the outermost phases (no double counting). That is
  what makes the roll-up's coverage claim honest: *attributed seconds /
  independently measured wall ≥ 0.95* is arithmetic, not hope.
- Self-times fold into **log-bucketed online histograms** (power-of-two
  µs buckets, 64 of them): O(1) memory per key no matter how many
  reconciles run, with p50/p99 read back by bucket interpolation.
- Context flows down the stack: a phase opened with an explicit
  ``controller``/``shard`` (the engine's per-reconcile phase, the
  scheduler's round phase) re-keys every descendant phase, so a store
  commit inside a PodClique reconcile on shard 3 lands under
  ``(podclique, 3, store-commit)`` without the store knowing either.

Cost model, same discipline as the tracer (PR 1): **off by default**,
every instrumentation site reduces to one ``PROFILER.enabled`` boolean
check (``phase()`` is only called when enabled, or returns the shared
no-op). Enable with ``GROVE_TPU_PROFILE=1`` or ``PROFILER.enable()``.
Surfaced at ``GET /debug/profile``, ``cli profile``, the bench
``"attribution"`` block, and ``make profile-smoke``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

# Canonical phase names — the closed registry tests/test_docs_drift.py
# pins against the docs/observability.md "Profiler phases" table (the
# event-reason treatment, applied to phases). grovelint GL015 keeps the
# recording state itself private to this module.
PHASE_DRAIN = "drain"  # engine drain loop (self = pop/route glue)
PHASE_DEQUEUE = "dequeue"  # watch-event routing into workqueues
PHASE_RECONCILE = "reconcile"  # one reconcile (self = controller compute/diff)
PHASE_SNAPSHOT = "snapshot"  # store reads (get/list) under the open phase
PHASE_STORE_COMMIT = "store-commit"  # store writes (create/update/delete/cow)
PHASE_STATUS_WRITE = "status-write"  # status-subtree copy-on-write commits
PHASE_SCHEDULE = "schedule"  # one scheduler round (self = ordering/quota glue)
PHASE_PENDING_SCAN = "pending-scan"  # phase/health upkeep + pending encode
PHASE_ENCODE = "encode"  # problem assembly (from-scratch or delta)
PHASE_SOLVE = "solve"  # wave solve incl. device dispatch (or sidecar call)
PHASE_COMMIT = "commit"  # binding admitted gangs' pods
PHASE_TICK = "tick"  # one component tick (autoscaler/monitor/drainer/kubelet)
PHASE_WAL_FLUSH = "wal-flush"  # one WAL group commit (encode+write+fsync)

PHASES = frozenset(
    v
    for k, v in list(globals().items())
    if k.startswith("PHASE_") and isinstance(v, str)
)

# shard index meaning "not shard-scoped work" (cluster-wide / unsharded)
NO_SHARD = -1

_NBUCKETS = 64


class _Hist:
    """One (controller, shard, phase) key's online histogram: power-of-two
    µs buckets + exact count/total/max. Bounded and mergeable — the report
    is O(keys), never O(samples)."""

    __slots__ = ("counts", "count", "total_us", "max_us")

    def __init__(self) -> None:
        self.counts = [0] * _NBUCKETS
        self.count = 0
        self.total_us = 0
        self.max_us = 0

    def add(self, us: int) -> None:
        idx = us.bit_length()
        if idx >= _NBUCKETS:
            idx = _NBUCKETS - 1
        self.counts[idx] += 1
        self.count += 1
        self.total_us += us
        if us > self.max_us:
            self.max_us = us

    def quantile_us(self, q: float) -> float:
        """Bucket-interpolated quantile: the value is estimated at the
        geometric midpoint of the bucket holding the q-th sample (bucket b
        spans [2^(b-1), 2^b) µs), so the error is bounded by the bucket
        width — the price of O(1) memory."""
        if self.count == 0:
            return 0.0
        target = max(1, int(q * self.count + 0.5))
        seen = 0
        for b, n in enumerate(self.counts):
            seen += n
            if seen >= target:
                if b == 0:
                    return 0.5
                return 1.5 * (1 << (b - 1))
        return float(self.max_us)


class _NullPhase:
    """Shared no-op phase (the disabled path's `with` target)."""

    __slots__ = ()

    def __enter__(self) -> "_NullPhase":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def end(self) -> None:
        pass


_NULL_PHASE = _NullPhase()


class _Phase:
    __slots__ = ("_prof", "key", "_t0", "_child", "_prev_ctx", "_done")

    def __init__(
        self,
        prof: "WallProfiler",
        key: Tuple[str, int, str],
        prev_ctx: Optional[Tuple[str, int]],
    ) -> None:
        self._prof = prof
        self.key = key
        self._prev_ctx = prev_ctx  # restored on end() when ctx was re-keyed
        self._child = 0.0
        self._done = False
        self._t0 = time.perf_counter()

    def end(self) -> None:
        if self._done:
            return
        self._done = True
        dur = time.perf_counter() - self._t0
        prof = self._prof
        tls = prof._tls
        stack = tls.stack
        # tolerate out-of-order ends (a parent ended from a finally after a
        # leaked child) — drop self from wherever it sits
        if self in stack:
            stack.remove(self)
        if stack:
            stack[-1]._child += dur
        else:
            prof._note_toplevel(dur)
        if self._prev_ctx is not None:
            tls.ctx = self._prev_ctx
        self_s = dur - self._child
        if self_s < 0.0:
            self_s = 0.0
        prof._record(self.key, self_s)

    def __enter__(self) -> "_Phase":
        return self

    def __exit__(self, *exc) -> bool:
        self.end()
        return False


class WallProfiler:
    """Process-global (``PROFILER``), thread-safe: histogram updates are
    locked (drain_concurrent and the parallel control plane's per-shard
    workers — runtime/workers.py — run reconciles on worker threads), the
    phase stack and attribution context are thread-local, so each
    worker's reconcile phases attribute independently. Under concurrent
    workers the summed self-times may legitimately EXCEED the measured
    wall (lanes overlap); the scale block's per-worker utilization
    (``attribution.by_worker``) groups shard-scoped rows by the
    shard → worker map, where each single worker's share stays ≤ 1."""

    def __init__(self) -> None:
        self.enabled = os.environ.get("GROVE_TPU_PROFILE", "") not in (
            "",
            "0",
            "false",
        )
        self._lock = threading.Lock()
        self._hist: Dict[Tuple[str, int, str], _Hist] = {}
        self._toplevel_s = 0.0  # wall covered by outermost phases
        self._tls = threading.local()

    # -- lifecycle -------------------------------------------------------

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._hist = {}
            self._toplevel_s = 0.0

    # -- recording -------------------------------------------------------

    def _state(self):
        tls = self._tls
        if getattr(tls, "stack", None) is None:
            tls.stack = []
            tls.ctx = ("-", NO_SHARD)
        return tls

    def phase(
        self,
        name: str,
        controller: Optional[str] = None,
        shard: Optional[int] = None,
    ):
        """Open a phase (context manager, or call ``.end()`` explicitly).
        ``controller``/``shard`` default to the enclosing phase's context;
        passing either re-keys the context for every descendant phase until
        this one ends. The disabled path is ONE attribute check at the call
        site (``if PROFILER.enabled``) — or this early return."""
        if not self.enabled:
            return _NULL_PHASE
        tls = self._state()
        ctx = tls.ctx
        prev = None
        if controller is not None or shard is not None:
            new_ctx = (
                controller if controller is not None else ctx[0],
                shard if shard is not None else ctx[1],
            )
            prev, tls.ctx, ctx = ctx, new_ctx, new_ctx
        ph = _Phase(self, (ctx[0], ctx[1], name), prev)
        tls.stack.append(ph)
        return ph

    def reconcile(self, controller: str, shard: int = NO_SHARD):
        """The engine's per-reconcile phase: re-keys the context so every
        store read/write inside the reconcile attributes to this
        (controller, shard)."""
        return self.phase(PHASE_RECONCILE, controller=controller, shard=shard)

    def _record(self, key: Tuple[str, int, str], self_s: float) -> None:
        us = int(self_s * 1e6)
        with self._lock:
            hist = self._hist.get(key)
            if hist is None:
                hist = self._hist[key] = _Hist()
            hist.add(us)

    def _note_toplevel(self, dur: float) -> None:
        with self._lock:
            self._toplevel_s += dur

    # -- report ----------------------------------------------------------

    def attributed_seconds(self) -> float:
        """Sum of every recorded self-time — the numerator of coverage."""
        with self._lock:
            return sum(h.total_us for h in self._hist.values()) / 1e6

    def covered_wall_seconds(self) -> float:
        """Wall covered by outermost phases (the profiler's own notion of
        the window; the smoke compares against an independent timer)."""
        with self._lock:
            return self._toplevel_s

    def report(
        self, wall_seconds: Optional[float] = None, top: Optional[int] = None
    ) -> dict:
        """The roll-up: per-(controller, shard, phase) rows sorted by total
        self-time, per-controller totals, and — when the caller provides an
        independently measured wall — the coverage ratio the acceptance
        gate reads (``attributed_seconds / wall_seconds``)."""
        with self._lock:
            items = [
                (key, h.count, h.total_us, h.quantile_us(0.5),
                 h.quantile_us(0.99), h.max_us)
                for key, h in self._hist.items()
            ]
            toplevel = self._toplevel_s
        items.sort(key=lambda row: -row[2])
        phases: List[dict] = []
        by_controller: Dict[str, float] = {}
        attributed_us = 0
        for (controller, shard, name), count, total_us, p50, p99, mx in items:
            attributed_us += total_us
            by_controller[controller] = (
                by_controller.get(controller, 0.0) + total_us / 1e6
            )
            phases.append(
                {
                    "controller": controller,
                    "shard": shard,
                    "phase": name,
                    "count": count,
                    "total_s": round(total_us / 1e6, 6),
                    "p50_s": round(p50 / 1e6, 9),
                    "p99_s": round(p99 / 1e6, 9),
                    "max_s": round(mx / 1e6, 6),
                }
            )
        if top is not None:
            phases = phases[:top]
        doc = {
            "enabled": self.enabled,
            "attributed_seconds": round(attributed_us / 1e6, 6),
            "covered_wall_seconds": round(toplevel, 6),
            "by_controller": {
                c: round(s, 6) for c, s in sorted(by_controller.items())
            },
            "phases": phases,
        }
        if wall_seconds is not None:
            doc["wall_seconds"] = round(wall_seconds, 6)
            doc["coverage"] = round(
                attributed_us / 1e6 / wall_seconds, 4
            ) if wall_seconds > 0 else 0.0
        return doc


def disabled_check_cost_ns(iters: int = 200_000) -> float:
    """Measured cost of ONE all-off instrumentation check — the exact
    boolean pattern every hot site pays while tracing/profiling/journeys/
    flight-recording are disabled. Feeds the bench's all-off-overhead
    estimate (checks × this ÷ measured wall), so the <1% claim is
    arithmetic over measured quantities."""
    from grove_tpu.observability.flightrec import FLIGHTREC
    from grove_tpu.observability.journey import JOURNEYS
    from grove_tpu.observability.tracing import TRACER

    t0 = time.perf_counter()
    for _ in range(iters):
        if (
            TRACER.enabled
            or PROFILER.enabled
            or JOURNEYS.enabled
            or FLIGHTREC.enabled
        ):  # pragma: no cover - all-off microbench
            pass
    return (time.perf_counter() - t0) / iters * 1e9


PROFILER = WallProfiler()
