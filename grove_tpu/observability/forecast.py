"""Seeded diurnal+trend forecaster over the time-series ring.

The SLO observatory (PR 14) remembers and judges; this module looks
FORWARD: per-series horizon predictions with confidence bands, so the
remediation controller (controller/remediate.py) can act *ahead* of the
forecast diurnal peak instead of after the burn alert. The model is
deliberately small and exactly reproducible — a pure function of the
ring's per-tick gauge samples, no wall clock, no RNG (GL001 strict
scope): seeded storms replay bit-identically, and every reduction is
pinned BIT-equal to a plain-NumPy oracle (tests/test_remediation.py),
ring wraparound and sparse/empty windows included.

Per forecast over one gauge series:

- **trend** — ordinary least squares over the ``(tick, value)`` samples
  of the training window (closed-form sums, float64);
- **diurnal** — trend residuals binned by phase (``tick mod period``,
  ``N_PHASE_BINS`` bins); the seasonal component is the per-bin mean
  residual (empty bins contribute zero);
- **bands** — residual std after seasonal removal, bands at
  ``mean ± BAND_Z·sigma``;
- **skill** — walk the training window at the horizon lag: the model's
  fitted MAE vs the persistence baseline's lag-``horizon`` MAE over the
  SAME sample subset. ``skill = persistence_mae - mae`` (positive ⇒ the
  forecast beats naive) is fed back into the ring as the first-class
  series ``forecast_skill/<name>`` so the bench can gate "forecasts beat
  naive" through the same oracle-pinned reducers.

Fewer than ``MIN_SAMPLES`` samples degrade to a flat persistence model
(``model: "persistence"``, no skill verdict); an empty window returns an
``n: 0`` shell. Surfaced at ``GET /debug/forecast`` + ``cli forecast``.
Off by default (``GROVE_TPU_FORECAST=1`` / ``FORECASTER.enable()``),
one-boolean-check discipline; fit internals are private to this module
(grovelint GL019).
"""

from __future__ import annotations

import os
from bisect import bisect_right
from typing import Dict, List, Optional, Tuple

import numpy as np

from grove_tpu.observability.metrics import METRICS
from grove_tpu.observability.timeseries import TIMESERIES

# First-class forecast series (the skill feed): gauge
# `forecast_skill/<series>` holds persistence_mae - model_mae per scoring
# round — positive means the model beats the naive baseline.
SERIES_FORECAST_SKILL = "forecast_skill"

DEFAULT_PERIOD = 600.0  # seconds; matches the traffic model's diurnal
DEFAULT_HORIZON = 300.0  # seconds of look-ahead
DEFAULT_HISTORY = 1800.0  # training window (3 diurnal periods)
N_PHASE_BINS = 48  # phase bins per period (clamped to period ticks)
N_POINTS = 12  # emitted prediction points across the horizon
BAND_Z = 2.0  # confidence band half-width in residual stds
MIN_SAMPLES = 8  # below this, degrade to flat persistence


def _fit(
    ticks: List[int], vals: np.ndarray, period_ticks: int
) -> Tuple[float, float, np.ndarray, int, float]:
    """Closed-form trend + seasonal fit: returns ``(intercept, slope,
    seasonal_bins, n_bins, sigma)``. All arithmetic is float64 in a fixed
    order — the NumPy oracle reproduces it term for term."""
    x = np.asarray(ticks, dtype=np.float64)
    n = float(x.size)
    sx = float(x.sum())
    sy = float(vals.sum())
    sxx = float((x * x).sum())
    sxy = float((x * vals).sum())
    denom = n * sxx - sx * sx
    slope = (n * sxy - sx * sy) / denom if denom != 0.0 else 0.0
    intercept = (sy - slope * sx) / n
    resid = vals - (intercept + slope * x)
    n_bins = min(N_PHASE_BINS, period_ticks)
    bins = np.asarray(
        [(t % period_ticks) * n_bins // period_ticks for t in ticks],
        dtype=np.int64,
    )
    seasonal = np.zeros(n_bins, dtype=np.float64)
    for b in range(n_bins):
        mask = bins == b
        cnt = int(mask.sum())
        if cnt:
            seasonal[b] = float(resid[mask].sum()) / cnt
    adj = resid - seasonal[bins]
    sigma = float(np.sqrt((adj * adj).sum() / n))
    return intercept, slope, seasonal, n_bins, sigma


def _phase_bin(tick: int, period_ticks: int, n_bins: int) -> int:
    return (tick % period_ticks) * n_bins // period_ticks


class Forecaster:
    """Process-global (``FORECASTER``), off by default. Holds only the
    model configuration and the watched-series set; every forecast is
    recomputed from the ring on demand — no fitted state survives between
    calls, so there is nothing to drift or to invalidate."""

    def __init__(self) -> None:
        self.enabled = os.environ.get("GROVE_TPU_FORECAST", "") not in (
            "",
            "0",
            "false",
        )
        self.clock = None
        self.period = DEFAULT_PERIOD
        self.horizon = DEFAULT_HORIZON
        self.history = DEFAULT_HISTORY
        self._watched: List[str] = []

    # -- lifecycle -------------------------------------------------------

    def enable(
        self,
        clock=None,
        period: Optional[float] = None,
        horizon: Optional[float] = None,
        history: Optional[float] = None,
    ) -> "Forecaster":
        if clock is not None:
            self.clock = clock
        if period is not None:
            self.period = float(period)
        if horizon is not None:
            self.horizon = float(horizon)
        if history is not None:
            self.history = float(history)
        self.enabled = True
        return self

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        self._watched = []
        self.clock = None
        self.period = DEFAULT_PERIOD
        self.horizon = DEFAULT_HORIZON
        self.history = DEFAULT_HISTORY

    def watch(self, name: str) -> None:
        """Register a series for the default ``report()`` sweep."""
        if name not in self._watched:
            self._watched.append(name)

    def watched(self) -> List[str]:
        return list(self._watched)

    # -- time ------------------------------------------------------------

    def _now(self, now: Optional[float]) -> float:
        if now is not None:
            return now
        clock = self.clock if self.clock is not None else TIMESERIES.clock
        return clock.now() if clock is not None else 0.0

    # -- the forecast ----------------------------------------------------

    def forecast(
        self,
        name: str,
        horizon: Optional[float] = None,
        now: Optional[float] = None,
        feed: bool = False,
    ) -> dict:
        """One series' horizon forecast. ``feed=True`` records the skill
        verdict into the ring (the remediator's per-tick scoring call);
        read surfaces (apiserver/cli) leave the ring untouched."""
        vt = self._now(now)
        horizon_s = float(horizon if horizon is not None else self.horizon)
        res = TIMESERIES.resolution
        samples = TIMESERIES.gauge_samples(name, self.history, now=vt)
        doc: dict = {
            "series": name,
            "n": len(samples),
            "now": vt,
            "horizon_s": horizon_s,
            "period_s": self.period,
        }
        METRICS.inc("forecast_evaluations_total")
        if not samples:
            doc["model"] = "absent"
            return doc
        ticks = [t for t, _ in samples]
        vals = np.asarray([v for _, v in samples], dtype=np.float64)
        t1 = TIMESERIES.tick_of(vt)
        period_ticks = max(2, int(round(self.period / res)))
        horizon_ticks = max(1, int(round(horizon_s / res)))
        last = float(vals[-1])
        if len(samples) < MIN_SAMPLES:
            # too sparse to fit: flat persistence with a dispersion band
            mean_v = float(vals.sum()) / vals.size
            dev = vals - mean_v
            sigma = float(np.sqrt((dev * dev).sum() / vals.size))
            intercept, slope = last, 0.0
            seasonal = np.zeros(1, dtype=np.float64)
            n_bins = 1
            doc["model"] = "persistence"
            predict_from = 0.0  # slope*tick term vanishes; flat at last
        else:
            intercept, slope, seasonal, n_bins, sigma = _fit(
                ticks, vals, period_ticks
            )
            doc["model"] = "diurnal-trend"
            predict_from = 1.0
        doc.update(
            {
                "last": last,
                "slope_per_s": slope / res,
                "sigma": sigma,
            }
        )
        # prediction points across (t1, t1 + horizon]
        step = max(1, horizon_ticks // N_POINTS)
        points = []
        peak = None
        for tf in range(t1 + step, t1 + horizon_ticks + 1, step):
            if predict_from:
                mean = (
                    intercept
                    + slope * float(tf)
                    + float(seasonal[_phase_bin(tf, period_ticks, n_bins)])
                )
            else:
                mean = last
            row = {
                "at_s": tf * res,
                "mean": mean,
                "lo": mean - BAND_Z * sigma,
                "hi": mean + BAND_Z * sigma,
            }
            points.append(row)
            if peak is None or mean > peak["mean"]:
                peak = {"at_s": row["at_s"], "mean": mean}
        doc["points"] = points
        doc["peak"] = peak
        # skill: fitted MAE vs persistence lag-horizon MAE over the same
        # subset (samples with a lag-`horizon` predecessor in the window)
        if doc["model"] == "diurnal-trend":
            pairs_i = []
            pairs_j = []
            for i, t in enumerate(ticks):
                j = bisect_right(ticks, t - horizon_ticks) - 1
                if j >= 0:
                    pairs_i.append(i)
                    pairs_j.append(j)
            if pairs_i:
                xi = np.asarray(
                    [ticks[i] for i in pairs_i], dtype=np.float64
                )
                bi = np.asarray(
                    [
                        _phase_bin(ticks[i], period_ticks, n_bins)
                        for i in pairs_i
                    ],
                    dtype=np.int64,
                )
                yi = vals[np.asarray(pairs_i, dtype=np.int64)]
                yj = vals[np.asarray(pairs_j, dtype=np.int64)]
                fitted = intercept + slope * xi + seasonal[bi]
                mae = float(np.abs(yi - fitted).sum()) / yi.size
                pmae = float(np.abs(yi - yj).sum()) / yi.size
                doc["mae"] = mae
                doc["persistence_mae"] = pmae
                doc["skill"] = pmae - mae
                if feed:
                    TIMESERIES.gauge(
                        f"{SERIES_FORECAST_SKILL}/{name}",
                        doc["skill"],
                        vt=vt,
                    )
        return doc

    def report(
        self,
        names: Optional[List[str]] = None,
        horizon: Optional[float] = None,
        now: Optional[float] = None,
    ) -> dict:
        """The ``GET /debug/forecast`` document: one forecast per watched
        (or requested) series."""
        targets = names if names else self.watched()
        return {
            "enabled": self.enabled,
            "period_s": self.period,
            "horizon_s": float(
                horizon if horizon is not None else self.horizon
            ),
            "history_s": self.history,
            "forecasts": [
                self.forecast(n, horizon=horizon, now=now) for n in targets
            ],
        }


FORECASTER = Forecaster()
