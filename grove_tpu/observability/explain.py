"""Admission explain engine: on-demand "why is my gang Pending, and what
would unblock it?" (docs/observability.md "Admission explain").

PR 12 made the control plane glass-box on the TIME axis (where the wall
goes); this module answers the DECISION axis. For any pending PodGang it
replays a constraint-elimination funnel from one consistent snapshot of
the store/delta state — every stage a read-only recount of exactly the
input the next scheduling round will consume (the data layer lives in
``solver/introspect.py``):

    cluster      which cluster owns the gang and why (federation tier)
    node-health  schedulable mask (cordon / NotReady / Lost)
    capacity     per-resource raw free capacity vs the gang floor
    topology     largest contiguous required-level domain packability
    quota        ceiling holds + DRF rank and who is ahead
    disruption   monitor requeue holds / storm-breaker state
    partition    frontier partition assignment (or RESIDUAL)
    solve        solo trial + the full-order trial solve

and emits a structured verdict: ``fits_now``, the failing stages
(``blocked_on``) with per-stage surviving-node counts, and the single
binding constraint. The verdict is TRUTHFUL by construction — the solve
stage runs the identical encode (same spec builder, same sticky padding,
same DRF order, same kernel) the next round runs, so ``fits_now=True``
implies admission by the next solve absent intervening churn (the seeded
churn property in tests/test_explain.py pins this, and pins every
``blocked_on`` stage against an independent NumPy recount).

The engine is STRICTLY read-only: no store commit, no bind, no eviction,
no delta/frontier invalidation — ``Store.resource_version_vector()`` and
``DeltaSolveState.state_fingerprint()`` are byte-identical across any
explain/capacity/what-if burst (grovelint GL016 locks both modules to
this contract; the verdict cache below is private to this module).

What-if (``POST /debug/whatif`` / ``cli whatif``): hypothetical trial
solves — drain/remove/add nodes, rewrite a queue's deserved/ceiling —
evaluated through the SAME funnel over an overlay view, reusing the
drain controller's gang-whole relocation semantics
(``introspect.gang_spec_from_cr``: evicted gangs re-enter the pending
order, their off-node usage credited back) without committing anything.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from grove_tpu.observability.events import (
    DETAIL_DISRUPTION_HOLD,
    DETAIL_INSUFFICIENT_CAPACITY,
    DETAIL_NO_NODES,
    DETAIL_QUEUE_POSITION,
    DETAIL_QUOTA_CEILING,
    DETAIL_TOPOLOGY_FRAGMENTATION,
    DETAIL_UNSATISFIABLE,
)

# Canonical funnel stages, in elimination order — the closed registry
# tests/test_docs_drift.py pins against the docs/observability.md
# "Admission explain" stage table.
FUNNEL_STAGES = (
    "cluster",
    "node-health",
    "capacity",
    "topology",
    "quota",
    "disruption",
    "partition",
    "solve",
)

# detail slug -> the funnel stage that owns it (binding-constraint map)
_SLUG_STAGE = {
    DETAIL_NO_NODES: "node-health",
    DETAIL_INSUFFICIENT_CAPACITY: "capacity",
    DETAIL_TOPOLOGY_FRAGMENTATION: "topology",
    DETAIL_UNSATISFIABLE: "topology",
    DETAIL_QUOTA_CEILING: "quota",
    DETAIL_QUEUE_POSITION: "quota",
    DETAIL_DISRUPTION_HOLD: "disruption",
}


def _store_rv(store):
    """The store's scalar resourceVersion, or None on stores that carry
    no local counter (cluster mode's HttpStore — the operator's view of
    an external apiserver; verdicts there stamp no rv)."""
    return getattr(store, "resource_version", None)


class ExplainEngine:
    """One scheduler's decision-explainability face. Thread-safe; all
    state is the bounded verdict cache (private to this module — GL016)."""

    def __init__(self, scheduler, max_cached: int = 4096) -> None:
        self.scheduler = scheduler
        self.max_cached = max_cached
        self._lock = threading.Lock()
        # (ns, name) -> slim last verdict, LRU-bounded; feeds the
        # /debug/journeys pending annotation (journey gap fix)
        self._verdicts: "OrderedDict[tuple, dict]" = OrderedDict()
        # lifetime counters (the bench "explain" block)
        self.explains_total = 0
        self.whatifs_total = 0
        # federation hook (grove_tpu/federation): the router installs a
        # ``(namespace, name) -> str`` callback per cluster so the
        # funnel's opening "cluster" stage answers WHICH cluster owns
        # this gang and why it was routed there. None on a bare harness
        # — the stage then reports the single-cluster degenerate case.
        self.cluster_context = None

    # -- wire faces ------------------------------------------------------

    def explain(self, namespace: str, name: str) -> Optional[dict]:
        """The admission-explain verdict for one PodGang, or None when no
        such PodGang exists."""
        from grove_tpu.api.meta import get_condition
        from grove_tpu.api.types import COND_PODGANG_SCHEDULED
        from grove_tpu.solver import introspect

        sched = self.scheduler
        gang = sched.store.get("PodGang", namespace, name, readonly=True)
        if gang is None:
            return None
        t0 = time.perf_counter()
        cond = get_condition(gang.status.conditions, COND_PODGANG_SCHEDULED)
        if cond is not None and cond.is_true():
            doc = {
                "kind": "GangExplain",
                "namespace": namespace,
                "name": name,
                "state": "scheduled",
                "fits_now": True,
                "binding_constraint": None,
                "blocked_on": [],
                "funnel": [],
                "message": "gang is scheduled (Scheduled=True); nothing"
                " to explain",
            }
            self._finish(namespace, name, doc, t0)
            return doc
        # best-effort consistency under concurrency: in threaded cluster
        # mode the scheduler mutates its working sets while this handler
        # thread reads them — a torn dict iteration raises RuntimeError,
        # which is transient by construction (the next snapshot attempt
        # reads a settled round). Verdicts are evidence, so retry rather
        # than 500; lock coupling is off the table (the apiserver's
        # nested-self-call rule).
        last_err = None
        for _ in range(3):
            try:
                view = introspect.collect_pending(sched)
                doc = self._evaluate(view, namespace, name)
                break
            except RuntimeError as e:
                last_err = e
        else:
            raise last_err
        self._finish(namespace, name, doc, t0)
        return doc

    def capacity(self) -> dict:
        """``GET /debug/capacity``: per-level domain free vectors + the
        fragmentation statistic (introspect.capacity_report)."""
        from grove_tpu.solver import introspect

        doc = dict(
            {"kind": "CapacityReport"},
            **introspect.capacity_report(self.scheduler),
        )
        doc["resource_version"] = _store_rv(self.scheduler.store)
        return doc

    def whatif(self, body: dict) -> dict:
        """``POST /debug/whatif``: evaluate the target gang's verdict
        before and after a list of hypothetical actions, committing
        nothing. Raises ValueError on a malformed request."""
        gang_ref = body.get("gang") or {}
        namespace = gang_ref.get("namespace", "default")
        name = gang_ref.get("name")
        if not name:
            raise ValueError("whatif: body.gang.name is required")
        actions = body.get("actions") or []
        if not isinstance(actions, list) or not actions:
            raise ValueError("whatif: body.actions must be a non-empty list")
        before = self.explain(namespace, name)
        if before is None:
            raise ValueError(
                f"whatif: PodGang {namespace}/{name} not found"
            )
        # same transient-tear retry as explain() (threaded cluster mode)
        last_err = None
        for _ in range(3):
            try:
                after, applied = self._evaluate_hypothetical(
                    namespace, name, actions
                )
                break
            except RuntimeError as e:
                last_err = e
        else:
            raise last_err
        self.whatifs_total += 1
        return {
            "kind": "WhatIfReport",
            "gang": {"namespace": namespace, "name": name},
            "actions": applied,
            "before": before,
            "after": after,
            "flipped": bool(before.get("fits_now"))
            != bool(after.get("fits_now")),
        }

    def last_verdict(self, namespace: str, name: str) -> Optional[dict]:
        """Slim cached last verdict (journey-gap annotation), or None."""
        with self._lock:
            return self._verdicts.get((namespace, name))

    def pending_journeys(self) -> List[dict]:
        """``/debug/journeys`` pending rows: every active (un-scheduled)
        journey with age/stage, annotated with this engine's last verdict
        when one was computed — stuck gangs become visible instead of
        silently absent from the completed-only summary."""
        from grove_tpu.observability.journey import JOURNEYS

        rows = JOURNEYS.pending()
        for row in rows:
            v = self.last_verdict(row["namespace"], row["name"])
            if v is not None:
                row["last_verdict"] = v
        return rows

    # -- internals -------------------------------------------------------

    def _finish(self, namespace, name, doc, t0: float) -> None:
        from grove_tpu.observability.metrics import METRICS

        doc["evaluated_in_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        slim = {
            "state": doc.get("state"),
            "fits_now": doc.get("fits_now"),
            "binding_constraint": doc.get("binding_constraint"),
            "detail": doc.get("detail"),
            "evaluated_at_rv": doc.get("resource_version"),
        }
        with self._lock:
            self._verdicts[(namespace, name)] = slim
            self._verdicts.move_to_end((namespace, name))
            while len(self._verdicts) > self.max_cached:
                self._verdicts.popitem(last=False)
        self.explains_total += 1
        METRICS.observe("explain_verdict_seconds", (time.perf_counter() - t0))

    def _evaluate(
        self,
        view,
        namespace: str,
        name: str,
        queue_crs: Optional[dict] = None,
        usage: Optional[dict] = None,
        hypothetical: bool = False,
    ) -> dict:
        """The funnel over one PendingView (live or overlay)."""
        from grove_tpu.solver import introspect

        sched = self.scheduler
        key = (namespace, name)
        target = next(
            (
                s
                for s in view.specs
                if s["namespace"] == namespace and s["gang_name"] == name
            ),
            None,
        )
        monitor_held = key in set(view.held_monitor)
        if target is None and monitor_held:
            target = view.held_specs.get(key)
        doc: dict = {
            "kind": "GangExplain",
            "namespace": namespace,
            "name": name,
            "state": "held" if monitor_held else "pending",
            "hypothetical": hypothetical,
            "resource_version": _store_rv(sched.store),
        }
        if target is None:
            # a PodGang with no pending pods this instant (pods still
            # materializing, or all pods gated) — nothing to solve yet
            doc.update(
                {
                    "state": "no-pending-pods",
                    "fits_now": False,
                    "binding_constraint": None,
                    "blocked_on": [],
                    "funnel": [],
                    "message": "the gang has no pending (ungated,"
                    " unscheduled) pods this instant — controllers may"
                    " still be materializing them",
                }
            )
            return doc

        funnel: List[dict] = []

        def stage(name_, surviving, ok, detail):
            funnel.append(
                {
                    "stage": name_,
                    "surviving_nodes": int(surviving),
                    "ok": bool(ok),
                    "detail": detail,
                }
            )

        # 0. cluster -----------------------------------------------------
        # the federation tier's "which cluster and why" stage: never a
        # blocker (a gang that reached this engine IS in this cluster);
        # the detail cites the router's placement decision when a
        # FederationRouter installed cluster_context, else the
        # single-cluster degenerate case. surviving = the whole node
        # population so the funnel stays monotone from the top.
        stage(
            "cluster",
            view.total_nodes,
            True,
            self.cluster_context(namespace, name)
            if self.cluster_context is not None
            else "single-cluster (no federation tier)",
        )

        # 1. node-health -------------------------------------------------
        n_sched = len(view.nodes)
        stage(
            "node-health",
            n_sched,
            n_sched > 0,
            f"{n_sched} of {view.total_nodes} nodes schedulable"
            " (cordoned/NotReady/Lost masked)",
        )

        # 2. capacity ----------------------------------------------------
        floor = introspect.spec_floor_demand(target)
        hosts = 0
        total_free: Dict[str, float] = {}
        for node in view.nodes:
            row = view.free.get(node.name, {})
            for r, q in row.items():
                total_free[r] = total_free.get(r, 0.0) + q
            if any(
                all(
                    row.get(r, 0.0) >= q
                    for r, q in grp["demand"].items()
                )
                for grp in target["groups"]
            ):
                hosts += 1
        short = sorted(
            r
            for r, q in floor.items()
            if q > total_free.get(r, 0.0) + 1e-9
        )
        cap_ok = hosts > 0 and not short
        cap_detail = (
            f"{hosts} nodes can host >=1 pod; cluster free covers the"
            f" gang floor"
            if cap_ok
            else (
                f"cluster free cannot cover the gang floor for"
                f" {'/'.join(short)}"
                if short
                else "no single node fits any pod of the gang"
            )
        )
        stage("capacity", hosts, cap_ok, cap_detail)

        # 3. topology ----------------------------------------------------
        topo_ok = True
        surviving_topo = hosts
        req_key = target.get("required_key")
        if req_key is not None and n_sched:
            level_keys = [
                lvl.key for lvl in sched.topology.spec.levels
            ]
            try:
                li = level_keys.index(req_key)
            except ValueError:
                li = None
            if li is None:
                topo_ok = False
                surviving_topo = 0
                stage(
                    "topology",
                    0,
                    False,
                    f"required pack key {req_key!r} is not a cluster"
                    " topology level",
                )
            else:
                domains: Dict[tuple, List] = {}
                for node in view.nodes:
                    path = tuple(
                        node.labels.get(k, "")
                        for k in level_keys[: li + 1]
                    )
                    domains.setdefault(path, []).append(node)
                best_cover, best_name = 0.0, ""
                covered_nodes = 0
                covered_domains = 0
                for path, members in sorted(domains.items()):
                    dom_free: Dict[str, float] = {}
                    for node in members:
                        for r, q in view.free.get(node.name, {}).items():
                            dom_free[r] = dom_free.get(r, 0.0) + q
                    need = {r: q for r, q in floor.items() if q > 0}
                    cover = (
                        min(
                            dom_free.get(r, 0.0) / q
                            for r, q in need.items()
                        )
                        if need
                        else 1.0
                    )
                    if cover > best_cover:
                        best_cover, best_name = cover, path[-1]
                    if cover >= 1.0 - 1e-9:
                        covered_domains += 1
                        covered_nodes += len(members)
                topo_ok = covered_domains > 0
                surviving_topo = covered_nodes
                stage(
                    "topology",
                    covered_nodes,
                    topo_ok,
                    f"{covered_domains} of {len(domains)} {req_key}"
                    " domains cover the gang floor"
                    if topo_ok
                    else f"no single {req_key} domain covers the gang"
                    f" floor (best: {best_name!r} at {best_cover:.0%})"
                    " — free capacity is fragmented across domains",
                )
        else:
            stage(
                "topology",
                surviving_topo,
                True,
                "no gang-level required pack constraint"
                if req_key is None
                else "no schedulable nodes to judge",
            )

        # 4. quota -------------------------------------------------------
        crs = (
            queue_crs
            if queue_crs is not None
            else sched.quota.queue_crs()
        )
        ordered, held_quota = introspect.order_view(
            sched, list(view.specs), queue_crs=crs, usage=usage
        )
        held_reason = next(
            (
                reason
                for spec, reason in held_quota
                if spec["namespace"] == namespace
                and spec["gang_name"] == name
            ),
            None,
        )
        rank = next(
            (
                i
                for i, s in enumerate(ordered)
                if s["namespace"] == namespace and s["gang_name"] == name
            ),
            None,
        )
        queue_doc = {"name": target["queue"], "active": bool(crs)}
        if rank is not None:
            queue_doc["rank"] = rank
            queue_doc["ahead"] = [s["name"] for s in ordered[:rank]][:16]
            queue_doc["ahead_count"] = rank
        if crs:
            from grove_tpu.quota.oracle import dominant_share_of

            cr = crs.get(target["queue"])
            u = (
                usage
                if usage is not None
                else introspect.queue_usage(sched)
            )
            queue_doc["dominant_share"] = round(
                dominant_share_of(
                    u.get(target["queue"], {}),
                    dict(cr.spec.deserved) if cr is not None else {},
                ),
                6,
            )
        stage(
            "quota",
            surviving_topo,
            held_reason is None,
            held_reason
            if held_reason is not None
            else (
                f"rank {rank} of {len(ordered)} in this round's solve"
                " order"
                if rank is not None
                else "quota inert (no Queue CRs)"
                if not crs
                else "not in this round's order"
            ),
        )
        doc["queue"] = queue_doc

        # 5. disruption --------------------------------------------------
        broker = sched.broker
        # breaker_open is a property — calling it raised TypeError on any
        # explain taken while the broker was armed (latent until the
        # remediator started arming the broker on ordinary runs)
        breaker_open = bool(
            broker is not None
            and broker.active()
            and broker.breaker_open
        )
        dis_detail = (
            "gang is in the node-health monitor's requeue backoff"
            " (released into a later round)"
            if monitor_held
            else (
                "storm breaker OPEN: preemption/reclaim-assisted"
                " admission is paused"
                if breaker_open
                else "no holds; breaker closed"
            )
        )
        stage("disruption", surviving_topo, not monitor_held, dis_detail)

        # 6. partition ---------------------------------------------------
        partition = None
        if (
            not hypothetical
            and sched.frontier is not None
            and sched.delta is not None
        ):
            enc, free_mat = sched.delta.encoding_view()
            if enc is not None and free_mat is not None:
                plan = sched.frontier.plan_for(enc)
                if plan is not None and rank is not None:
                    part_of = sched.frontier.assign(
                        plan, enc, free_mat, ordered
                    )
                    partition = int(part_of[rank])
        stage(
            "partition",
            surviving_topo,
            True,
            "frontier off (global solve)"
            if partition is None
            else (
                "assigned to the global RESIDUAL pass"
                if partition < 0
                else f"assigned to frontier partition {partition}"
            ),
        )
        if partition is not None:
            doc["partition"] = (
                "residual" if partition < 0 else partition
            )

        # 7. solve (solo + full order) -----------------------------------
        solo_res, solo_prob, solo_err = introspect.solve_view_safe(
            sched, view.nodes, view.free, [target]
        )
        solo_ok = bool(
            solo_res is not None and solo_res.admitted[0]
        )
        full_idx = rank
        full_admitted = False
        if full_idx is not None and not monitor_held:
            full_res, _full_prob, full_err = introspect.solve_view_safe(
                sched, view.nodes, view.free, ordered
            )
            if full_res is not None:
                full_admitted = bool(full_res.admitted[full_idx])
            elif full_err is not None and solo_err is None:
                # a COMPETITOR carries the broken constraint: fall back
                # to the solo verdict (the real round would crash on the
                # competitor before ever judging this gang; admission
                # validation keeps this path theoretical for CR-borne
                # gangs)
                full_admitted = False
        fits_now = (
            full_admitted and held_reason is None and not monitor_held
        )
        stage(
            "solve",
            surviving_topo,
            fits_now,
            f"solo trial {'admits' if solo_ok else 'rejects'};"
            f" full-order trial"
            f" {'admits' if full_admitted else 'rejects'}"
            + (f" (constraint error: {solo_err})" if solo_err else ""),
        )

        # verdict --------------------------------------------------------
        slug = text = None
        if monitor_held:
            slug, text = DETAIL_DISRUPTION_HOLD, dis_detail
        elif held_reason is not None:
            slug, text = DETAIL_QUOTA_CEILING, held_reason
        elif not fits_now:
            if n_sched == 0:
                # the funnel died at stage one: adding capacity is not
                # the fix, uncordoning is — never let the empty-node
                # fallback read as insufficient-capacity
                slug = DETAIL_NO_NODES
                text = "no schedulable nodes (all cordoned/NotReady/Lost)"
            elif solo_err is not None:
                slug, text = DETAIL_UNSATISFIABLE, solo_err
            elif solo_ok:
                slug = DETAIL_QUEUE_POSITION
                text = (
                    f"admitted solo, but outcompeted at rank {rank}"
                    f" ({rank} gangs ahead in the"
                    f" {'fair-share' if crs else 'priority'} order)"
                )
            else:
                from grove_tpu.solver.introspect import (
                    classify_rejections,
                )

                cls = classify_rejections(
                    solo_prob, solo_res, [target]
                )
                slug, text = cls.get(
                    0,
                    (
                        DETAIL_INSUFFICIENT_CAPACITY,
                        "solo trial rejected",
                    ),
                )
        binding = _SLUG_STAGE.get(slug, "solve") if slug else None
        doc.update(
            {
                "fits_now": fits_now,
                "binding_constraint": binding,
                "detail": slug,
                "detail_text": text,
                "blocked_on": [f for f in funnel if not f["ok"]],
                "funnel": funnel,
            }
        )
        if fits_now:
            doc["message"] = (
                "the next solve admits this gang absent intervening churn"
            )
        return doc

    # -- what-if overlays -------------------------------------------------

    def _evaluate_hypothetical(
        self, namespace: str, name: str, actions: List[dict]
    ) -> Tuple[dict, List[dict]]:
        from grove_tpu.api.meta import deep_copy
        from grove_tpu.api.meta import get_condition
        from grove_tpu.api.types import COND_PODGANG_SCHEDULED
        from grove_tpu.sim.cluster import Node
        from grove_tpu.solver import introspect

        sched = self.scheduler
        cluster = sched.cluster
        removed: set = set()
        added: List = []
        drained: List = []  # gangs evicted whole by hypothetical drains
        crs = dict(sched.quota.queue_crs())
        crs_touched = False
        applied: List[dict] = []
        for act in actions:
            kind = (act.get("action") or "").replace("_", "-")
            if kind == "drain-node" or kind == "remove-node":
                node_name = act.get("node")
                if cluster.node(node_name) is None:
                    raise ValueError(
                        f"whatif: unknown node {node_name!r}"
                    )
                removed.add(node_name)
                if kind == "drain-node":
                    # gang-whole relocation semantics (the drain
                    # controller's): every SCHEDULED gang with a pod on
                    # the node re-enters the pending order whole
                    seen = set()
                    for (ns, pod_name), bound in sorted(
                        cluster.bindings.items()
                    ):
                        if bound != node_name:
                            continue
                        pod = sched.store.get(
                            "Pod", ns, pod_name, readonly=True
                        )
                        if pod is None:
                            continue
                        gname = self._gang_label_of(pod)
                        if not gname or (ns, gname) in seen:
                            continue
                        seen.add((ns, gname))
                        gang = sched.store.get(
                            "PodGang", ns, gname, readonly=True
                        )
                        if gang is None:
                            continue
                        cond = get_condition(
                            gang.status.conditions,
                            COND_PODGANG_SCHEDULED,
                        )
                        if cond is None or not cond.is_true():
                            continue
                        drained.append(gang)
                applied.append({"action": kind, "node": node_name})
            elif kind == "add-nodes":
                count = int(act.get("count", 1))
                like = act.get("like")
                template = cluster.node(like) if like else None
                if template is None and like:
                    raise ValueError(f"whatif: unknown node {like!r}")
                if template is None:
                    raise ValueError(
                        "whatif: add-nodes needs `like: <node>` to"
                        " clone capacity/topology from"
                    )
                host_key = "kubernetes.io/hostname"
                for i in range(count):
                    nm = f"whatif-{len(added)}-{template.name}"
                    labels = dict(template.labels)
                    if host_key in labels:
                        labels[host_key] = nm
                    added.append(
                        Node(
                            name=nm,
                            capacity=dict(template.capacity),
                            labels=labels,
                        )
                    )
                applied.append(
                    {"action": kind, "count": count, "like": like}
                )
            elif kind == "set-queue":
                qname = act.get("queue")
                if not qname:
                    raise ValueError("whatif: set-queue needs `queue`")
                cr = crs.get(qname)
                if cr is not None:
                    cr = deep_copy(cr)
                else:
                    from grove_tpu.api.meta import ObjectMeta
                    from grove_tpu.api.types import Queue, QueueSpec

                    cr = Queue(
                        metadata=ObjectMeta(name=qname),
                        spec=QueueSpec(),
                    )
                if act.get("deserved") is not None:
                    cr.spec.deserved = {
                        r: float(v)
                        for r, v in act["deserved"].items()
                    }
                if act.get("ceiling") is not None:
                    cr.spec.ceiling = {
                        r: float(v) for r, v in act["ceiling"].items()
                    }
                crs[qname] = cr
                crs_touched = True
                applied.append(
                    {
                        "action": kind,
                        "queue": qname,
                        "deserved": dict(cr.spec.deserved),
                        "ceiling": dict(cr.spec.ceiling),
                    }
                )
            else:
                raise ValueError(
                    f"whatif: unknown action {act.get('action')!r}"
                    " (drain-node | remove-node | add-nodes |"
                    " set-queue)"
                )

        all_nodes = [
            n for n in cluster.nodes if n.name not in removed
        ] + added
        sched_nodes = [n for n in all_nodes if n.schedulable]
        free = cluster.node_free_all(sched_nodes)
        usage = introspect.queue_usage(sched) if crs else None
        extra_specs: List[dict] = []
        for gang in drained:
            # credit the gang's bound usage back on SURVIVING nodes (the
            # hypothetical eviction releases it; capacity on removed
            # nodes leaves with the node) and debit its queue's ledger
            spec = introspect.gang_spec_from_cr(sched.store, sched, gang)
            extra_specs.append(spec)
            for group in gang.spec.pod_groups:
                for ref in group.pod_references:
                    bound = cluster.bindings.get(
                        (ref.namespace, ref.name)
                    )
                    pod = sched.store.get(
                        "Pod", ref.namespace, ref.name, readonly=True
                    )
                    if pod is None:
                        continue
                    reqs = pod.spec.total_requests()
                    if bound is not None and bound in free:
                        row = free[bound]
                        for r, q in reqs.items():
                            row[r] = row.get(r, 0.0) + q
                    if usage is not None and bound is not None:
                        qrow = usage.setdefault(spec["queue"], {})
                        for r, q in reqs.items():
                            qrow[r] = qrow.get(r, 0.0) - q
        view = introspect.collect_pending(
            sched, nodes=sched_nodes, free=free, all_nodes=all_nodes
        )
        existing = {(s["namespace"], s["gang_name"]) for s in view.specs}
        for spec in extra_specs:
            if (spec["namespace"], spec["gang_name"]) not in existing:
                view.specs.append(spec)
        after = self._evaluate(
            view,
            namespace,
            name,
            queue_crs=crs if (crs or crs_touched) else None,
            usage=usage,
            hypothetical=True,
        )
        return after, applied

    @staticmethod
    def _gang_label_of(pod) -> Optional[str]:
        from grove_tpu.api import names as namegen

        return pod.metadata.labels.get(namegen.LABEL_PODGANG)
