"""Declarative SLO layer: objectives, error budgets, burn-rate alerting.

The time-series engine (timeseries.py) remembers; this module *judges*.
An :class:`SloSpec` names an objective over one series — the grammar
(docs/observability.md "SLO observatory")

    <series> [:reducer] <op> <threshold>[unit] over <window> \
        [target <pct>] [budget <window>] [burn <factor>x <fast>/<slow>]

e.g. ``admission_latency_vt:p99 < 60s over 5m target 99% budget 30m
burn 6x 1m/10m`` or ``ready_fraction/default/serve >= 0.9 over 1m
target 99%``. Each evaluation round (the harness's tick boundary):

- the **indicator** reduces the series over ``window`` and compares
  against the threshold → one good/bad verdict per tick, recorded back
  into the time-series engine (series ``slo:<name>:good``) so attainment
  windows read through the SAME oracle-pinned reducers;
- **attainment** is the good fraction over ``budget`` (the compliance
  window); the **error budget** is ``1 - target`` of it, and
  ``budget_remaining = 1 - bad_fraction / (1 - target)`` (clamped ≥ 0);
- **burn rate** over a window w is ``bad_fraction(w) / (1 - target)`` —
  the Google-SRE multi-window multi-burn-rate rule fires
  ``SloBurnRateHigh`` only when BOTH the fast and slow windows burn
  above ``burn_factor`` (fast catches the step, slow filters the blip);
- **breach** is edge-triggered: attainment dropping below ``target``
  emits ``SloBreach``, bumps ``slo_breaches_total``, and freezes a
  flight-recorder bundle whose detail names the breaching objective and
  window (the PR-12 trigger set grown by one); re-attaining emits
  ``SloRecovered``.

Surfaced at ``GET /debug/slo``, ``cli slo``, and the Prometheus rows
``slo_attainment/<name>``, ``slo_burn_rate/<name>``,
``slo_budget_remaining/<name>``. Off by default (``GROVE_TPU_SLO=1`` /
``SLO.enable()``), one-boolean-check discipline; engine state is private
to this module (grovelint GL017).
"""

from __future__ import annotations

import os
import re
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from grove_tpu.observability.metrics import METRICS
from grove_tpu.observability.timeseries import TIMESERIES

_OPS = {
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
}

_REDUCERS = ("p50", "p99", "mean", "max", "min", "rate", "last")

_DUR_RE = re.compile(r"^(\d+(?:\.\d+)?)(ms|s|m|h|d)?$")
_DUR_UNITS = {"ms": 1e-3, None: 1.0, "s": 1.0, "m": 60.0, "h": 3600.0,
              "d": 86400.0}

_SPEC_RE = re.compile(
    r"^\s*(?P<series>[A-Za-z0-9_:/.@-]+?)"
    r"(?::(?P<reducer>p50|p99|mean|max|min|rate|last))?"
    r"\s*(?P<op><=|>=|<|>)\s*"
    r"(?P<threshold>\d+(?:\.\d+)?)(?P<unit>ms|s|m|h|d)?"
    r"\s+over\s+(?P<window>\S+)"
    r"(?:\s+target\s+(?P<target>\d+(?:\.\d+)?)%)?"
    r"(?:\s+budget\s+(?P<budget>\S+))?"
    r"(?:\s+burn\s+(?P<burn>\d+(?:\.\d+)?)x\s+"
    r"(?P<fast>\S+)/(?P<slow>\S+))?\s*$"
)


def parse_duration(text: str) -> float:
    m = _DUR_RE.match(text.strip())
    if m is None:
        raise ValueError(f"unparseable duration {text!r} (want e.g. 30s, 5m)")
    return float(m.group(1)) * _DUR_UNITS[m.group(2)]


@dataclass
class SloSpec:
    """One objective. ``series``/``reducer``/``op``/``threshold`` define
    the per-tick indicator; ``window`` the indicator's reduction window;
    ``target`` the attainment objective over the ``budget`` compliance
    window; the burn windows/factor drive the multi-window alert."""

    name: str
    series: str
    op: str
    threshold: float
    window: float  # indicator reduction window, seconds
    reducer: Optional[str] = None  # None -> 'last' for gauges, 'p99' dists
    target: float = 0.99
    budget: Optional[float] = None  # compliance window; default 6x window
    burn_factor: float = 6.0
    fast_window: Optional[float] = None  # default: window
    slow_window: Optional[float] = None  # default: budget

    def __post_init__(self) -> None:
        if self.op not in _OPS:
            raise ValueError(f"unknown op {self.op!r}")
        if not 0.0 < self.target < 1.0:
            raise ValueError("target must be in (0, 1)")
        if self.budget is None:
            self.budget = 6.0 * self.window
        if self.fast_window is None:
            self.fast_window = self.window
        if self.slow_window is None:
            self.slow_window = self.budget
        if self.reducer is not None and self.reducer not in _REDUCERS:
            raise ValueError(f"unknown reducer {self.reducer!r}")

    @classmethod
    def parse(cls, text: str, name: Optional[str] = None) -> "SloSpec":
        m = _SPEC_RE.match(text)
        if m is None:
            raise ValueError(
                f"unparseable SLO spec {text!r} — grammar: '<series>"
                "[:reducer] <op> <threshold>[unit] over <window>"
                " [target <pct>] [budget <window>]"
                " [burn <factor>x <fast>/<slow>]'"
            )
        g = m.groupdict()
        threshold = float(g["threshold"]) * _DUR_UNITS[g["unit"]]
        kwargs = dict(
            name=name or g["series"].replace("/", "_").replace(":", "_"),
            series=g["series"],
            reducer=g["reducer"],
            op=g["op"],
            threshold=threshold,
            window=parse_duration(g["window"]),
        )
        if g["target"]:
            kwargs["target"] = float(g["target"]) / 100.0
        if g["budget"]:
            kwargs["budget"] = parse_duration(g["budget"])
        if g["burn"]:
            kwargs["burn_factor"] = float(g["burn"])
            kwargs["fast_window"] = parse_duration(g["fast"])
            kwargs["slow_window"] = parse_duration(g["slow"])
        return cls(**kwargs)

    def render(self) -> str:
        red = f":{self.reducer}" if self.reducer else ""
        return (
            f"{self.series}{red} {self.op} {self.threshold:g} over"
            f" {self.window:g}s target {self.target * 100:g}% budget"
            f" {self.budget:g}s burn {self.burn_factor:g}x"
            f" {self.fast_window:g}s/{self.slow_window:g}s"
        )


class _ObjectiveState:
    __slots__ = ("spec", "breached", "burning", "evaluations", "good",
                 "bad", "last_value", "last_attainment", "last_burn_fast",
                 "last_burn_slow", "breaches", "recoveries", "last_tick",
                 "config_error")

    def __init__(self, spec: SloSpec) -> None:
        self.spec = spec
        self.breached = False
        self.burning = False
        self.evaluations = 0
        self.good = 0
        self.bad = 0
        self.last_value: Optional[float] = None
        self.last_attainment: Optional[float] = None
        self.last_burn_fast = 0.0
        self.last_burn_slow = 0.0
        self.breaches = 0
        self.recoveries = 0
        self.last_tick = -1  # one verdict per virtual tick (idempotent)
        self.config_error = False  # reducer/series-kind mismatch


class SloEngine:
    """Process-global (``SLO``), thread-safe. Evaluation runs at tick
    boundaries behind one boolean check; nothing here is on a hot path."""

    def __init__(self) -> None:
        self.enabled = os.environ.get("GROVE_TPU_SLO", "") not in (
            "",
            "0",
            "false",
        )
        self._lock = threading.Lock()
        self._state: Dict[str, _ObjectiveState] = {}

    # -- lifecycle -------------------------------------------------------

    def enable(self) -> "SloEngine":
        self.enabled = True
        return self

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._state = {}

    # -- spec management -------------------------------------------------

    def add(self, spec) -> SloSpec:
        """Register an objective (an :class:`SloSpec`, or grammar text)."""
        if isinstance(spec, str):
            spec = SloSpec.parse(spec)
        with self._lock:
            if spec.name in self._state:
                raise ValueError(f"objective {spec.name!r} already defined")
            self._state[spec.name] = _ObjectiveState(spec)
        return spec

    def specs(self) -> List[SloSpec]:
        with self._lock:
            return [st.spec for st in self._state.values()]

    # -- evaluation ------------------------------------------------------

    def _indicator(self, st: _ObjectiveState, now: float) -> Optional[float]:
        """The objective's current indicator value, or None when the
        window holds no data. A window WITH data but without the spec'd
        reducer (``rate`` on a gauge, ``min``/``last`` on a distribution)
        is a spec/series-kind mismatch — flagged as ``config_error`` so
        the status surface distinguishes it from genuinely absent data
        (a silently never-evaluating objective alerts no one)."""
        spec = st.spec
        doc = TIMESERIES.window(spec.series, spec.window, now=now)
        if doc.get("n", 0) == 0 and doc.get("count", 0) == 0:
            return None
        reducer = spec.reducer
        if reducer is None:
            reducer = "p99" if doc.get("kind") == "dist" else "last"
        value = doc.get(reducer)
        st.config_error = value is None
        return value

    def _good_fraction(
        self, name: str, seconds: float, now: float
    ) -> Optional[float]:
        doc = TIMESERIES.window(f"slo:{name}:good", seconds, now=now)
        if doc.get("n", 0) == 0:
            return None
        return doc["mean"]

    def evaluate(self, now: float) -> None:
        """One evaluation round over every objective (tick boundary)."""
        if not self.enabled:
            return
        tick = TIMESERIES.tick_of(now)
        with self._lock:
            states = list(self._state.values())
        for st in states:
            spec = st.spec
            # one verdict per virtual tick: a second evaluation in the
            # same tick (the scenario's guaranteed post-converge round
            # landing on a tick the converge loop already judged) must
            # not double-count good/bad
            if st.last_tick == tick:
                continue
            value = self._indicator(st, now)
            if value is None:
                continue  # no data in the window: not counted either way
            st.last_tick = tick
            good = _OPS[spec.op](value, spec.threshold)
            st.last_value = value
            st.evaluations += 1
            if good:
                st.good += 1
            else:
                st.bad += 1
            TIMESERIES.gauge(
                f"slo:{spec.name}:good", 1.0 if good else 0.0, vt=now
            )
            budget_frac = 1.0 - spec.target
            att = self._good_fraction(spec.name, spec.budget, now)
            if att is None:
                continue
            st.last_attainment = att
            good_fast = self._good_fraction(spec.name, spec.fast_window, now)
            good_slow = self._good_fraction(spec.name, spec.slow_window, now)
            st.last_burn_fast = (
                (1.0 - good_fast) / budget_frac
                if good_fast is not None
                else 0.0
            )
            st.last_burn_slow = (
                (1.0 - good_slow) / budget_frac
                if good_slow is not None
                else 0.0
            )
            remaining = max(0.0, 1.0 - (1.0 - att) / budget_frac)
            METRICS.set(f"slo_attainment/{spec.name}", att)
            METRICS.set(f"slo_burn_rate/{spec.name}", st.last_burn_fast)
            METRICS.set(f"slo_budget_remaining/{spec.name}", remaining)
            self._alert(st, att, now)

    def _alert(self, st: _ObjectiveState, attainment: float, now: float) -> None:
        """Edge-triggered state machine: breach/recovery on the
        compliance-window attainment, burn-rate page on the fast AND slow
        windows both burning above the factor."""
        from grove_tpu.observability.events import (
            EVENTS,
            REASON_SLO_BREACH,
            REASON_SLO_BURN_RATE_HIGH,
            REASON_SLO_RECOVERED,
            TYPE_NORMAL,
            TYPE_WARNING,
        )
        from grove_tpu.observability.flightrec import FLIGHTREC

        spec = st.spec
        ref = ("SloObjective", "", spec.name)
        burning = (
            st.last_burn_fast >= spec.burn_factor
            and st.last_burn_slow >= spec.burn_factor
        )
        if burning and not st.burning:
            EVENTS.record(
                ref,
                TYPE_WARNING,
                REASON_SLO_BURN_RATE_HIGH,
                f"{spec.name}: burn {st.last_burn_fast:.1f}x over"
                f" {spec.fast_window:g}s and {st.last_burn_slow:.1f}x over"
                f" {spec.slow_window:g}s (threshold {spec.burn_factor:g}x)",
            )
            METRICS.inc("slo_burn_alerts_total")
        st.burning = burning
        if attainment < spec.target and not st.breached:
            st.breached = True
            st.breaches += 1
            METRICS.inc("slo_breaches_total")
            detail = (
                f"objective={spec.name} window={spec.budget:g}s"
                f" attainment={attainment:.4f} target={spec.target:g}"
                f" indicator={spec.render()}"
            )
            EVENTS.record(
                ref,
                TYPE_WARNING,
                REASON_SLO_BREACH,
                f"{spec.name}: attainment {attainment:.4f} <"
                f" target {spec.target:g} over {spec.budget:g}s",
            )
            if FLIGHTREC.enabled:
                # the postmortem bundle, stamped with the breaching
                # objective + window (PR-12 trigger set + 1)
                FLIGHTREC.trigger("SloBreach", detail)
        elif st.breached and attainment >= spec.target:
            st.breached = False
            st.recoveries += 1
            METRICS.inc("slo_recoveries_total")
            EVENTS.record(
                ref,
                TYPE_NORMAL,
                REASON_SLO_RECOVERED,
                f"{spec.name}: attainment {attainment:.4f} back above"
                f" target {spec.target:g}",
            )

    # -- read side -------------------------------------------------------

    def burning(self) -> List[dict]:
        """Objectives currently in a burn-rate alert or breached — the
        remediation controller's trigger read (engine state stays private
        to this module, GL017). Each row carries what a remediator needs
        to decide and to account: the objective name, its series, the
        alert state, and the error budget remaining."""
        with self._lock:
            states = list(self._state.values())
        out = []
        for st in states:
            if not (st.burning or st.breached):
                continue
            spec = st.spec
            att = st.last_attainment
            out.append(
                {
                    "name": spec.name,
                    "series": spec.series,
                    "breached": st.breached,
                    "burning": st.burning,
                    "burn_rate_fast": st.last_burn_fast,
                    "burn_rate_slow": st.last_burn_slow,
                    "budget_remaining": (
                        max(0.0, 1.0 - (1.0 - att) / (1.0 - spec.target))
                        if att is not None
                        else None
                    ),
                }
            )
        return out

    def budget_remaining(self, name: str) -> Optional[float]:
        """Error budget remaining for one objective (None before its
        first attainment round) — the ledger's effect-measurement read."""
        with self._lock:
            st = self._state.get(name)
            if st is None or st.last_attainment is None:
                return None
            return max(
                0.0,
                1.0 - (1.0 - st.last_attainment) / (1.0 - st.spec.target),
            )

    def status(self, series_window: float = 300.0) -> dict:
        """The ``GET /debug/slo`` document: one row per objective plus the
        series appendix (every live series reduced over one window)."""
        with self._lock:
            states = list(self._state.values())
        objectives = []
        for st in states:
            spec = st.spec
            budget_frac = 1.0 - spec.target
            att = st.last_attainment
            objectives.append(
                {
                    "name": spec.name,
                    "spec": spec.render(),
                    "series": spec.series,
                    "state": "config-error" if st.config_error else (
                        "breached" if st.breached else (
                            "burning" if st.burning else "ok"
                        )
                    ),
                    "value": st.last_value,
                    "attainment": att,
                    "budget_remaining": (
                        max(0.0, 1.0 - (1.0 - att) / budget_frac)
                        if att is not None
                        else None
                    ),
                    "burn_rate_fast": round(st.last_burn_fast, 4),
                    "burn_rate_slow": round(st.last_burn_slow, 4),
                    "evaluations": st.evaluations,
                    "good": st.good,
                    "bad": st.bad,
                    "breaches": st.breaches,
                    "recoveries": st.recoveries,
                }
            )
        return {
            "enabled": self.enabled,
            "objectives": objectives,
            "series": TIMESERIES.snapshot(series_window),
        }


SLO = SloEngine()
