"""Gang-journey tracing: the causal record of one PodGang's admission.

The schedulers in PAPERS.md that reason about starvation all lean on the
same primitive — per-queue latency decomposition: you cannot even DEFINE
"starved" without splitting *how long a gang waited in the queue* from
*how long the control plane spent serving it* from *how long the solver
held it*. The span tracer can't provide that: spans are per-call-site,
a gang's admission crosses dozens of them over many rounds.

``JOURNEYS`` records, per PodGang, the causal chain

    created → first-scan → encode → solve → commit → scheduled

with both wall (``time.perf_counter``) and virtual-clock timestamps, and
derives the admission-latency decomposition on completion:

- ``queue_wait``: creation → the encode of the round that ADMITTED it
  (covers detection latency + every deferred round);
- ``encode`` / ``solve``: that round's problem-assembly and solve walls
  (the gang experiences the whole batch phase — batch attribution is the
  honest per-gang number in a batched scheduler);
- ``commit``: solve end → this gang's pods bound;
- ``status``: bind → the Scheduled=True condition committed.

The partitioned frontier stamps which partition (or the residual pass)
solved the gang, so a journey names its frontier lane. A critical-path
fold over completed journeys (:meth:`JourneyTracker.critical_path`)
explains converge wall top-down: per-segment totals/shares plus the tail
journey's own decomposition.

Off by default, one-boolean-check discipline (``GROVE_TPU_JOURNEY=1`` /
``JOURNEYS.enable()``). Surfaced at ``GET /gangs/{ns}/{name}/journey``,
``cli journey``, and the bench's admission-latency block.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from grove_tpu.observability.metrics import METRICS, _quantile
from grove_tpu.observability.timeseries import (
    SERIES_ADMISSION,
    SERIES_ADMISSION_VT,
    TIMESERIES,
)

# Canonical journey phases, in causal order — the closed registry
# tests/test_docs_drift.py pins against the docs/observability.md
# "Journey phases" table. RESIDUAL/partition ids annotate `solve`.
JOURNEY_PHASES = (
    "created",
    "first-scan",
    "encode",
    "solve",
    "commit",
    "scheduled",
)
# admission-latency decomposition segment names (derived, docs-gated too)
JOURNEY_SEGMENTS = ("queue_wait", "encode", "solve", "commit", "status")

PARTITION_RESIDUAL = -1  # solved by the global residual pass (or global solve)


class _Journey:
    __slots__ = (
        "namespace",
        "name",
        "marks",  # phase -> (wall_t, vt)
        "rounds",  # solve rounds this gang was encoded into (deferrals + 1)
        "partition",
        "segments",  # filled on completion
        "complete",
    )

    def __init__(self, namespace: str, name: str) -> None:
        self.namespace = namespace
        self.name = name
        self.marks: Dict[str, Tuple[float, Optional[float]]] = {}
        self.rounds = 0
        self.partition: Optional[int] = None
        self.segments: Optional[Dict[str, float]] = None
        self.complete = False

    def as_dict(self) -> dict:
        origin = self.marks.get("created") or self.marks.get("first-scan")
        t0 = origin[0] if origin else 0.0
        phases = [
            {
                "phase": ph,
                "t_s": round(self.marks[ph][0] - t0, 9),
                **(
                    {"vt": self.marks[ph][1]}
                    if self.marks[ph][1] is not None
                    else {}
                ),
            }
            for ph in JOURNEY_PHASES
            if ph in self.marks
        ]
        doc = {
            "namespace": self.namespace,
            "name": self.name,
            "complete": self.complete,
            "rounds": self.rounds,
            "phases": phases,
        }
        if self.partition is not None:
            doc["partition"] = self.partition
        if self.segments is not None:
            doc["segments"] = {
                k: round(v, 9) for k, v in self.segments.items()
            }
            doc["total_s"] = round(sum(self.segments.values()), 9)
        return doc


class JourneyTracker:
    """Process-global (``JOURNEYS``), thread-safe, bounded: active
    journeys are LRU-evicted past ``max_active`` (deleted gangs are
    dropped eagerly), completed ones keep the most recent
    ``max_completed`` for percentile math."""

    def __init__(
        self, max_active: int = 65_536, max_completed: int = 65_536
    ) -> None:
        self.enabled = os.environ.get("GROVE_TPU_JOURNEY", "") not in (
            "",
            "0",
            "false",
        )
        self.clock = None  # optional virtual clock (newest harness wins)
        self.max_active = max_active
        self.max_completed = max_completed
        self.completed_total = 0
        self._lock = threading.Lock()
        self._active: "OrderedDict[tuple, _Journey]" = OrderedDict()
        self._done: "OrderedDict[tuple, _Journey]" = OrderedDict()
        # current solve round's batch stamps (encode start/end, solve end):
        # written by the scheduler once per round, consumed per admitted gang
        self._round: Optional[Tuple[float, float, float]] = None

    # -- lifecycle -------------------------------------------------------

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._active.clear()
            self._done.clear()
            self._round = None
            self.completed_total = 0

    # -- marks (scheduler / store call sites) ----------------------------

    def t(self) -> float:
        return time.perf_counter()

    def _vt(self) -> Optional[float]:
        return round(self.clock.now(), 3) if self.clock is not None else None

    def _get(self, namespace: str, name: str, create: bool) -> Optional[_Journey]:
        key = (namespace, name)
        j = self._active.get(key)
        if j is None and create:
            j = self._active[key] = _Journey(namespace, name)
            while len(self._active) > self.max_active:
                self._active.popitem(last=False)
        return j

    def _mark(self, j: _Journey, phase: str, t: Optional[float] = None) -> None:
        j.marks[phase] = (t if t is not None else self.t(), self._vt())

    def note_created(self, namespace: str, name: str) -> None:
        """PodGang ADDED committed (store watch hook)."""
        with self._lock:
            j = self._get(namespace, name, create=True)
            if "created" not in j.marks:
                self._mark(j, "created")

    def note_deleted(self, namespace: str, name: str) -> None:
        with self._lock:
            self._active.pop((namespace, name), None)

    def note_seen(self, namespace: str, name: str) -> None:
        """The gang's pods entered a pending scan (first-win)."""
        with self._lock:
            j = self._get(namespace, name, create=True)
            if "first-scan" not in j.marks:
                self._mark(j, "first-scan")

    def note_round(self, t_encode0: float, t_encode1: float, t_solve1: float) -> None:
        """One solve round's batch stamps: encode start, encode end, solve
        end. Consumed by every gang admitted (or deferred) in the round."""
        with self._lock:
            self._round = (t_encode0, t_encode1, t_solve1)

    def note_encoded(self, namespace: str, name: str) -> None:
        """The gang's spec was in the round's solver input (deferred rounds
        bump the counter; the ADMITTING round's stamps win)."""
        with self._lock:
            j = self._get(namespace, name, create=True)
            j.rounds += 1
            if self._round is not None:
                t_enc0, t_enc1, _t_solve1 = self._round
                # the ADMITTING round's stamps win: deferred rounds just
                # overwrite until the gang finally places
                self._mark(j, "encode", t_enc0)
                self._mark(j, "solve", t_enc1)

    def note_partition(self, namespace: str, name: str, partition: int) -> None:
        """Frontier lane stamp: partition id, or PARTITION_RESIDUAL."""
        with self._lock:
            j = self._active.get((namespace, name))
            if j is not None:
                j.partition = partition

    def note_commit(self, namespace: str, name: str) -> None:
        """This gang's pods were bound (commit loop)."""
        with self._lock:
            j = self._active.get((namespace, name))
            if j is not None:
                self._mark(j, "commit")

    def note_scheduled(self, namespace: str, name: str) -> None:
        """Scheduled=True committed — the journey completes and its
        admission-latency decomposition is derived."""
        now = self.t()
        with self._lock:
            key = (namespace, name)
            j = self._active.pop(key, None)
            if j is None:
                return
            self._mark(j, "scheduled", now)
            rnd = self._round
            marks = j.marks
            created = marks.get("created") or marks.get("first-scan")
            enc0 = marks.get("encode")
            solve0 = marks.get("solve")
            commit = marks.get("commit")
            if created and enc0 and solve0 and commit and rnd is not None:
                t_solve1 = min(rnd[2], commit[0])
                j.segments = {
                    "queue_wait": max(enc0[0] - created[0], 0.0),
                    "encode": max(solve0[0] - enc0[0], 0.0),
                    "solve": max(t_solve1 - solve0[0], 0.0),
                    "commit": max(commit[0] - t_solve1, 0.0),
                    "status": max(now - commit[0], 0.0),
                }
            j.complete = all(ph in marks for ph in JOURNEY_PHASES)
            self._done[key] = j
            self.completed_total += 1
            while len(self._done) > self.max_completed:
                self._done.popitem(last=False)
        METRICS.inc("journeys_completed_total")
        # SLO observatory feed (one boolean check when the engine is off):
        # the completed journey's admission latency becomes a time-series
        # observation — wall seconds (the segments' sum, the SAME number
        # decomposition() reports) and virtual seconds (created→scheduled
        # on the sim clock, the deterministically replayable signal the
        # serving objectives judge)
        if TIMESERIES.enabled:
            if j.segments is not None:
                TIMESERIES.observe(
                    SERIES_ADMISSION, sum(j.segments.values())
                )
            created = j.marks.get("created") or j.marks.get("first-scan")
            sched = j.marks.get("scheduled")
            if (
                created is not None
                and sched is not None
                and created[1] is not None
                and sched[1] is not None
            ):
                TIMESERIES.observe(
                    SERIES_ADMISSION_VT, max(sched[1] - created[1], 0.0)
                )

    # -- read side -------------------------------------------------------

    def journey(self, namespace: str, name: str) -> Optional[dict]:
        with self._lock:
            # active first: a deleted-and-recreated gang's LIVE in-flight
            # journey must not be shadowed by its previous incarnation's
            # completed record (that is exactly the gang someone queries)
            j = self._active.get((namespace, name)) or self._done.get(
                (namespace, name)
            )
            return j.as_dict() if j is not None else None

    def completed(self) -> List[_Journey]:
        with self._lock:
            return list(self._done.values())

    def pending(self) -> List[dict]:
        """Every ACTIVE (not-yet-Scheduled) journey, oldest first, with
        its age and current stage — the journey-gap fix: /debug/journeys
        used to summarize only completions, so a stuck gang was silently
        absent from the one endpoint that should surface it. Age prefers
        the virtual clock (sims; lines up with requeue math) and falls
        back to wall time."""
        wall_now = self.t()
        vt_now = self._vt()
        with self._lock:
            journeys = list(self._active.values())
        rows = []
        for j in journeys:
            origin = j.marks.get("created") or j.marks.get("first-scan")
            doc = j.as_dict()
            if origin is not None:
                if vt_now is not None and origin[1] is not None:
                    doc["age_s"] = round(max(vt_now - origin[1], 0.0), 3)
                else:
                    doc["age_s"] = round(
                        max(wall_now - origin[0], 0.0), 9
                    )
            else:
                doc["age_s"] = 0.0
            doc["stage"] = next(
                (
                    ph
                    for ph in reversed(JOURNEY_PHASES)
                    if ph in j.marks
                ),
                "created",
            )
            rows.append(doc)
        rows.sort(key=lambda d: -d["age_s"])
        return rows

    def pending_ages(self) -> List[Tuple[str, float]]:
        """(namespace, oldest-pending-age) per namespace, virtual seconds
        (falls back to wall) — the lightweight per-tenant queue-wait
        signal the serving collector samples every tick (pending() builds
        full documents; this is two floats per namespace)."""
        wall_now = self.t()
        vt_now = self._vt()
        with self._lock:
            journeys = list(self._active.values())
        oldest: Dict[str, float] = {}
        for j in journeys:
            origin = j.marks.get("created") or j.marks.get("first-scan")
            if origin is None:
                continue
            if vt_now is not None and origin[1] is not None:
                age = max(vt_now - origin[1], 0.0)
            else:
                age = max(wall_now - origin[0], 0.0)
            if age > oldest.get(j.namespace, -1.0):
                oldest[j.namespace] = age
        return sorted(oldest.items())

    def window_summary(self, seconds: float = 300.0) -> dict:
        """Per-window admission-latency summary, read THROUGH the SLO
        observatory's time-series engine — the journey view and the SLO
        layer cite the same windowed numbers by construction (pinned
        equal in tests/test_slo_observatory.py). Returns empty shells
        while the engine is off (decomposition() keeps serving the
        all-time numbers)."""
        return {
            "window_s": seconds,
            "enabled": TIMESERIES.enabled,
            "wall": TIMESERIES.window(SERIES_ADMISSION, seconds),
            "virtual": TIMESERIES.window(SERIES_ADMISSION_VT, seconds),
        }

    def decomposition(self) -> dict:
        """Admission-latency p50/p99 per segment over completed journeys —
        the bench's first-class field."""
        samples: Dict[str, List[float]] = {seg: [] for seg in JOURNEY_SEGMENTS}
        totals: List[float] = []
        for j in self.completed():
            if j.segments is None:
                continue
            for seg in JOURNEY_SEGMENTS:
                samples[seg].append(j.segments[seg])
            totals.append(sum(j.segments.values()))
        totals.sort()
        doc = {
            "journeys": len(totals),
            "completed_total": self.completed_total,
            "admission_p50_s": round(_quantile(totals, 0.5), 6)
            if totals
            else 0.0,
            "admission_p99_s": round(_quantile(totals, 0.99), 6)
            if totals
            else 0.0,
            "segments": {},
        }
        for seg in JOURNEY_SEGMENTS:
            vals = sorted(samples[seg])
            doc["segments"][seg] = {
                "p50_s": round(_quantile(vals, 0.5), 6) if vals else 0.0,
                "p99_s": round(_quantile(vals, 0.99), 6) if vals else 0.0,
                "total_s": round(sum(vals), 6),
            }
        return doc

    def critical_path(self) -> dict:
        """Top-down converge explanation: per-segment share of total
        admission latency across every completed journey, plus the TAIL
        journey (latest completion) decomposed — the gang whose journey
        bounds the converge wall."""
        per_seg: Dict[str, float] = {seg: 0.0 for seg in JOURNEY_SEGMENTS}
        tail: Optional[_Journey] = None
        tail_t = -1.0
        n = 0
        for j in self.completed():
            if j.segments is None:
                continue
            n += 1
            for seg in JOURNEY_SEGMENTS:
                per_seg[seg] += j.segments[seg]
            done_t = j.marks.get("scheduled", (0.0, None))[0]
            if done_t > tail_t:
                tail_t, tail = done_t, j
        total = sum(per_seg.values())
        doc = {
            "journeys": n,
            "total_s": round(total, 6),
            "segments": {
                seg: {
                    "total_s": round(v, 6),
                    "share": round(v / total, 4) if total > 0 else 0.0,
                }
                for seg, v in per_seg.items()
            },
        }
        if tail is not None:
            doc["tail"] = tail.as_dict()
        return doc


JOURNEYS = JourneyTracker()
