"""Host-environment block for bench/smoke artifacts (docs/control-plane.md
§5 "honest measurement").

Every speedup — or bounded-overhead — claim the bench family makes is a
function of the box it ran on: a 1-core cgroup-throttled container cannot
show parallel speedup no matter how clean the ownership boundaries are,
and a GIL build caps thread-backend scaling regardless of cores. The
``"host"`` block stamps the facts into the artifact so the claim is
auditable after the fact: logical CPU count, the cgroup CPU quota actually
enforced on the container (v2 ``cpu.max``, v1 ``cfs_quota_us``/
``cfs_period_us``), the Python version, whether this is a free-threading
(no-GIL) build, and which control-plane executor backend produced the
numbers.
"""

from __future__ import annotations

import os
import sys
from typing import Optional


def _cgroup_cpu_quota() -> Optional[float]:
    """Effective CPU limit in cores from the cgroup, None when unlimited
    or unreadable. Reads v2 first (`cpu.max`: "<quota> <period>" or
    "max <period>"), then the v1 cfs pair."""
    try:
        with open("/sys/fs/cgroup/cpu.max", "r", encoding="ascii") as fh:
            quota_s, period_s = fh.read().split()
        if quota_s == "max":
            return None
        return round(int(quota_s) / int(period_s), 3)
    except (OSError, ValueError):
        pass
    try:
        with open(
            "/sys/fs/cgroup/cpu/cpu.cfs_quota_us", "r", encoding="ascii"
        ) as fh:
            quota = int(fh.read().strip())
        if quota <= 0:
            return None
        with open(
            "/sys/fs/cgroup/cpu/cpu.cfs_period_us", "r", encoding="ascii"
        ) as fh:
            period = int(fh.read().strip())
        return round(quota / period, 3)
    except (OSError, ValueError):
        return None


def host_block(backend: Optional[str] = None) -> dict:
    """The artifact ``"host"`` block. ``backend`` names the control-plane
    executor that produced the surrounding numbers ("serial", "thread",
    "process") when the caller knows it; omitted otherwise."""
    block = {
        "nproc": os.cpu_count(),
        "cgroup_cpu_quota": _cgroup_cpu_quota(),
        "python": sys.version.split()[0],
        # free-threading builds report GIL absence here; GIL builds (and
        # pythons predating the flag) report False — the honesty flag for
        # every thread-backend scaling claim
        "free_threading": not getattr(sys, "_is_gil_enabled", lambda: True)(),
    }
    if backend is not None:
        block["backend"] = backend
    return block
