"""Minimal metrics registry (counter/gauge/histogram).

Stands in for the controller-runtime Prometheus metrics server the reference
exposes (manager.go:88-90). Exportable as Prometheus text format for a real
deployment; in the sim it feeds assertions and the bench report.
"""

from __future__ import annotations

import math
import threading
from collections import defaultdict
from typing import Dict, List


class Metrics:
    """Thread-safe: reconcile worker threads (Engine.drain_concurrent) and
    watch threads observe concurrently; unsynchronized += would silently
    lose increments and break the monotonic-counter contract scrapers rely
    on."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = defaultdict(float)
        self.gauges: Dict[str, float] = {}
        self.histograms: Dict[str, List[float]] = defaultdict(list)
        # cumulative across window trims — the exported _count/_sum series
        # must be monotonic or scrapers read every trim as a counter reset
        self.hist_count: Dict[str, float] = defaultdict(float)
        self.hist_sum: Dict[str, float] = defaultdict(float)

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self.counters[name] += value

    def set(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = value

    # long-running operators observe forever: percentiles come from a
    # bounded recent window; _count/_sum stay cumulative across trims
    MAX_SAMPLES = 4096

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            values = self.histograms[name]
            values.append(value)
            self.hist_count[name] += 1
            self.hist_sum[name] += value
            if len(values) > self.MAX_SAMPLES:
                del values[: self.MAX_SAMPLES // 2]

    def percentile(self, name: str, q: float) -> float:
        with self._lock:
            values = sorted(self.histograms.get(name, []))
        return _quantile(values, q)

    def reset(self) -> None:
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self.histograms.clear()
            self.hist_count.clear()
            self.hist_sum.clear()

    def prometheus_text(self) -> str:
        # snapshot under the lock: a scrape during concurrent writes must
        # not hit "dict changed size during iteration"
        with self._lock:
            self_counters = dict(self.counters)
            self_gauges = dict(self.gauges)
            self_hists = {k: list(v) for k, v in self.histograms.items()}
            hist_count = dict(self.hist_count)
            hist_sum = dict(self.hist_sum)
        lines = []
        for name, v in sorted(self_counters.items()):
            lines.append(f"{_promname(name)} {v}")
        for name, v in sorted(self_gauges.items()):
            lines.append(f"{_promname(name)} {v}")
        for name, values in sorted(self_hists.items()):
            base, label = _prom_parts(name)
            lines.append(
                f"{base}_count{label and '{' + label + '}'} "
                f"{hist_count.get(name, 0.0)}"
            )
            lines.append(
                f"{base}_sum{label and '{' + label + '}'} "
                f"{hist_sum.get(name, 0.0)}"
            )
            # an empty recent window would render `nan` quantile samples —
            # invalid for many scrapers; _count/_sum above still expose the
            # cumulative series, so skipping the quantile lines is lossless
            window = sorted(values)
            if not window:
                continue
            for q in (0.5, 0.9, 0.99):
                qlabel = f'quantile="{q}"' + (f",{label}" if label else "")
                lines.append(f"{base}{{{qlabel}}} {_quantile(window, q)}")
        return "\n".join(lines) + "\n"


def _quantile(sorted_values: List[float], q: float) -> float:
    """Single home for the quantile index arithmetic (Metrics.percentile and
    the Prometheus exposition must never diverge)."""
    if not sorted_values:
        return math.nan
    idx = min(
        len(sorted_values) - 1, max(0, math.ceil(q * len(sorted_values)) - 1)
    )
    return sorted_values[idx]


def _prom_parts(name: str):
    """Registry-name grammar → (prometheus name, label string).

    ``base`` → no labels; ``base/label`` → ``name="label"``;
    ``base@K`` → ``shard="K"``; ``base/label@K`` → both. The ``@shard``
    suffix is how per-keyspace-shard series (engine backlogs, shard
    census, pending feeds, WAL streams) expose the shard as a first-class
    Prometheus label instead of overloading ``name=`` — so PR 13's
    concurrent per-shard workers can be graphed with a `by (shard)`."""
    shard = None
    if "@" in name:
        name, _, shard = name.rpartition("@")
    labels = []
    if "/" in name:
        name, _, label = name.partition("/")
        labels.append(f'name="{label}"')
    if shard is not None:
        labels.append(f'shard="{shard}"')
    return f"grove_tpu_{name}", ",".join(labels)


def _promname(name: str) -> str:
    base, label = _prom_parts(name)
    return f"{base}{{{label}}}" if label else base


METRICS = Metrics()
