"""Minimal metrics registry (counter/gauge/histogram).

Stands in for the controller-runtime Prometheus metrics server the reference
exposes (manager.go:88-90). Exportable as Prometheus text format for a real
deployment; in the sim it feeds assertions and the bench report.
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import Dict, List


class Metrics:
    def __init__(self) -> None:
        self.counters: Dict[str, float] = defaultdict(float)
        self.gauges: Dict[str, float] = {}
        self.histograms: Dict[str, List[float]] = defaultdict(list)
        # cumulative across window trims — the exported _count/_sum series
        # must be monotonic or scrapers read every trim as a counter reset
        self.hist_count: Dict[str, float] = defaultdict(float)
        self.hist_sum: Dict[str, float] = defaultdict(float)

    def inc(self, name: str, value: float = 1.0) -> None:
        self.counters[name] += value

    def set(self, name: str, value: float) -> None:
        self.gauges[name] = value

    # long-running operators observe forever: percentiles come from a
    # bounded recent window; _count/_sum stay cumulative across trims
    MAX_SAMPLES = 4096

    def observe(self, name: str, value: float) -> None:
        values = self.histograms[name]
        values.append(value)
        self.hist_count[name] += 1
        self.hist_sum[name] += value
        if len(values) > self.MAX_SAMPLES:
            del values[: self.MAX_SAMPLES // 2]

    def percentile(self, name: str, q: float) -> float:
        values = sorted(self.histograms.get(name, []))
        if not values:
            return math.nan
        idx = min(len(values) - 1, max(0, math.ceil(q * len(values)) - 1))
        return values[idx]

    def reset(self) -> None:
        self.counters.clear()
        self.gauges.clear()
        self.histograms.clear()
        self.hist_count.clear()
        self.hist_sum.clear()

    def prometheus_text(self) -> str:
        lines = []
        for name, v in sorted(self.counters.items()):
            lines.append(f"{_promname(name)} {v}")
        for name, v in sorted(self.gauges.items()):
            lines.append(f"{_promname(name)} {v}")
        for name, values in sorted(self.histograms.items()):
            base, label = _prom_parts(name)
            lines.append(
                f"{base}_count{label and '{' + label + '}'} "
                f"{self.hist_count[name]}"
            )
            lines.append(
                f"{base}_sum{label and '{' + label + '}'} {self.hist_sum[name]}"
            )
            for q in (0.5, 0.9, 0.99):
                qlabel = f'quantile="{q}"' + (f",{label}" if label else "")
                lines.append(f"{base}{{{qlabel}}} {self.percentile(name, q)}")
        return "\n".join(lines) + "\n"


def _prom_parts(name: str):
    if "/" in name:
        base, label = name.split("/", 1)
        return f"grove_tpu_{base}", f'name="{label}"'
    return f"grove_tpu_{name}", ""


def _promname(name: str) -> str:
    base, label = _prom_parts(name)
    return f"{base}{{{label}}}" if label else base


METRICS = Metrics()
