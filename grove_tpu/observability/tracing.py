"""Span tracing: the latency layer the metrics registry cannot provide.

The reference operator leans on controller-runtime's Prometheus server for
counters; the question it cannot answer — "why did gang X take 8s to
place?" — needs spans. This module provides:

- ``Span``: a named, timed interval with kv attributes and a parent link
  (nesting via a per-thread span stack, so ``engine.reconcile`` naturally
  parents whatever a reconcile opens, and ``scheduler.schedule`` parents
  encode/solve/commit/status-write).
- ``Tracer``: a thread-safe, bounded in-memory collector exporting
  (1) a JSON summary — per-span-name count/total/p50/p99 — and
  (2) Chrome ``trace_event`` format (an array of ``ph:"X"`` complete
  events) loadable by ``chrome://tracing`` and Perfetto.

Cost model: tracing is OFF by default; every instrumentation site reduces
to a single ``TRACER.enabled`` boolean check (``span()`` returns a shared
no-op span), so tier-1 runtime and the bench's hot path are unaffected.
Durations come from ``time.perf_counter()`` (real latency is the point);
when a virtual clock is attached (``TRACER.clock``), every span also
carries the virtual timestamp as a ``vt`` attribute so sim traces can be
correlated with virtual-time requeue math.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from grove_tpu.observability.metrics import _quantile


class _NullSpan:
    """Shared no-op span returned while tracing is disabled: instrumented
    code never branches on enablement beyond the one check in span()."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, key: str, value) -> None:
        pass

    def end(self) -> None:
        pass


_NULL_SPAN = _NullSpan()

# Sanitizer hook (grove_tpu.analysis.sanitize): an object with
# span_opened(span)/span_closed(span), installed only under
# GROVE_TPU_SANITIZE=1 for leaked-span detection. One global load per
# span lifecycle when tracing is on; no cost while tracing is off.
SPAN_HOOK = None

# Flight-recorder sink (grove_tpu.observability.flightrec): an object with
# note_span(span), installed by FLIGHTREC.enable() so finished spans land
# in the per-shard postmortem rings. Same cost contract as SPAN_HOOK.
FLIGHT_SINK = None


class Span:
    __slots__ = (
        "name",
        "attrs",
        "parent",
        "tid",
        "ts_us",
        "dur_us",
        "_t0",
        "_tracer",
        "_done",
    )

    def __init__(self, tracer: "Tracer", name: str, attrs: dict) -> None:
        self.name = name
        self.attrs = attrs
        self._tracer = tracer
        self.tid = threading.get_ident()
        stack = tracer._stack()
        self.parent = stack[-1].name if stack else None
        stack.append(self)
        if tracer.clock is not None:
            attrs["vt"] = round(tracer.clock.now(), 3)
        # shard attribution (docs/control-plane.md keyspace sharding): the
        # engine stamps the owning shard around each reconcile, so every
        # span opened inside it carries its lane; explicit attrs win
        if "shard" not in attrs:
            shard = getattr(tracer._tls, "shard", None)
            if shard is not None:
                attrs["shard"] = shard
        # worker attribution (docs/control-plane.md §5 parallel control
        # plane): the parallel drain stamps the owning worker around each
        # group, so spans from concurrent reconciles render as separate
        # worker lanes alongside the shard column
        if "worker" not in attrs:
            worker = getattr(tracer._tls, "worker", None)
            if worker is not None:
                attrs["worker"] = worker
        self._done = False
        if SPAN_HOOK is not None:
            SPAN_HOOK.span_opened(self)
        self._t0 = time.perf_counter()
        self.ts_us = int((self._t0 - tracer._origin) * 1e6)
        self.dur_us = 0

    def set(self, key: str, value) -> None:
        self.attrs[key] = value

    def end(self) -> None:
        if self._done:
            return
        self._done = True
        if SPAN_HOOK is not None:
            SPAN_HOOK.span_closed(self)
        self.dur_us = int((time.perf_counter() - self._t0) * 1e6)
        if FLIGHT_SINK is not None:
            FLIGHT_SINK.note_span(self)
        tracer = self._tracer
        stack = tracer._stack()
        # tolerate out-of-order ends (a span ended from a finally after its
        # child leaked): drop this span from wherever it sits in the stack
        if self in stack:
            stack.remove(self)
        with tracer._lock:
            tracer._spans.append(self)
            tracer.recorded += 1

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> bool:
        self.end()
        return False


class Tracer:
    """Bounded in-memory span collector (oldest spans drop when full)."""

    def __init__(self, max_spans: int = 20_000, clock=None) -> None:
        self.enabled = os.environ.get("GROVE_TPU_TRACE", "") not in (
            "",
            "0",
            "false",
        )
        self.max_spans = max_spans
        # virtual clock (optional): spans carry its reading as a `vt` attr
        self.clock = clock
        self.recorded = 0
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=max_spans)
        self._origin = time.perf_counter()
        self._tls = threading.local()

    # -- lifecycle -------------------------------------------------------

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
            self.recorded = 0
        self._origin = time.perf_counter()

    # -- recording -------------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def span(self, name: str, **attrs):
        """Open a span (context manager, or call .end() explicitly).
        The disabled path is ONE attribute check + a shared no-op object."""
        if not self.enabled:
            return _NULL_SPAN
        return Span(self, name, attrs)

    def current_span(self):
        stack = self._stack()
        return stack[-1] if stack else None

    def set_shard(self, shard: Optional[int]) -> None:
        """Per-thread shard context: spans opened after this carry the
        shard as an attribute (and the Chrome export's `shard` column)
        until cleared with None. Set by the engine around each reconcile
        when sharded; costs nothing while tracing is off (only called
        behind the enabled check)."""
        self._tls.shard = shard

    def set_worker(self, worker: Optional[int]) -> None:
        """Per-thread worker identity (the parallel control plane's
        extension of the shard context, docs/control-plane.md §5): spans
        opened after this carry the reconcile worker as an attribute
        until cleared with None. Same cost contract as set_shard."""
        self._tls.worker = worker

    # -- export ----------------------------------------------------------

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-span-name aggregate: count, total/p50/p99/max seconds."""
        by_name: Dict[str, List[int]] = {}
        for sp in self.spans():
            by_name.setdefault(sp.name, []).append(sp.dur_us)
        out: Dict[str, Dict[str, float]] = {}
        for name, durs in sorted(by_name.items()):
            durs.sort()
            out[name] = {
                "count": len(durs),
                "total_s": round(sum(durs) / 1e6, 6),
                "p50_s": round(_quantile(durs, 0.5) / 1e6, 6),
                "p99_s": round(_quantile(durs, 0.99) / 1e6, 6),
                "max_s": round(durs[-1] / 1e6, 6),
            }
        return out

    def summary_json(self) -> dict:
        return {
            "enabled": self.enabled,
            "recorded": self.recorded,
            "retained": len(self._spans),
            "dropped": max(0, self.recorded - len(self._spans)),
            "spans": self.summary(),
        }

    def slowest(self, n: int = 10) -> List[Span]:
        return sorted(self.spans(), key=lambda s: -s.dur_us)[:n]

    def chrome_trace(self) -> List[dict]:
        """Chrome trace_event complete events ("ph":"X"), ts/dur in µs.
        A JSON array — chrome://tracing and Perfetto load it directly;
        nesting is by time containment within (pid, tid). Every event
        carries a `shard` column (the span's keyspace-shard attribution,
        -1 for unsharded/cluster-wide work) so per-shard workers render
        as separate lanes when grouped by it."""
        pid = os.getpid()
        events = []
        for sp in self.spans():
            shard = sp.attrs.get("shard")
            events.append(
                {
                    "name": sp.name,
                    "ph": "X",
                    "ts": sp.ts_us,
                    "dur": sp.dur_us,
                    "pid": pid,
                    "tid": sp.tid,
                    "shard": shard if isinstance(shard, int) else -1,
                    "args": dict(sp.attrs, parent=sp.parent),
                }
            )
        events.sort(key=lambda e: e["ts"])
        return events


def validate_chrome_trace(events) -> List[str]:
    """Well-formedness check shared by `make trace-smoke` and the tier-1
    test: an array of objects each carrying ph/ts/name (dur for "X"
    events). Returns a list of problems (empty = valid)."""
    problems: List[str] = []
    if not isinstance(events, list):
        return [f"top-level JSON must be an array, got {type(events).__name__}"]
    if not events:
        problems.append("trace is empty (tracing enabled?)")
    for i, ev in enumerate(events[:10_000]):
        if not isinstance(ev, dict):
            problems.append(f"event {i} is not an object")
            continue
        for field in ("ph", "ts", "name"):
            if field not in ev:
                problems.append(f"event {i} missing {field!r}")
        if ev.get("ph") == "X" and not isinstance(ev.get("dur"), int):
            problems.append(f"event {i} ('X') missing integer 'dur'")
        if not isinstance(ev.get("ts"), int):
            problems.append(f"event {i} 'ts' must be an integer (µs)")
    return problems


TRACER = Tracer()
