"""Causal decision→effect ledger for the remediation loop.

Automated actions are only trustworthy when every one of them can be
audited after the fact: WHAT fired (trigger), WHY the controller believed
acting would help (diagnosis: the explain verdict cited, by gang), HOW it
proved the action before committing (simulation: the what-if trial's
``flipped`` verdict), WHAT it actually did (action: broker grant id,
drain / migration / scale-up), and WHAT HAPPENED (measured effect: the
SLO error-budget delta over the effect window). ``LEDGER`` is the
bounded, vt-stamped ring of those causal chains — the
``controller/remediate.py`` policy writes one entry per considered
action (grovelint GL019 ``act-must-log`` enforces that every act call in
that module has an in-function ledger write), and nothing else writes
here.

Each ``record()`` also emits a ``RemediationExecuted`` /
``RemediationSkipped`` Event and bumps the
``remediation_actions_total/<kind>/<outcome>`` counter, so the chains
flow into ``FLIGHTREC`` bundles through the event sink and into the
Prometheus surface without a second bookkeeping path. Effects land later
(``effect(entry_id, ...)``) once the effect window has elapsed.

Surfaced at ``GET /debug/ledger`` + ``cli ledger``. Off by default
(``GROVE_TPU_LEDGER=1`` / ``LEDGER.enable()``), one-boolean-check
discipline; ring internals are private to this module (GL019).
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import List, Optional

from grove_tpu.observability.events import (
    EVENTS,
    REASON_REMEDIATION_EXECUTED,
    REASON_REMEDIATION_SKIPPED,
    TYPE_NORMAL,
    TYPE_WARNING,
)
from grove_tpu.observability.metrics import METRICS

# The closed vocabulary of causal-chain heads and tails. Docs-drift
# (tests/test_docs_drift.py) pins ACTION_KINDS against the
# docs/observability.md "Action kinds" table; grovelint GL006-style
# registry discipline, ledger edition.
TRIGGER_SLO_BURN = "slo-burn"  # SloBurnRateHigh from the observatory
TRIGGER_FORECAST_PEAK = "forecast-peak"  # forecast band crosses threshold
TRIGGER_FRAG_THRESHOLD = "frag-threshold"  # fragmentation score too high
TRIGGER_FAILSLOW = "fail-slow"  # node Degraded by the suspicion EWMA

TRIGGER_KINDS = (
    TRIGGER_SLO_BURN,
    TRIGGER_FORECAST_PEAK,
    TRIGGER_FRAG_THRESHOLD,
    TRIGGER_FAILSLOW,
)

ACTION_DRAIN_NODE = "drain-node"  # drain a flapping/filler node
ACTION_MIGRATE_GANG = "migrate-gang"  # budget-gated defrag migration
ACTION_SCALE_UP = "scale-up"  # preemptive HPA raise ahead of the peak

ACTION_KINDS = (
    ACTION_DRAIN_NODE,
    ACTION_MIGRATE_GANG,
    ACTION_SCALE_UP,
)

OUTCOME_EXECUTED = "executed"
OUTCOME_SKIPPED = "skipped"

DEFAULT_CAPACITY = 256


class DecisionLedger:
    """Process-global (``LEDGER``), thread-safe, bounded ring of causal
    decision→effect entries."""

    def __init__(self) -> None:
        self.enabled = os.environ.get("GROVE_TPU_LEDGER", "") not in (
            "",
            "0",
            "false",
        )
        self.clock = None
        self._lock = threading.Lock()
        self._entries: deque = deque(maxlen=DEFAULT_CAPACITY)
        self._seq = 0

    # -- lifecycle -------------------------------------------------------

    def enable(
        self, capacity: int = DEFAULT_CAPACITY, clock=None
    ) -> "DecisionLedger":
        with self._lock:
            self._entries = deque(self._entries, maxlen=max(8, capacity))
            if clock is not None:
                self.clock = clock
            self.enabled = True
        return self

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
            self._seq = 0
            self.clock = None

    def _vt(self, now: Optional[float]) -> float:
        if now is not None:
            return now
        return self.clock.now() if self.clock is not None else 0.0

    # -- writes (controller/remediate.py only — GL019) -------------------

    def record(
        self,
        trigger_kind: str,
        action_kind: str,
        outcome: str,
        trigger_detail: str = "",
        diagnosis: Optional[dict] = None,
        simulation: Optional[dict] = None,
        action: Optional[dict] = None,
        reason: str = "",
        now: Optional[float] = None,
    ) -> Optional[int]:
        """Append one causal chain; returns the entry id (None when the
        ledger is off). ``diagnosis`` cites the explain verdict by gang
        (``{"gang", "binding_constraint", "detail"}``), ``simulation`` the
        what-if trial (``{"flipped", "actions"}``), ``action`` the
        executed mechanics (``{"target", "grant", ...}``); ``reason``
        says why a skipped entry was skipped."""
        if not self.enabled:
            return None
        vt = self._vt(now)
        with self._lock:
            self._seq += 1
            entry = {
                "id": self._seq,
                "vt": vt,
                "trigger": {"kind": trigger_kind, "detail": trigger_detail},
                "diagnosis": diagnosis or {},
                "simulation": simulation or {},
                "action": dict({"kind": action_kind}, **(action or {})),
                "outcome": outcome,
                "reason": reason,
                "effect": None,
            }
            self._entries.append(entry)
        METRICS.inc(f"remediation_actions_total/{action_kind}/{outcome}")
        executed = outcome == OUTCOME_EXECUTED
        target = (action or {}).get("target", "") or (diagnosis or {}).get(
            "gang", ""
        )
        if executed:
            event_type, event_reason = (
                TYPE_NORMAL, REASON_REMEDIATION_EXECUTED,
            )
        else:
            event_type, event_reason = (
                TYPE_WARNING, REASON_REMEDIATION_SKIPPED,
            )
        EVENTS.record(
            ("Remediation", "", target or "cluster"),
            event_type,
            event_reason,
            f"{trigger_kind} -> {action_kind}"
            + (f" on {target}" if target else "")
            + (f": {reason}" if reason else ""),
        )
        return entry["id"]

    def effect(
        self,
        entry_id: int,
        window_s: float,
        budget_before: Optional[float],
        budget_after: Optional[float],
        now: Optional[float] = None,
    ) -> bool:
        """Close the chain: the measured SLO error-budget delta over the
        effect window. Returns False for unknown/evicted entries."""
        if not self.enabled:
            return False
        vt = self._vt(now)
        with self._lock:
            for entry in self._entries:
                if entry["id"] != entry_id:
                    continue
                delta = (
                    budget_after - budget_before
                    if budget_after is not None and budget_before is not None
                    else None
                )
                entry["effect"] = {
                    "vt": vt,
                    "window_s": window_s,
                    "budget_before": budget_before,
                    "budget_after": budget_after,
                    "budget_delta": delta,
                }
                return True
        return False

    # -- reads -----------------------------------------------------------

    def entries(
        self,
        outcome: Optional[str] = None,
        action_kind: Optional[str] = None,
    ) -> List[dict]:
        with self._lock:
            rows = [dict(e) for e in self._entries]
        return [
            e
            for e in rows
            if (outcome is None or e["outcome"] == outcome)
            and (action_kind is None or e["action"]["kind"] == action_kind)
        ]

    def status(self) -> dict:
        """The ``GET /debug/ledger`` document: the ring plus per-kind /
        per-outcome tallies and the flip-confirmed rate."""
        with self._lock:
            rows = [dict(e) for e in self._entries]
            total = self._seq
        by_kind: dict = {}
        executed = skipped = flipped = simulated = 0
        measured = []
        for e in rows:
            kind = e["action"]["kind"]
            out = e["outcome"]
            by_kind.setdefault(kind, {}).setdefault(out, 0)
            by_kind[kind][out] += 1
            if out == OUTCOME_EXECUTED:
                executed += 1
                # flip confirmation only applies to structural actions
                # that ran a what-if trial (scale-ups carry flipped=None)
                if e["simulation"].get("flipped") is not None:
                    simulated += 1
                    if e["simulation"]["flipped"]:
                        flipped += 1
            else:
                skipped += 1
            eff = e.get("effect")
            if eff and eff.get("budget_delta") is not None:
                measured.append(eff["budget_delta"])
        return {
            "enabled": self.enabled,
            "recorded_total": total,
            "retained": len(rows),
            "executed": executed,
            "skipped": skipped,
            "flip_confirmed_rate": (
                (flipped / simulated) if simulated else None
            ),
            "mean_budget_delta": (
                sum(measured) / len(measured) if measured else None
            ),
            "by_kind": by_kind,
            "entries": rows,
        }


LEDGER = DecisionLedger()
