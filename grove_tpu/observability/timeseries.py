"""Bounded virtual-clock time-series engine: the observatory's memory.

Everything observability built so far is a *snapshot*: the profiler's
ledger, a journey's decomposition, an explain verdict all answer "what is
true now / what happened to this one gang". None of them can answer the
serving questions ROADMAP's SLO item asks — *what was admission p99 over
the last five minutes, how fast is the ready fraction falling, is the
queue wait trending up through the flash crowd?* — because nothing keeps
**windowed history**. This module is that history:

- ``TIMESERIES.gauge(name, v)`` / ``.observe(name, v)`` fold samples into
  a **bounded ring of per-tick cells** keyed by the virtual clock
  (``int(vt // resolution)``). Gauges keep one value per tick (last write
  wins — the sampler's cadence IS the resolution); distributions keep
  per-tick ``(count, total, max, log-bucket counts)`` rows reusing the
  PR-12 power-of-two-µs bucketing, so a tick holding 10k admission
  latencies costs the same as one holding 3.
- ``TIMESERIES.sample(now)`` runs at tick boundaries (the harness owns
  the cadence): it executes registered collectors — the **serving
  signals**: per-PCS ready-replica fraction from the level-2 pod
  aggregates, per-tenant queue wait from the pending journeys, per-queue
  usage from the quota accountant — and mirrors tracked counters from
  the metrics registry as per-tick rate samples.
- ``TIMESERIES.window(name, seconds)`` reduces the ring over
  ``(now - seconds, now]``: rate/mean/max/min/last plus p50/p99 (exact
  over gauge samples; bucket-interpolated over distribution rows). The
  reducer arithmetic is **pinned bit-equal to a plain-NumPy oracle** over
  seeded storms (tests/test_slo_observatory.py), ring wraparound and
  sparse/empty windows included — the SLO layer's attainment math is
  only as honest as these reductions.

Cost discipline (PR 1): **off by default**, every feed site reduces to a
single ``TIMESERIES.enabled`` boolean while disabled; enable with
``GROVE_TPU_TIMESERIES=1`` or ``TIMESERIES.enable()``. Ring/window
internals are private to this module and ``slo.py`` — grovelint GL017.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from grove_tpu.observability.metrics import METRICS

# power-of-two µ-unit buckets, shared with the PR-12 profiler histograms:
# bucket b spans [2^(b-1), 2^b) µ-units, quantiles interpolate at the
# geometric midpoint 1.5 * 2^(b-1) (b=0 -> 0.5µ)
N_BUCKETS = 64

# default ring capacity in ticks: at 1 s resolution this is ~68 minutes
# of history — enough for a 1 h slow-burn window with room to spare
DEFAULT_CAPACITY = 4096

# Serving-signal series the installed collector feeds (the closed
# registry docs/observability.md's "Serving signals" table pins, the
# event-reason treatment): admission latency is pushed by the journey
# tracker on completion; the rest are pulled per sample() round.
SERIES_ADMISSION = "admission_latency"  # wall seconds, per completed gang
SERIES_ADMISSION_VT = "admission_latency_vt"  # virtual seconds, same gangs
SERIES_READY_FRACTION = "ready_fraction"  # ready/desired, cluster + per-PCS
SERIES_QUEUE_WAIT = "queue_wait_vt"  # oldest pending journey age, per tenant
SERIES_QUEUE_USAGE = "queue_usage"  # accountant cpu usage, per queue
SERIES_SCALEUP_LATENCY = "scaleup_latency_vt"  # HPA bump -> ready, virtual s

SERVING_SIGNALS = (
    SERIES_ADMISSION,
    SERIES_ADMISSION_VT,
    SERIES_READY_FRACTION,
    SERIES_QUEUE_WAIT,
    SERIES_QUEUE_USAGE,
    SERIES_SCALEUP_LATENCY,
)


def bucket_of(units: int) -> int:
    """Log bucket index of a non-negative integer µ-unit value (the
    profiler's ``us.bit_length()`` rule, one home for the SLO layer and
    the NumPy oracle to share)."""
    idx = units.bit_length()
    return idx if idx < N_BUCKETS else N_BUCKETS - 1


def bucket_value(b: int) -> float:
    """Representative µ-unit value of bucket ``b`` (geometric midpoint)."""
    return 0.5 if b == 0 else 1.5 * float(1 << (b - 1))


class _GaugeRing:
    """One gauge series: per-tick last-written value in a bounded ring.
    ``_stamps[i]`` records which tick owns slot ``i`` — a slot whose stamp
    is not the probed tick is stale (wrapped past) and reads as absent."""

    __slots__ = ("_stamps", "_values", "capacity")

    kind = "gauge"

    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        self._stamps = [-1] * capacity
        self._values = [0.0] * capacity

    def put(self, tick: int, value: float) -> None:
        slot = tick % self.capacity
        self._stamps[slot] = tick
        self._values[slot] = float(value)

    def window_values(self, t0: int, t1: int) -> List[float]:
        """Samples with tick in (t0, t1], in tick order. Clamped to tick
        0: virtual time starts at zero, and a negative probe tick would
        alias the ring's -1 initial stamps into phantom samples."""
        lo = max(t0 + 1, t1 - self.capacity + 1, 0)
        out = []
        for tick in range(lo, t1 + 1):
            slot = tick % self.capacity
            if self._stamps[slot] == tick:
                out.append(self._values[slot])
        return out

    def window_samples(self, t0: int, t1: int) -> List[Tuple[int, float]]:
        """Like window_values, but keeping each sample's tick — the
        forecaster fits trend/seasonality against tick positions, so
        sparse rings must not collapse into a dense sequence."""
        lo = max(t0 + 1, t1 - self.capacity + 1, 0)
        out = []
        for tick in range(lo, t1 + 1):
            slot = tick % self.capacity
            if self._stamps[slot] == tick:
                out.append((tick, self._values[slot]))
        return out


class _DistRing:
    """One distribution series: per-tick (count, total, max, buckets)
    aggregation rows. Values are folded as integer µ-units so the bucket
    math is exact and the window merge is pure integer arithmetic."""

    __slots__ = ("_stamps", "_counts", "_totals", "_maxes", "_buckets",
                 "capacity")

    kind = "dist"

    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        self._stamps = [-1] * capacity
        self._counts = [0] * capacity
        self._totals = [0] * capacity  # integer µ-units
        self._maxes = [0] * capacity
        self._buckets: List[Optional[List[int]]] = [None] * capacity

    def put(self, tick: int, value: float) -> None:
        slot = tick % self.capacity
        if self._stamps[slot] != tick:
            self._stamps[slot] = tick
            self._counts[slot] = 0
            self._totals[slot] = 0
            self._maxes[slot] = 0
            self._buckets[slot] = [0] * N_BUCKETS
        units = int(value * 1e6)
        if units < 0:
            units = 0
        row = self._buckets[slot]
        row[bucket_of(units)] += 1
        self._counts[slot] += 1
        self._totals[slot] += units
        if units > self._maxes[slot]:
            self._maxes[slot] = units

    def window_rows(
        self, t0: int, t1: int
    ) -> List[Tuple[int, int, int, List[int]]]:
        """(count, total, max, buckets) rows for ticks in (t0, t1],
        clamped to tick 0 (see window_values). Bucket rows are COPIED:
        the caller merges them outside the store lock, and a concurrent
        ``put`` into the same tick must not mutate a row mid-merge."""
        lo = max(t0 + 1, t1 - self.capacity + 1, 0)
        out = []
        for tick in range(lo, t1 + 1):
            slot = tick % self.capacity
            if self._stamps[slot] == tick and self._counts[slot]:
                out.append(
                    (
                        self._counts[slot],
                        self._totals[slot],
                        self._maxes[slot],
                        list(self._buckets[slot]),
                    )
                )
        return out


def dist_quantile_units(merged_buckets: np.ndarray, count: int, q: float) -> float:
    """Bucket-interpolated quantile over a merged bucket row, in µ-units —
    the PR-12 ``_Hist.quantile_us`` rule applied to a window merge. One
    home: the SLO layer, the journey window summary, and the NumPy oracle
    all call (or reproduce) exactly this."""
    if count == 0:
        return 0.0
    target = max(1, int(q * count + 0.5))
    cum = np.cumsum(merged_buckets)
    b = int(np.searchsorted(cum, target))
    return bucket_value(b)


class TimeSeriesStore:
    """Process-global (``TIMESERIES``), thread-safe, bounded: one ring per
    series name, O(capacity) memory per series regardless of sample
    volume. The virtual clock is authoritative — wall time never enters a
    ring, so seeded storms replay bit-identically."""

    def __init__(
        self, capacity: int = DEFAULT_CAPACITY, resolution: float = 1.0
    ) -> None:
        self.enabled = os.environ.get("GROVE_TPU_TIMESERIES", "") not in (
            "",
            "0",
            "false",
        )
        self.clock = None  # optional virtual clock (newest harness wins)
        self.capacity = capacity
        self.resolution = resolution
        self.tap: Optional[Callable[[str, int, float], None]] = None
        self._lock = threading.Lock()
        self._series: Dict[str, object] = {}
        self._collectors: List[Callable[[float], None]] = []
        self._tracked: Dict[str, float] = {}  # counter name -> last seen
        self._now = 0.0  # last sample() timestamp (vt)

    # -- lifecycle -------------------------------------------------------

    def enable(
        self,
        clock=None,
        capacity: Optional[int] = None,
        resolution: Optional[float] = None,
    ) -> "TimeSeriesStore":
        with self._lock:
            if clock is not None:
                self.clock = clock
            if capacity is not None:
                self.capacity = capacity
            if resolution is not None:
                self.resolution = resolution
            self.enabled = True
        return self

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._series = {}
            self._collectors = []
            self._tracked = {}
            self._now = 0.0

    # -- time ------------------------------------------------------------

    def _vt(self) -> float:
        return self.clock.now() if self.clock is not None else self._now

    def tick_of(self, vt: float) -> int:
        return int(vt // self.resolution)

    # -- feeds (one boolean check each when disabled) --------------------

    def _ring(self, name: str, cls):
        ring = self._series.get(name)
        if ring is None:
            ring = self._series[name] = cls(self.capacity)
        return ring

    def gauge(self, name: str, value: float, vt: Optional[float] = None) -> None:
        """Record a gauge sample at the current virtual tick (last write
        in a tick wins — the sampling cadence is the resolution)."""
        if not self.enabled:
            return
        tick = self.tick_of(vt if vt is not None else self._vt())
        with self._lock:
            self._ring(name, _GaugeRing).put(tick, value)
        if self.tap is not None:
            self.tap(name, tick, float(value))

    def observe(self, name: str, value: float, vt: Optional[float] = None) -> None:
        """Fold one observation into the tick's distribution row."""
        if not self.enabled:
            return
        tick = self.tick_of(vt if vt is not None else self._vt())
        with self._lock:
            self._ring(name, _DistRing).put(tick, value)
        if self.tap is not None:
            self.tap(name, tick, float(value))

    # -- sampling round (tick boundary) ----------------------------------

    def add_collector(self, fn: Callable[[float], None]) -> None:
        """Register a per-sample collector (called with the vt)."""
        with self._lock:
            self._collectors.append(fn)

    def remove_collector(self, fn: Callable[[float], None]) -> None:
        """Unregister a collector (scenario teardown: a collector's
        closure pins its harness, and a stale one firing on a later
        re-enable would feed gauges from a dead store)."""
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def track_counter(self, name: str) -> None:
        """Mirror a metrics-registry counter as a per-tick delta gauge
        series named ``rate:<counter>`` (the registry is cumulative; a
        window rate needs the per-tick increments)."""
        with self._lock:
            self._tracked.setdefault(name, METRICS.counters.get(name, 0.0))

    def sample(self, now: float) -> None:
        """One sampling round at a tick boundary: run every collector,
        then fold tracked counter deltas. The harness calls this per
        converge tick behind the one-boolean check."""
        if not self.enabled:
            return
        self._now = now
        for fn in list(self._collectors):
            fn(now)
        if self._tracked:
            for name in list(self._tracked):
                cur = METRICS.counters.get(name, 0.0)
                self.gauge(f"rate:{name}", cur - self._tracked[name], vt=now)
                self._tracked[name] = cur
        METRICS.inc("timeseries_samples_total")

    # -- windowed reducers -----------------------------------------------

    def window(
        self, name: str, seconds: float, now: Optional[float] = None
    ) -> dict:
        """Reduce ``name`` over the ticks in ``(now - seconds, now]``.

        Gauge series: ``n/mean/max/min/last/p50/p99`` (exact quantiles
        over the per-tick samples, the metrics.py index rule). Dist
        series: ``count/rate/mean/max/p50/p99`` (bucket-interpolated).
        Empty windows return ``{"n": 0}`` / ``{"count": 0}`` shells — the
        SLO layer treats them as "no data", never as zero latency.
        ``seconds`` is clamped to one resolution tick: the minimum
        meaningful window (and the rate divisor) is one tick, so a
        zero/negative request cannot divide by zero.
        """
        seconds = max(float(seconds), self.resolution)
        vt = now if now is not None else self._vt()
        t1 = self.tick_of(vt)
        t0 = t1 - max(1, int(round(seconds / self.resolution)))
        with self._lock:
            ring = self._series.get(name)
            if ring is None:
                return {"kind": "absent", "n": 0, "count": 0}
            if ring.kind == "gauge":
                values = ring.window_values(t0, t1)
            else:
                rows = ring.window_rows(t0, t1)
        if ring.kind == "gauge":
            if not values:
                return {"kind": "gauge", "n": 0}
            arr = np.asarray(values, dtype=np.float64)
            srt = np.sort(arr)
            return {
                "kind": "gauge",
                "n": int(arr.size),
                "mean": float(arr.sum() / arr.size),
                "max": float(srt[-1]),
                "min": float(srt[0]),
                "last": float(arr[-1]),
                "p50": float(srt[_q_idx(arr.size, 0.5)]),
                "p99": float(srt[_q_idx(arr.size, 0.99)]),
            }
        if not rows:
            return {"kind": "dist", "count": 0}
        count = sum(r[0] for r in rows)
        total = sum(r[1] for r in rows)
        mx = max(r[2] for r in rows)
        merged = np.sum(
            np.asarray([r[3] for r in rows], dtype=np.int64), axis=0
        )
        return {
            "kind": "dist",
            "count": int(count),
            "rate": float(count) / float(seconds),
            "mean": float(total) / float(count) / 1e6,
            "max": float(mx) / 1e6,
            "p50": dist_quantile_units(merged, count, 0.5) / 1e6,
            "p99": dist_quantile_units(merged, count, 0.99) / 1e6,
        }

    def reduce(
        self,
        name: str,
        reducer: str,
        seconds: float,
        now: Optional[float] = None,
    ) -> Optional[float]:
        """One reducer value, or None when the window holds no data —
        the SLO layer's read primitive."""
        doc = self.window(name, seconds, now=now)
        if doc.get("n", 0) == 0 and doc.get("count", 0) == 0:
            return None
        return doc.get(reducer)

    def gauge_samples(
        self, name: str, seconds: float, now: Optional[float] = None
    ) -> List[Tuple[int, float]]:
        """Raw per-tick ``(tick, value)`` gauge samples over the ticks in
        ``(now - seconds, now]`` — the forecaster's read primitive (ring
        internals stay private to this module, GL017). Returns ``[]`` for
        absent series and for distribution series (forecasting reduces
        gauges only; dist windows go through ``window()``)."""
        seconds = max(float(seconds), self.resolution)
        vt = now if now is not None else self._vt()
        t1 = self.tick_of(vt)
        t0 = t1 - max(1, int(round(seconds / self.resolution)))
        with self._lock:
            ring = self._series.get(name)
            if ring is None or ring.kind != "gauge":
                return []
            return ring.window_samples(t0, t1)

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def snapshot(self, seconds: float = 300.0) -> dict:
        """Every series reduced over one window (the /debug/slo report's
        series appendix)."""
        return {
            name: self.window(name, seconds) for name in self.series_names()
        }


def _q_idx(n: int, q: float) -> int:
    """The exact-quantile index rule (metrics.py::_quantile, restated for
    array indexing so the gauge reducers and the oracle agree bit-wise)."""
    return min(n - 1, max(0, math.ceil(q * n) - 1))


def install_serving_collector(
    store, scheduler=None, clock=None
) -> Callable[[float], None]:
    """Register the serving-signals collector: per sample round it feeds

    - ``ready_fraction`` (cluster-wide, from the level-2 pod aggregates'
      ``Store.pod_summary``) and ``ready_fraction/<ns>/<pcs>`` per
      PodCliqueSet (ready ÷ desired over its cliques' counter rows);
    - ``queue_wait_vt/<tenant>`` — the oldest pending journey age per
      namespace (virtual seconds), from the journey tracker;
    - ``queue_usage/<queue>`` — the quota accountant's cpu usage row.

    Returns the collector so scenarios can call it out-of-band."""
    from grove_tpu.api import names as namegen
    from grove_tpu.observability.journey import JOURNEYS

    def collect(now: float) -> None:
        total, ready = store.pod_summary()
        if total:
            TIMESERIES.gauge(
                SERIES_READY_FRACTION, ready / total, vt=now
            )
        # per-PCS ready fraction: desired from the PodClique specs, ready
        # from the same aggregate rows the PCLQ status controller reads
        for pcs in store.scan("PodCliqueSet"):
            ns = pcs.metadata.namespace
            desired = 0
            got = 0
            for pclq in store.scan("PodClique", ns):
                owner = pclq.metadata.labels.get(namegen.LABEL_PART_OF)
                if owner != pcs.metadata.name:
                    continue
                desired += int(pclq.spec.replicas or 0)
                got += store.pod_counters(ns, pclq.metadata.name).ready
            if desired:
                TIMESERIES.gauge(
                    f"{SERIES_READY_FRACTION}/{ns}/{pcs.metadata.name}",
                    got / desired,
                    vt=now,
                )
        for ns, age in JOURNEYS.pending_ages():
            TIMESERIES.gauge(f"{SERIES_QUEUE_WAIT}/{ns}", age, vt=now)
        if scheduler is not None and scheduler.quota.active():
            for queue, row in scheduler.quota.accountant.snapshot().items():
                TIMESERIES.gauge(
                    f"{SERIES_QUEUE_USAGE}/{queue}",
                    float(row.get("cpu", 0.0)),
                    vt=now,
                )

    if clock is not None:
        TIMESERIES.clock = clock
    TIMESERIES.add_collector(collect)
    return collect


TIMESERIES = TimeSeriesStore()
