"""Kubernetes-style Event recorder with dedup-and-count semantics.

Re-host of client-go's EventRecorder/EventCorrelator boundary: the reference
operator emits corev1 Events on every important transition and the apiserver
aggregates repeats into one Event with a bumped ``count``. Here the recorder
IS the aggregator: ``record(obj_ref, type, reason, message)`` dedups on
(kind, namespace, name, type, reason), bumps ``count``, and keeps
first/last timestamps — so "this gang was admitted 14 times" reads as one
line, not 14.

The recorder is process-global (``EVENTS``), mirroring how one event
broadcaster serves every controller in the reference manager; the sim
apiserver's ``GET /events`` endpoint and the CLI read from it. Bounded:
oldest dedup groups are evicted once ``max_events`` distinct groups exist.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import List, Optional, Tuple

TYPE_NORMAL = "Normal"
TYPE_WARNING = "Warning"

# canonical reasons emitted by the scheduler/controllers (docs/observability.md)
REASON_GANG_ADMITTED = "GangAdmitted"
REASON_GANG_DEFERRED = "GangDeferred"
REASON_POD_BOUND = "PodBound"
REASON_PREEMPTED = "Preempted"
REASON_ROLLING_UPDATE_STARTED = "RollingUpdateStarted"
# quota subsystem (docs/quota.md): a gang held back because its queue is at
# its ceiling, and a scheduled gang evicted so a queue below its deserved
# share can place (victim-side event naming the claimant)
REASON_QUEUE_PENDING = "QueuePending"
REASON_QUOTA_RECLAIM = "QuotaReclaim"
# node-failure lifecycle (docs/robustness.md, controller/nodehealth.py):
# heartbeat transitions, and the two gang-recovery outcomes — rescued
# (delta-solve rejoined the survivors' domain) vs. requeued (gang below
# its floor, torn down and re-admitted whole under backoff)
REASON_NODE_NOT_READY = "NodeNotReady"
REASON_NODE_LOST = "NodeLost"
REASON_NODE_READY = "NodeReady"
REASON_GANG_RESCUED = "GangRescued"
REASON_GANG_REQUEUED = "GangRequeued"
REASON_GANG_RELEASED = "GangBackoffReleased"
# voluntary-disruption layer (docs/robustness.md, grove_tpu/disruption):
# drain lifecycle, budget/breaker denials, and the breaker's state flips
REASON_NODE_DRAINING = "NodeDraining"
REASON_NODE_DRAINED = "NodeDrained"
REASON_NODE_UNCORDONED = "NodeUncordoned"
REASON_GANG_DRAINED = "GangDrained"
REASON_DISRUPTION_THROTTLED = "DisruptionThrottled"
REASON_BREAKER_OPEN = "BreakerOpen"
REASON_BREAKER_CLOSED = "BreakerClosed"
# durability layer (docs/robustness.md, grove_tpu/durability): periodic
# store snapshot + WAL truncation, crash-restart recovery finishing its
# snapshot-load + tail replay, and a torn WAL tail truncated at the first
# bad CRC during that replay
REASON_SNAPSHOT_TAKEN = "SnapshotTaken"
REASON_RECOVERY_COMPLETED = "RecoveryCompleted"
REASON_WAL_TORN_TAIL = "WalTornTail"
# glass-box layer (docs/observability.md "Flight recorder"): the chaos
# flight recorder froze its telemetry rings into a postmortem bundle
# (invariant violation, reconcile GroveError, breaker open, or explicit)
REASON_FLIGHT_RECORDED = "FlightRecorderDumped"
# SLO observatory (docs/observability.md "SLO observatory",
# observability/slo.py): an objective's compliance-window attainment
# dropped below target (breach, edge-triggered — also freezes a flight
# bundle), the multi-window burn rate crossed the paging factor on BOTH
# the fast and slow windows, and a breached objective re-attaining.
# grovelint GL017 pins every Slo*-family reason literal to this registry.
REASON_SLO_BREACH = "SloBreach"
REASON_SLO_BURN_RATE_HIGH = "SloBurnRateHigh"
REASON_SLO_RECOVERED = "SloRecovered"
# operator-component lifecycle reasons (controller/podcliqueset components,
# rolling update, gang termination) — emitted as literals at the call
# sites; registered here so grovelint GL006 and the docs-drift test keep
# the emitted set ⊆ this registry ⊆ docs/observability.md's catalog
REASON_GANG_TERMINATED = "GangTerminated"
REASON_SCALED_REPLICA_GANG_TERMINATED = "ScaledReplicaGangTerminated"
REASON_ROLLING_UPDATE_REPLICA_STARTED = "RollingUpdateReplicaStarted"
REASON_ROLLING_UPDATE_REPLICA_COMPLETED = "RollingUpdateReplicaCompleted"
REASON_ROLLING_UPDATE_COMPLETED = "RollingUpdateCompleted"
REASON_POD_CREATE_SUCCESSFUL = "PodCreateSuccessful"
REASON_POD_DELETE_SUCCESSFUL = "PodDeleteSuccessful"
REASON_POD_UPDATE_DELETE_SUCCESSFUL = "PodUpdateDeleteSuccessful"
REASON_POD_CLIQUE_CREATE_SUCCESSFUL = "PodCliqueCreateSuccessful"
REASON_POD_CLIQUE_DELETE_SUCCESSFUL = "PodCliqueDeleteSuccessful"
REASON_PCSG_CREATE_SUCCESSFUL = "PCSGCreateSuccessful"
REASON_PCSG_DELETE_SUCCESSFUL = "PCSGDeleteSuccessful"
REASON_PODGANG_CREATE_SUCCESSFUL = "PodGangCreateSuccessful"
REASON_PODGANG_DELETE_SUCCESSFUL = "PodGangDeleteSuccessful"
# remediation loop (docs/observability.md "Remediation & ledger",
# controller/remediate.py via observability/ledger.py): a ledger entry
# closed with an executed action (what-if-proven, broker-granted), or a
# considered remediation skipped with the reason recorded (not flipped,
# breaker open, budget denied, cooldown)
REASON_REMEDIATION_EXECUTED = "RemediationExecuted"
REASON_REMEDIATION_SKIPPED = "RemediationSkipped"

# federation tier (docs/federation.md, grove_tpu/federation/router.py):
# a gang moved off its home cluster because the home explain verdict
# said it cannot admit now; an entire region killed/restored
REASON_GANG_SPILLED = "GangSpilled"
REASON_CLUSTER_LOST = "ClusterLost"
REASON_CLUSTER_REJOINED = "ClusterRejoined"

# gray failures (docs/robustness.md "Gray failures"): the fail-slow
# suspicion EWMA masking/unmasking a node (controller/nodehealth.py), a
# federation region suspected partitioned vs. healed (federation/
# router.py — partition ≠ crash: the region is alive but unreachable),
# and the WAL degradation ladder (durability/wal.py — slow-fsync /
# disk-full faults step the store through a loud degraded / read-only
# mode instead of crashing). Every degraded-mode entry/exit site MUST
# emit one of these (grovelint GL022).
REASON_NODE_DEGRADED = "NodeDegraded"
REASON_NODE_RECOVERED = "NodeRecovered"
REASON_CLUSTER_PARTITIONED = "ClusterPartitioned"
REASON_CLUSTER_HEALED = "ClusterHealed"
REASON_WAL_DEGRADED = "WalDegraded"
REASON_WAL_RECOVERED = "WalRecovered"

# The closed set of event reasons this codebase may emit. grovelint's
# GL006 rule checks every record()/record_event() call site against it,
# and tests/test_docs_drift.py pins it against docs/observability.md.
REGISTERED_REASONS = frozenset(
    v
    for k, v in list(globals().items())
    if k.startswith("REASON_") and isinstance(v, str)
)

# Deferral-detail slugs (docs/observability.md "Admission explain"): the
# closed vocabulary of machine-readable blocking reasons an unscheduled
# gang can carry. The scheduler prefixes GangDeferred/QueuePending
# messages with one (`<slug>: <text>`), and the explain engine's verdicts
# cite the same slug for the same gang — one classifier
# (solver/introspect.py classify_rejections) feeds both, so `GET /events`
# alone answers the common "why is it Pending" case and never disagrees
# with `GET /gangs/{ns}/{name}/explain`. tests/test_docs_drift.py pins
# this registry against the docs table.
DETAIL_NO_NODES = "no-schedulable-nodes"
DETAIL_INSUFFICIENT_CAPACITY = "insufficient-capacity"
DETAIL_TOPOLOGY_FRAGMENTATION = "topology-fragmentation"
DETAIL_NODE_FRAGMENTATION = "node-fragmentation"
DETAIL_UNSATISFIABLE = "unsatisfiable-constraint"
DETAIL_QUOTA_CEILING = "quota-ceiling"
DETAIL_QUEUE_POSITION = "queue-position"
DETAIL_DISRUPTION_HOLD = "disruption-hold"

REGISTERED_DETAILS = frozenset(
    v
    for k, v in list(globals().items())
    if k.startswith("DETAIL_") and isinstance(v, str)
)


@dataclass
class EventRecord:
    kind: str
    namespace: str
    name: str
    type: str
    reason: str
    message: str
    count: int
    first_timestamp: float
    last_timestamp: float
    # owning keyspace shard of the involved object's namespace (0 on
    # unsharded stores; cluster-scoped objects pin to shard 0) — stamped
    # so per-shard telemetry consumers (flight recorder rings, PR 13's
    # worker lanes) can slice the event stream without re-hashing
    shard: int = 0

    def as_dict(self) -> dict:
        return {
            "involvedObject": {
                "kind": self.kind,
                "namespace": self.namespace,
                "name": self.name,
            },
            "type": self.type,
            "reason": self.reason,
            "message": self.message,
            "count": self.count,
            "firstTimestamp": self.first_timestamp,
            "lastTimestamp": self.last_timestamp,
            "shard": self.shard,
        }


def ref_of(obj) -> Tuple[str, str, str]:
    """(kind, namespace, name) from a typed API object."""
    return (
        getattr(obj, "kind", type(obj).__name__),
        obj.metadata.namespace,
        obj.metadata.name,
    )


class EventRecorder:
    """Thread-safe: reconcile worker threads and the scheduler record
    concurrently in cluster mode."""

    def __init__(self, max_events: int = 8192, clock=None) -> None:
        self.max_events = max_events
        # virtual clock (optional): sim timestamps then line up with the
        # harness's requeue math instead of wall time
        self.clock = clock
        # shard attribution (optional): namespace -> shard index, wired by
        # a sharded Store at construction (Store.shard_index). None keeps
        # the unsharded shard-0 stamp.
        self.shard_fn = None
        # flight-recorder sink (observability/flightrec.py): receives each
        # updated EventRecord; installed by FLIGHTREC.enable(), one
        # attribute check per record otherwise
        self.sink = None
        self._lock = threading.Lock()
        # dedup key -> EventRecord, recency-ordered (LRU) for bounded
        # eviction: least-recently-updated groups drop first
        self._events: "OrderedDict[tuple, EventRecord]" = OrderedDict()

    def _now(self) -> float:
        return self.clock.now() if self.clock is not None else time.time()

    def record(self, obj_ref, type: str, reason: str, message: str) -> EventRecord:
        """obj_ref: (kind, namespace, name) tuple or a typed API object."""
        if not isinstance(obj_ref, tuple):
            obj_ref = ref_of(obj_ref)
        kind, namespace, name = obj_ref
        key = (kind, namespace, name, type, reason)
        now = self._now()
        with self._lock:
            rec = self._events.get(key)
            if rec is not None:
                rec.count += 1
                rec.last_timestamp = now
                rec.message = message  # latest message wins (client-go)
                # LRU: an actively-updated group must outlive idle ones, or
                # bounded eviction would silently reset its count to 1
                self._events.move_to_end(key)
            else:
                rec = EventRecord(
                    kind=kind,
                    namespace=namespace,
                    name=name,
                    type=type,
                    reason=reason,
                    message=message,
                    count=1,
                    first_timestamp=now,
                    last_timestamp=now,
                    shard=self.shard_fn(namespace)
                    if self.shard_fn is not None
                    else 0,
                )
                self._events[key] = rec
                while len(self._events) > self.max_events:
                    self._events.popitem(last=False)
        if self.sink is not None:
            self.sink.note_event(rec)
        return rec

    def list(
        self,
        namespace: Optional[str] = None,
        reason: Optional[str] = None,
        kind: Optional[str] = None,
    ) -> List[EventRecord]:
        with self._lock:
            records = list(self._events.values())
        return [
            r
            for r in records
            if (namespace is None or r.namespace == namespace)
            and (reason is None or r.reason == reason)
            and (kind is None or r.kind == kind)
        ]

    def reset(self) -> None:
        with self._lock:
            self._events.clear()


EVENTS = EventRecorder()
