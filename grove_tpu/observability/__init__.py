"""Observability: metrics registry, span tracer, event recorder, logging,
and the glass-box layer — wall-attribution profiler, gang-journey tracer,
chaos flight recorder.

Singletons (process-global, mirroring the reference manager's one metrics
server / one event broadcaster): ``METRICS``, ``TRACER``, ``EVENTS``,
``PROFILER``, ``JOURNEYS``, ``FLIGHTREC``. The glass-box trio follows the
PR-1 cost discipline: off by default, one boolean check per instrumented
site while disabled.
"""

from grove_tpu.observability.events import EVENTS, EventRecorder
from grove_tpu.observability.flightrec import FLIGHTREC, FlightRecorder
from grove_tpu.observability.journey import JOURNEYS, JourneyTracker
from grove_tpu.observability.metrics import METRICS, Metrics
from grove_tpu.observability.profile import PROFILER, WallProfiler
from grove_tpu.observability.slo import SLO, SloEngine, SloSpec
from grove_tpu.observability.timeseries import TIMESERIES, TimeSeriesStore
from grove_tpu.observability.tracing import TRACER, Tracer

__all__ = [
    "EVENTS",
    "EventRecorder",
    "FLIGHTREC",
    "FlightRecorder",
    "JOURNEYS",
    "JourneyTracker",
    "METRICS",
    "Metrics",
    "PROFILER",
    "WallProfiler",
    "SLO",
    "SloEngine",
    "SloSpec",
    "TIMESERIES",
    "TimeSeriesStore",
    "TRACER",
    "Tracer",
]
