"""Observability: metrics registry, span tracer, event recorder, logging.

Singletons (process-global, mirroring the reference manager's one metrics
server / one event broadcaster): ``METRICS``, ``TRACER``, ``EVENTS``.
"""

from grove_tpu.observability.events import EVENTS, EventRecorder
from grove_tpu.observability.metrics import METRICS, Metrics
from grove_tpu.observability.tracing import TRACER, Tracer

__all__ = [
    "EVENTS",
    "EventRecorder",
    "METRICS",
    "Metrics",
    "TRACER",
    "Tracer",
]
