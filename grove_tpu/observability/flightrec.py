"""Chaos flight recorder: the postmortem bundle a failing run ships.

Before this module, a chaos invariant violation died with one line in a
report — "t=42s: node overcommitted" — and zero context: which commits
led up to it, which spans were in flight, which events fired, on which
shard. Re-running under a debugger loses the race; the evidence must be
captured AT the failure, from state the process was already keeping.

``FLIGHTREC`` is a bounded per-shard ring of recent telemetry:

- **store-commit digests** (kind/ns/name/rv/op, stamped with the owning
  keyspace shard) fed from ``Store._emit`` — one boolean check when off;
- **spans** (name/ts/dur/attrs) fed from the tracer's end hook;
- **events** (reason/object/count) fed from the event recorder's sink;
- **reconcile errors** (controller/key/exception).

``trigger(reason, detail)`` freezes the rings into a postmortem bundle:
``flight.json`` (manifest + rings + recent events + profiler/journey
snapshots when those layers are on) plus ``trace.json`` — a Chrome
``trace_event`` array of the ring's spans with per-shard lanes, loadable
in chrome://tracing / Perfetto. Dump count is capped PER TRIGGER KIND
(``max_dumps`` bundles for each distinct reason string) so a GroveError
storm cannot disk-spam — and a chatty remediation trigger cannot starve
the chaos-invariant budget (each kind draws from its own pool).

Wired triggers: chaos invariant violations (``ChaosRunner``), a
GroveError escaping a reconcile (engine), the disruption breaker
opening, and explicit requests (tests, ``make profile-smoke``).

Off by default, one-boolean-check discipline (``GROVE_TPU_FLIGHTREC=1``
sets a default directory, or call ``FLIGHTREC.enable(...)``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import List, Optional

from grove_tpu.observability.metrics import METRICS

_DEFAULT_CAPACITY = 1024


class FlightRecorder:
    """Process-global (``FLIGHTREC``), thread-safe, bounded."""

    def __init__(self) -> None:
        self.enabled = False
        self.clock = None  # optional virtual clock for vt stamps
        self.out_dir: Optional[str] = None
        self.max_dumps = 8
        self.dumps: List[str] = []
        self._lock = threading.Lock()
        self._rings: List[deque] = [deque(maxlen=_DEFAULT_CAPACITY)]
        self._events: deque = deque(maxlen=_DEFAULT_CAPACITY)
        self._errors: deque = deque(maxlen=256)
        self._dump_seq = 0
        # per-trigger-kind dump budget: reason string -> bundles shipped.
        # max_dumps caps each kind separately, not the process total.
        self._kind_dumps: dict = {}
        self._origin = time.perf_counter()
        env_dir = os.environ.get("GROVE_TPU_FLIGHTREC", "")
        if env_dir not in ("", "0", "false"):
            self.enable(
                out_dir=env_dir if env_dir not in ("1", "true") else None
            )

    # -- lifecycle -------------------------------------------------------

    def enable(
        self,
        num_shards: int = 1,
        capacity: int = _DEFAULT_CAPACITY,
        out_dir: Optional[str] = None,
        max_dumps: int = 8,
        clock=None,
    ) -> "FlightRecorder":
        """Arm the recorder: one ring per keyspace shard (shard stamps
        come with the records — commits carry ``WatchEvent.shard``, spans
        their ``shard`` attribute). Also installs itself as the tracer's
        flight sink and the event recorder's sink."""
        with self._lock:
            self._rings = [
                deque(maxlen=capacity) for _ in range(max(1, num_shards))
            ]
            self._events = deque(maxlen=capacity)
            self._errors = deque(maxlen=256)
            self.out_dir = out_dir
            self.max_dumps = max_dumps
            self.clock = clock
            self._origin = time.perf_counter()
            self.enabled = True
        from grove_tpu.observability import events as _events
        from grove_tpu.observability import tracing as _tracing

        _tracing.FLIGHT_SINK = self
        _events.EVENTS.sink = self
        return self

    def disable(self) -> None:
        from grove_tpu.observability import events as _events
        from grove_tpu.observability import tracing as _tracing

        self.enabled = False
        if _tracing.FLIGHT_SINK is self:
            _tracing.FLIGHT_SINK = None
        if _events.EVENTS.sink is self:
            _events.EVENTS.sink = None

    def reset(self) -> None:
        with self._lock:
            for ring in self._rings:
                ring.clear()
            self._events.clear()
            self._errors.clear()
            self.dumps = []
            self._dump_seq = 0
            self._kind_dumps = {}

    # -- feeds (one boolean check each when disabled) --------------------

    def _t(self) -> float:
        return round(time.perf_counter() - self._origin, 6)

    def _vt(self) -> Optional[float]:
        return round(self.clock.now(), 3) if self.clock is not None else None

    def _ring(self, shard: int) -> deque:
        rings = self._rings
        return rings[shard] if 0 <= shard < len(rings) else rings[0]

    def note_commit(self, ev) -> None:
        """Store-commit digest (fed from Store._emit)."""
        meta = ev.obj.metadata
        self._ring(ev.shard).append(
            {
                "t": self._t(),
                "vt": self._vt(),
                "rec": "commit",
                "op": ev.type,
                "kind": ev.kind,
                "ns": meta.namespace,
                "name": meta.name,
                "rv": meta.resource_version,
            }
        )

    def note_span(self, span) -> None:
        """Finished span (fed from tracing's FLIGHT_SINK hook)."""
        shard = span.attrs.get("shard", 0)
        self._ring(shard if isinstance(shard, int) else 0).append(
            {
                "t": self._t(),
                "rec": "span",
                "name": span.name,
                "ts_us": span.ts_us,
                "dur_us": span.dur_us,
                "tid": span.tid,
                "shard": shard if isinstance(shard, int) else 0,
                "attrs": {
                    k: v
                    for k, v in span.attrs.items()
                    if isinstance(v, (str, int, float, bool))
                },
            }
        )

    def note_event(self, rec) -> None:
        """Deduped Event update (fed from the EventRecorder sink)."""
        self._events.append(
            {
                "t": self._t(),
                "vt": self._vt(),
                "rec": "event",
                "reason": rec.reason,
                "type": rec.type,
                "kind": rec.kind,
                "ns": rec.namespace,
                "name": rec.name,
                "count": rec.count,
                "shard": rec.shard,
            }
        )

    def note_error(self, controller: str, key, exc: BaseException) -> None:
        """A reconcile raised (fed from the engine's completion path)."""
        self._errors.append(
            {
                "t": self._t(),
                "vt": self._vt(),
                "rec": "error",
                "controller": controller,
                "key": "/".join(str(k) for k in key),
                "error": f"{type(exc).__name__}: {exc}",
            }
        )

    # -- dump ------------------------------------------------------------

    def trigger(self, reason: str, detail: str = "") -> Optional[str]:
        """Freeze the rings into a postmortem bundle. Returns the bundle
        directory, or None (disabled / this trigger kind's dump budget
        exhausted — other kinds keep their own budgets)."""
        if not self.enabled:
            return None
        with self._lock:
            if self._kind_dumps.get(reason, 0) >= self.max_dumps:
                return None
            self._kind_dumps[reason] = self._kind_dumps.get(reason, 0) + 1
            self._dump_seq += 1
            seq = self._dump_seq
            shards = [
                {"shard": i, "records": list(ring)}
                for i, ring in enumerate(self._rings)
            ]
            events = list(self._events)
            errors = list(self._errors)
        out_dir = self.out_dir
        if out_dir is None:
            import tempfile

            out_dir = tempfile.mkdtemp(prefix="grove-flightrec-")
            self.out_dir = out_dir
        slug = "".join(
            c if c.isalnum() or c in "-_" else "-" for c in reason
        )[:48]
        bundle = os.path.join(out_dir, f"bundle-{seq:03d}-{slug}")
        os.makedirs(bundle, exist_ok=True)
        manifest = {
            "reason": reason,
            "detail": detail,
            "t": self._t(),
            "vt": self._vt(),
            "shards": shards,
            "events": events,
            "errors": errors,
        }
        # snapshots of the sibling glass-box layers, when they are on —
        # a postmortem with the attribution ledger beats one without
        from grove_tpu.observability.journey import JOURNEYS
        from grove_tpu.observability.profile import PROFILER

        if PROFILER.enabled:
            manifest["profile"] = PROFILER.report(top=32)
        if JOURNEYS.enabled:
            manifest["journeys"] = JOURNEYS.critical_path()
        with open(os.path.join(bundle, "flight.json"), "w") as f:
            json.dump(manifest, f, indent=1)
        with open(os.path.join(bundle, "trace.json"), "w") as f:
            json.dump(self._chrome(shards), f)
        self.dumps.append(bundle)
        METRICS.inc("flightrec_dumps_total")
        from grove_tpu.observability.events import (
            EVENTS,
            REASON_FLIGHT_RECORDED,
            TYPE_WARNING,
        )

        EVENTS.record(
            ("FlightRecorder", "", "cluster"),
            TYPE_WARNING,
            REASON_FLIGHT_RECORDED,
            f"postmortem bundle dumped to {bundle}: {reason}"
            + (f" ({detail})" if detail else ""),
        )
        return bundle

    @staticmethod
    def _chrome(shards: List[dict]) -> List[dict]:
        """The ring's spans as a Chrome trace_event array; the shard rides
        both as a top-level column and as the pid so per-shard work renders
        as separate lanes (PR 13's concurrent workers will land there)."""
        out = []
        for entry in shards:
            for rec in entry["records"]:
                if rec.get("rec") != "span":
                    continue
                # the record's OWN shard stamp wins: cluster-wide spans
                # (shard -1) live in ring 0 but must not render as shard 0
                shard = rec.get("shard", entry["shard"])
                out.append(
                    {
                        "name": rec["name"],
                        "ph": "X",
                        "ts": rec["ts_us"],
                        "dur": rec["dur_us"],
                        "pid": shard,
                        "tid": rec["tid"],
                        "shard": shard,
                        "args": rec.get("attrs", {}),
                    }
                )
        out.sort(key=lambda e: e["ts"])
        return out


def load_bundle(path: str) -> dict:
    """Re-read a dumped bundle (the smoke's round-trip check): returns the
    manifest with the chrome trace attached under ``"chrome"``."""
    with open(os.path.join(path, "flight.json")) as f:
        manifest = json.load(f)
    with open(os.path.join(path, "trace.json")) as f:
        manifest["chrome"] = json.load(f)
    return manifest


FLIGHTREC = FlightRecorder()
