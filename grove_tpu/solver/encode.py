"""Host-side encoder: domain objects → dense PackingProblem tensors.

Bridges the control plane (PodGangs, pods, sim nodes, ClusterTopology) and
the TPU kernel. Nodes are topology-sorted so every domain is a contiguous
slab; per-level domain labels become dense int ids; gang/group/pod structures
are padded into static-size buckets so the jitted kernel compiles once per
bucket (SURVEY §7 'dynamic shapes' hard part).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from grove_tpu.api.topology import ClusterTopology
from grove_tpu.solver.types import PackingProblem


class ConstraintError(ValueError):
    """A gang carries an unsatisfiable/contradictory constraint DECLARATION
    (unknown hard topology key, spread combined with per-group packs) — the
    caller's input is at fault, distinct from solver-side failures. The gRPC
    sidecar maps this to INVALID_ARGUMENT."""


def _next_pow2(x: int) -> int:
    n = 1
    while n < x:
        n *= 2
    return n


# Minimum padded sizes: every distinct (G, P) shape compiles its own
# executable, so small problems share a handful of buckets instead of
# compiling one per pending-gang count (compiles dominate wall time when the
# chip sits behind a remote link). The GANG axis keeps pow2 buckets — the
# pending-gang count changes every solve. The GROUP axis pads EXACTLY to
# the population's max group count (round 4): it is template-driven and
# changes rarely, while every padded group row costs a full [N,R] fill
# scan per gang per fill — pow2(3)=4 wasted 25% of the stress mix's fill
# work, and a single-group population would pay 4x.
MIN_GANG_BUCKET = 32


def encode_nodes(
    nodes: Sequence,
    topology: ClusterTopology,
    free_capacity: Optional[Dict[str, Dict[str, float]]] = None,
    resource_names: Optional[List[str]] = None,
) -> Tuple[np.ndarray, np.ndarray, List[str], List[str], List[str]]:
    """Sort nodes topologically and build (capacity[N,R], topo[N,L]).

    `free_capacity` overrides per-node capacity (already-bound pods deducted).
    Returns (capacity, topo, node_names, resource_names, level_keys).
    """
    level_keys = [lvl.key for lvl in topology.spec.levels]
    if resource_names is None:
        rset = set()
        for node in nodes:
            rset.update(node.capacity)
        resource_names = sorted(rset)

    def topo_path(node):
        return tuple(node.labels.get(k, "") for k in level_keys)

    ordered = sorted(nodes, key=lambda n: (topo_path(n), n.name))
    n = len(ordered)
    capacity = np.zeros((n, len(resource_names)), dtype=np.float32)
    topo = np.zeros((n, len(level_keys)), dtype=np.int32)
    # Domain identity is the PATH PREFIX (labels of levels 0..l), not the
    # bare label: a rack name reused under two zones is two domains (matches
    # k8s label reality), and path-keyed ids over path-sorted nodes are
    # monotone — every domain is one contiguous slab whose slab index equals
    # its dense id (the kernel's boundary-gather aggregation relies on this).
    id_maps: List[Dict[tuple, int]] = [{} for _ in level_keys]
    for i, node in enumerate(ordered):
        caps = (
            free_capacity.get(node.name, node.capacity)
            if free_capacity
            else node.capacity
        )
        for r, rname in enumerate(resource_names):
            capacity[i, r] = caps.get(rname, 0.0)
        path = topo_path(node)
        for l in range(len(level_keys)):
            prefix = path[: l + 1]
            topo[i, l] = id_maps[l].setdefault(prefix, len(id_maps[l]))
    node_names = [node.name for node in ordered]
    return capacity, topo, node_names, resource_names, level_keys


def domain_boundaries(topo: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-level contiguous-domain [start, end) node ranges (topology-sorted
    nodes ⇒ each domain is a slab). Padded with empty ranges to the max
    domain count across levels."""
    n, levels = topo.shape
    d_max = 1
    per_level = []
    for l in range(levels):
        col = topo[:, l]
        # boundaries where the id changes
        changes = np.flatnonzero(np.diff(col)) + 1
        starts = np.concatenate([[0], changes]).astype(np.int32)
        ends = np.concatenate([changes, [n]]).astype(np.int32)
        # slab index must equal dense domain id (path-keyed encoding
        # guarantees it; the kernel masks nodes with topo == slab index)
        if not np.array_equal(col[starts], np.arange(len(starts))):
            raise ValueError(
                f"level {l}: domain ids are not contiguous slab indices — "
                "nodes must be encoded with path-keyed topology ids"
            )
        per_level.append((starts, ends))
        d_max = max(d_max, len(starts))
    seg_starts = np.zeros((levels, d_max), dtype=np.int32)
    seg_ends = np.zeros((levels, d_max), dtype=np.int32)
    for l, (starts, ends) in enumerate(per_level):
        seg_starts[l, : len(starts)] = starts
        seg_ends[l, : len(ends)] = ends
    return seg_starts, seg_ends


def level_index_for_key(
    level_keys: List[str], key: Optional[str], required: bool = False
) -> int:
    if key is None:
        return -1
    try:
        return level_keys.index(key)
    except ValueError:
        if required:
            # A HARD pack constraint must never silently degrade to
            # cluster-wide scatter (TopologyPackConstraint.Required).
            raise ConstraintError(
                f"required topology key {key!r} is not a level of the cluster"
                f" topology {level_keys}"
            )
        return -1


def encode_gangs(
    gang_specs: List[dict],
    resource_names: List[str],
    level_keys: List[str],
    pad_gangs: Optional[int] = None,
    pad_groups: Optional[int] = None,
) -> Tuple[np.ndarray, ...]:
    """gang_specs: [{name, groups: [{name, demand: {res: qty}, count,
    min_count}], required_key, preferred_key, priority}] → padded tensors."""
    g = len(gang_specs)
    p = max((len(s["groups"]) for s in gang_specs), default=1)
    gp = pad_gangs or _next_pow2(max(g, MIN_GANG_BUCKET))
    pp = pad_groups or max(p, 1)
    r = len(resource_names)

    demand = np.zeros((gp, pp, r), dtype=np.float32)
    count = np.zeros((gp, pp), dtype=np.int32)
    min_count = np.zeros((gp, pp), dtype=np.int32)
    group_req = np.full((gp, pp), -1, dtype=np.int32)
    req_level = np.full((gp,), -1, dtype=np.int32)
    pref_level = np.full((gp,), -1, dtype=np.int32)
    spread_level = np.full((gp,), -1, dtype=np.int32)
    spread_min = np.zeros((gp,), dtype=np.int32)
    spread_required = np.zeros((gp,), dtype=bool)
    priority = np.zeros((gp,), dtype=np.int32)
    gang_names: List[str] = []
    group_names: List[List[str]] = []

    for gi, spec in enumerate(gang_specs):
        gang_names.append(spec["name"])
        names = []
        for pi, grp in enumerate(spec["groups"]):
            names.append(grp["name"])
            for ri, rname in enumerate(resource_names):
                demand[gi, pi, ri] = grp["demand"].get(rname, 0.0)
            count[gi, pi] = grp["count"]
            min_count[gi, pi] = grp["min_count"]
            group_req[gi, pi] = level_index_for_key(
                level_keys, grp.get("required_key"), required=True
            )
        group_names.append(names)
        req_level[gi] = level_index_for_key(
            level_keys, spec.get("required_key"), required=True
        )
        pref_level[gi] = level_index_for_key(level_keys, spec.get("preferred_key"))
        # spread: a hard (required) spread key must resolve, like a hard pack
        spread_required[gi] = bool(spec.get("spread_required", False))
        spread_level[gi] = level_index_for_key(
            level_keys, spec.get("spread_key"), required=spread_required[gi]
        )
        if spread_level[gi] < 0:
            spread_required[gi] = False
        elif (group_req[gi] >= 0).any():
            # the balanced spread fill places the whole gang and cannot
            # honor per-group hard packs at the same time — reject at the
            # solver boundary (operator admission enforces the same rule,
            # but external gRPC clients reach the encoder directly and a
            # silent group-pack violation must never look admitted)
            raise ConstraintError(
                f"gang {spec['name']!r}: spread_key cannot be combined with"
                " per-group required pack constraints"
            )
        elif 0 <= spread_level[gi] <= req_level[gi]:
            # operator admission enforces "spread domain strictly narrower
            # than pack domain"; mirror it at the solver boundary — a direct
            # gRPC client sending spread_key >= pack breadth would otherwise
            # get a gang that can never span >1 spread domain inside one
            # pack domain and silently stays pending forever
            raise ConstraintError(
                f"gang {spec['name']!r}: spread_key must be strictly"
                " narrower than required_key"
            )
        spread_min[gi] = int(spec.get("spread_min_domains", 2) or 2)
        priority[gi] = spec.get("priority", 0)

    return (
        demand,
        count,
        min_count,
        req_level,
        pref_level,
        priority,
        group_req,
        spread_level,
        spread_min,
        spread_required,
        gang_names,
        group_names,
    )


def _quantize_resources(
    capacity: np.ndarray, demand: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Rescale each resource axis into float32-exact integer units.

    Byte-denominated resources (memory ~2^35) exceed float32's integer range,
    so tiny requests would vanish in `free -= take*demand`. Per resource:
    unit = max(smallest positive demand, max capacity / 2^22); capacity
    rounds DOWN and demand rounds UP in those units — conservative (never
    overcommits), and all kernel arithmetic becomes exact.
    """
    capacity = capacity.copy()
    demand = demand.copy()
    for r in range(capacity.shape[1]):
        cap_max = float(capacity[:, r].max(initial=0.0))
        pos = demand[:, :, r][demand[:, :, r] > 0]
        unit = max(
            float(pos.min()) if pos.size else 1.0,
            cap_max / float(1 << 22),
            1e-12,
        )
        # epsilon guards against float ratio error (0.02/0.01 → 2.0000000004)
        capacity[:, r] = np.floor(capacity[:, r] / unit + 1e-9)
        demand[:, :, r] = np.ceil(demand[:, :, r] / unit - 1e-9)
    return capacity.astype(np.float32), demand.astype(np.float32)


class StickyGroupPad:
    """Thread-safe sticky group-axis padding for repeat ``build_problem``
    callers.

    The encoder pads the group axis EXACTLY (wide pow2 padding wastes fill
    scans — measured 25% at full size), which means the padded shape tracks
    the pending mix's max group count. Any caller that solves repeatedly
    (scheduler round loop, gRPC sidecar, multi-problem batchers) must
    remember the widest template seen and keep padding there, or shape
    churn forces a fresh XLA compile of the wave program per distinct
    width. One instance per solve endpoint; ``grow()`` is a locked
    read-modify-write so concurrent solvers can't momentarily shrink the
    sticky width (which would trigger exactly the redundant recompiles the
    mechanism exists to prevent).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._width = 1

    def grow(self, gang_specs: List[dict]) -> int:
        """Fold one batch's max group count into the sticky width and
        return the width to pass as ``build_problem(pad_groups=...)``."""
        batch_max = max((len(s["groups"]) for s in gang_specs), default=1)
        with self._lock:
            self._width = max(self._width, batch_max, 1)
            return self._width

    def peek(self, gang_specs: List[dict]) -> int:
        """The width :meth:`grow` WOULD return for this batch, without
        committing it — read-only replay paths (the admission explain
        engine) must pad exactly like the next real solve will, while
        leaving the scheduler's sticky state untouched."""
        batch_max = max((len(s["groups"]) for s in gang_specs), default=1)
        with self._lock:
            return max(self._width, batch_max, 1)


class NodeEncoding:
    """Cached node-side tensors for repeat solves over an unchanged
    topology — the delta-solve tier (solver/deltastate.py).

    Holds everything :func:`encode_nodes` derives that does NOT change per
    tick: the topology sort order, dense path-keyed domain ids, contiguous
    domain boundaries, the node-name index, and the BASE capacity matrix
    (``node.capacity`` with no usage deducted). Per-tick free capacity is a
    separate ``[N, R]`` matrix whose dirty rows the delta state patches;
    :func:`build_problem_cached` assembles a problem from the pair that is
    BIT-IDENTICAL to a from-scratch :func:`build_problem` over the same
    inputs (pinned by tests/test_deltastate.py).

    The static tensors stay plain host ndarrays: downstream consumers
    (the NumPy oracle, preemption trials, the GSPMD sharded path's
    shard_map partitioning) index them host-side, so staging them as
    committed device buffers here would either force per-scalar syncs or
    fight the sharded solve's placement. What the cache buys is skipping
    the re-sort/re-derive — the upload is the jit dispatch's job.
    """

    def __init__(
        self,
        nodes: Sequence,
        topology: ClusterTopology,
        resource_names: List[str],
    ) -> None:
        capacity, topo, node_names, resource_names, level_keys = encode_nodes(
            nodes, topology, None, list(resource_names)
        )
        self.base_capacity = capacity  # [N, R] float32, node.capacity only
        self.topo = topo
        self.node_names = node_names
        self.resource_names = resource_names
        self.level_keys = level_keys
        self.seg_starts, self.seg_ends = domain_boundaries(topo)
        self.node_index = {name: i for i, name in enumerate(node_names)}

def slice_encoding(
    enc: NodeEncoding, start: int, end: int, pad_to: Optional[int] = None
):
    """Localized node-side tensors for one contiguous topology slab of a
    :class:`NodeEncoding` — the partitioned frontier's subproblem encode
    (solver/frontier.py).

    Nodes are topology-sorted, so the slab ``[start, end)`` of a domain at
    any level is contiguous and its per-level dense ids form contiguous
    ranges; subtracting the first row re-bases them at 0 without changing
    domain identity (two slab nodes share a local id iff they shared the
    global one). ``pad_to`` appends zero-capacity ghost nodes that EXTEND
    the last domain of every level (ids replicated from the final real
    row), which the kernel provably never fills — zero capacity means a
    zero capped-fit count everywhere — so padded and unpadded solves are
    bit-identical while every subproblem in a batch bucket shares one
    static shape.

    Returns ``(topo_local, seg_starts, seg_ends, node_names, node_index)``
    where ``node_names`` includes ghost names for the padding rows and
    ``node_index`` maps REAL slab nodes only."""
    n_real = end - start
    topo_local = enc.topo[start:end] - enc.topo[start : start + 1]
    if pad_to is not None and pad_to > n_real:
        topo_local = np.concatenate(
            [
                topo_local,
                np.repeat(topo_local[-1:], pad_to - n_real, axis=0),
            ]
        )
    seg_starts, seg_ends = domain_boundaries(topo_local)
    node_names = list(enc.node_names[start:end])
    node_index = {name: i for i, name in enumerate(node_names)}
    if pad_to is not None and pad_to > n_real:
        node_names.extend(
            f"__frontier-pad-{i}" for i in range(pad_to - n_real)
        )
    return topo_local, seg_starts, seg_ends, node_names, node_index


def build_problem(
    nodes: Sequence,
    gang_specs: List[dict],
    topology: ClusterTopology,
    free_capacity: Optional[Dict[str, Dict[str, float]]] = None,
    pad_gangs: Optional[int] = None,
    pad_groups: Optional[int] = None,
) -> PackingProblem:
    """Encode nodes + gang specs into padded solver tensors.

    ``pad_groups``: the group axis is padded EXACTLY when omitted, so the
    problem shape follows this batch's widest template. One-shot callers
    can omit it; every repeat caller should hold a ``StickyGroupPad`` and
    pass ``sticky.grow(gang_specs)`` here, or pending-mix churn recompiles
    the wave program per distinct width (see StickyGroupPad).
    """
    # resource name space = union over nodes and demands
    rset = set()
    for node in nodes:
        rset.update(node.capacity)
    for spec in gang_specs:
        for grp in spec["groups"]:
            rset.update(grp["demand"])
    resource_names = sorted(rset)

    capacity, topo, node_names, resource_names, level_keys = encode_nodes(
        nodes, topology, free_capacity, resource_names
    )
    seg_starts, seg_ends = domain_boundaries(topo)
    return _assemble_problem(
        capacity,
        topo,
        seg_starts,
        seg_ends,
        node_names,
        resource_names,
        level_keys,
        {name: i for i, name in enumerate(node_names)},
        gang_specs,
        pad_gangs,
        pad_groups,
    )


def build_problem_cached(
    enc: NodeEncoding,
    capacity: np.ndarray,
    gang_specs: List[dict],
    pad_gangs: Optional[int] = None,
    pad_groups: Optional[int] = None,
    pre_encoded: Optional[tuple] = None,
) -> PackingProblem:
    """Assemble a problem from a cached :class:`NodeEncoding` and an
    externally-maintained free-capacity matrix (the delta-solve hot path:
    the O(nodes) re-sort/re-id/boundary scan of :func:`encode_nodes` is
    skipped; only the small gang-side tensors are built per tick).

    ``capacity`` must hold the same float32 values a from-scratch encode
    would produce for the current free capacity — the caller (the delta
    state) owns that contract, and the result is then bit-identical to
    :func:`build_problem`.

    ``pre_encoded``: an :func:`encode_gangs` result computed earlier for
    the SAME (gang_specs, pad_gangs, pad_groups) — the frontier's
    residual-overlap path encodes the gang tensors while the device
    executes the partition solves and assembles here once the
    post-partition capacity is known (docs/solver.md "Residual
    overlap"). encode_gangs is pure, so reusing its output is
    bit-identical to recomputing it."""
    return _assemble_problem(
        capacity,
        enc.topo,
        enc.seg_starts,
        enc.seg_ends,
        enc.node_names,
        enc.resource_names,
        enc.level_keys,
        enc.node_index,
        gang_specs,
        pad_gangs,
        pad_groups,
        pre_encoded=pre_encoded,
    )


def _assemble_problem(
    capacity: np.ndarray,
    topo: np.ndarray,
    seg_starts: np.ndarray,
    seg_ends: np.ndarray,
    node_names: List[str],
    resource_names: List[str],
    level_keys: List[str],
    node_index: Dict[str, int],
    gang_specs: List[dict],
    pad_gangs: Optional[int],
    pad_groups: Optional[int],
    pre_encoded: Optional[tuple] = None,
) -> PackingProblem:
    """Gang-side half of the encode (shared by the from-scratch and cached
    paths so the two can never diverge). ``pre_encoded`` short-circuits
    the :func:`encode_gangs` call with a result computed earlier for the
    same arguments (the frontier's residual-overlap path; encode_gangs is
    pure, so the tensors are bit-identical either way)."""
    (
        demand,
        count,
        min_count,
        req_level,
        pref_level,
        priority,
        group_req,
        spread_level,
        spread_min,
        spread_required,
        gang_names,
        group_names,
    ) = (
        pre_encoded
        if pre_encoded is not None
        else encode_gangs(
            gang_specs, resource_names, level_keys, pad_gangs, pad_groups
        )
    )

    capacity, demand = _quantize_resources(capacity, demand)

    # recovery pins: a constrained group with surviving pods must rejoin
    # their domain — map the pinned node to its domain id at the group level
    group_pin = np.full_like(group_req, -1)
    gang_pin = np.full_like(req_level, -1)
    for gi, spec in enumerate(gang_specs):
        for pi, grp in enumerate(spec["groups"]):
            pin_node = grp.get("pinned_node")
            lvl = group_req[gi, pi]
            if pin_node is not None and lvl >= 0 and pin_node in node_index:
                group_pin[gi, pi] = topo[node_index[pin_node], lvl]
        # gang-level recovery pin: survivors of a gang with a gang-level
        # required pack anchor the whole delta-solve to their domain
        gpin_node = spec.get("gang_pinned_node")
        glvl = req_level[gi]
        if gpin_node is not None and glvl >= 0 and gpin_node in node_index:
            gang_pin[gi] = topo[node_index[gpin_node], glvl]

    # spread recovery seed: survivor pods per spread-level domain, so a
    # delta-solve judges the live gang's spread and the balanced fill
    # steers replacements into un-covered domains
    spread_seed = np.zeros(
        (spread_level.shape[0], seg_starts.shape[1]), dtype=np.int32
    )
    for gi, spec in enumerate(gang_specs):
        slvl = spread_level[gi]
        if slvl < 0:
            continue
        for node in spec.get("spread_survivor_nodes") or []:
            if node in node_index:
                spread_seed[gi, topo[node_index[node], slvl]] += 1
    if not spread_seed.any():
        # zero-width placeholder: a full [G, D] zeros tensor would be
        # shipped to the device on every seedless solve (~200MB at stress
        # scale) only for XLA to ignore it
        spread_seed = np.zeros((spread_level.shape[0], 0), dtype=np.int32)

    return PackingProblem(
        capacity=capacity,
        topo=topo,
        seg_starts=seg_starts,
        seg_ends=seg_ends,
        group_req=group_req,
        group_pin=group_pin,
        gang_pin=gang_pin,
        demand=demand,
        count=count,
        min_count=min_count,
        req_level=req_level,
        pref_level=pref_level,
        spread_level=spread_level,
        spread_min=spread_min,
        spread_required=spread_required,
        spread_seed=spread_seed,
        priority=priority,
        node_names=node_names,
        gang_names=gang_names,
        group_names=group_names,
        resource_names=resource_names,
        level_keys=level_keys,
    )
