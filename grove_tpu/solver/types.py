"""Solver problem encoding: dense tensors for the packing kernel.

The TPU-side representation of "pending PodGangs × cluster nodes × topology".
Shapes are static (padded) so the kernel jit-compiles once per size bucket:

- nodes sorted topologically (domains contiguous at every level)
- capacity[N, R]          float32  free resources per node
- topo[N, L]              int32    domain id of node n at level l (globally
                                   unique per level; level 0 broadest)
- demand[G, P, R]         float32  per-POD resource vector of group p
- count[G, P]             int32    desired pods per group
- min_count[G, P]         int32    gang floor per group (PodGroup.MinReplicas)
- req_level[G]            int32    level the gang MUST pack within (-1 none)
- pref_level[G]           int32    level the gang prefers to pack within
                                   (-1 → narrowest; scheduler podgang.go:108)
- priority[G]             int32    commit order (higher first)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

import numpy as np


@dataclass
class PackingProblem:
    capacity: np.ndarray  # [N, R] float32
    topo: np.ndarray  # [N, L] int32
    demand: np.ndarray  # [G, P, R] float32
    count: np.ndarray  # [G, P] int32
    min_count: np.ndarray  # [G, P] int32
    req_level: np.ndarray  # [G] int32
    pref_level: np.ndarray  # [G] int32
    priority: np.ndarray  # [G] int32

    # Contiguous-domain boundaries (nodes are topology-sorted): domain d of
    # level l spans node indices [seg_starts[l,d], seg_ends[l,d]). Padded
    # entries are empty ranges. Lets the kernel compute per-domain aggregates
    # as prefix-sum gathers instead of TPU-hostile scatter segment-sums.
    seg_starts: np.ndarray = None  # [L, D] int32
    seg_ends: np.ndarray = None  # [L, D] int32
    # per-group required pack level (-1 none): PodGroup/PCSG constraint tier
    group_req: np.ndarray = None  # [G, P] int32
    # pinned domain id per group at its required level (-1 none)
    group_pin: np.ndarray = None  # [G, P] int32
    # pinned domain id for the whole gang at req_level (-1 none): recovery
    # replacements of a gang-level-constrained gang rejoin the survivors'
    # domain (never split a live gang across required domains)
    gang_pin: np.ndarray = None  # [G] int32
    # topology SPREAD constraint (TopologySpreadConstraint): level whose
    # domains the gang's pods are balanced across (-1 none); minimum distinct
    # domains required; hard (reject) vs soft (score-only)
    spread_level: np.ndarray = None  # [G] int32
    spread_min: np.ndarray = None  # [G] int32
    spread_required: np.ndarray = None  # [G] bool
    # recovery seed: survivor pod counts per spread-level domain — a
    # delta-solve judges the LIVE gang's spread (survivors + replacements)
    # and steers replacements away from survivor domains
    spread_seed: np.ndarray = None  # [G, D] int32

    # bookkeeping (host side, not shipped to device)
    node_names: List[str] = field(default_factory=list)
    gang_names: List[str] = field(default_factory=list)
    # gang -> group -> pclq fqn
    group_names: List[List[str]] = field(default_factory=list)
    resource_names: List[str] = field(default_factory=list)
    level_keys: List[str] = field(default_factory=list)

    @property
    def num_nodes(self) -> int:
        return self.capacity.shape[0]

    @property
    def num_gangs(self) -> int:
        return self.demand.shape[0]

    @property
    def max_groups(self) -> int:
        return self.demand.shape[1]

    @property
    def num_levels(self) -> int:
        return self.topo.shape[1]


@dataclass
class PackingResult:
    admitted: np.ndarray  # [G] bool
    placed: np.ndarray  # [G, P] int32 pods actually placed
    score: np.ndarray  # [G] float32 in (0,1]; 0 for unadmitted
    chosen_level: np.ndarray  # [G] int32 (-1: cluster-wide fallback)
    # [G, P, N] int32 per-node pod counts (None in stats-only mode)
    alloc: np.ndarray | None = None
    free_after: np.ndarray | None = None  # [N, R]
    solve_seconds: float = 0.0

    def assignments(
        self, problem: PackingProblem
    ) -> Dict[str, Dict[str, List[str]]]:
        """gang -> pclq fqn -> node names (one entry per pod), from alloc."""
        if self.alloc is None:
            raise ValueError("solver ran in stats-only mode (no alloc)")
        out: Dict[str, Dict[str, List[str]]] = {}
        for g, gang_name in enumerate(problem.gang_names):
            if not self.admitted[g]:
                continue
            groups: Dict[str, List[str]] = {}
            for p, pclq_name in enumerate(problem.group_names[g]):
                nodes: List[str] = []
                for n in np.nonzero(self.alloc[g, p])[0]:
                    nodes.extend([problem.node_names[n]] * int(self.alloc[g, p, n]))
                if nodes:
                    groups[pclq_name] = nodes
            out[gang_name] = groups
        return out
