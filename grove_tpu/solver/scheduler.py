"""Solver-backed gang scheduler: the KAI-replacement binding loop.

Occupies the boundary the reference delegates to the external KAI scheduler
(SURVEY §2 'scheduler contract'): consumes PodGangs + ungated pods, encodes
pending work as dense tensors, runs the TPU packing kernel, binds pods to
nodes, and writes PodGang status (phase, Scheduled condition, PlacementScore
— scheduler podgang.go:139-176).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional

from grove_tpu.api import names as namegen
from grove_tpu.api.meta import (
    Condition,
    clone_status,
    get_condition,
    set_condition,
)
from grove_tpu.api.pod import is_scheduled, is_terminating
from grove_tpu.api.topology import ClusterTopology
from grove_tpu.api.types import (
    COND_PODGANG_DISRUPTION_TARGET,
    COND_PODGANG_SCHEDULED,
    COND_PODGANG_UNHEALTHY,
    PHASE_PENDING,
    PHASE_RUNNING,
    PHASE_STARTING,
    SPREAD_SCHEDULE_ANYWAY,
)
from grove_tpu.observability.events import (
    DETAIL_QUOTA_CEILING,
    EVENTS,
    REASON_GANG_ADMITTED,
    REASON_GANG_DEFERRED,
    REASON_POD_BOUND,
    REASON_PREEMPTED,
    REASON_QUEUE_PENDING,
    REASON_QUOTA_RECLAIM,
    TYPE_NORMAL,
    TYPE_WARNING,
)
from grove_tpu.observability.journey import JOURNEYS
from grove_tpu.observability.metrics import METRICS
from grove_tpu.observability.profile import PROFILER
from grove_tpu.observability.tracing import TRACER
from grove_tpu.quota.manager import QuotaManager, spec_demand
from grove_tpu.runtime.errors import ERR_CONFLICT, ERR_NOT_FOUND, GroveError
from grove_tpu.runtime.store import Store
from grove_tpu.sim.cluster import SimCluster
from grove_tpu.solver.encode import StickyGroupPad, build_problem
from grove_tpu.solver.kernel import solve_waves


class GangScheduler:
    """All-or-nothing, topology-aware binder over a SimCluster."""

    def __init__(
        self,
        store: Store,
        cluster: SimCluster,
        topology: Optional[ClusterTopology] = None,
        priority_map: Optional[Dict[str, int]] = None,
        chunk_size: int = 32,
        max_waves: int = 16,
        solver_sidecar: Optional[str] = None,
    ) -> None:
        self.store = store
        self.cluster = cluster
        self.topology = topology or ClusterTopology()
        # priorityClassName -> numeric priority (higher schedules first)
        self.priority_map = priority_map or {}
        self.chunk_size = chunk_size
        self.max_waves = max_waves
        # BASELINE north star: the scheduling loop can call the packing
        # solve through a gRPC sidecar (host:port) instead of in-process —
        # the same boundary the reference's scheduler plugin puts KAI behind
        self.solver_sidecar = solver_sidecar
        # sticky group-axis padding (see _solve_batch): grows to the widest
        # template seen, never shrinks — pending-mix churn must not force
        # per-shape recompiles of the wave program
        self._pad_groups = StickyGroupPad()
        # multi-tenant quota & fair-share (grove_tpu/quota, docs/quota.md):
        # with no Queue CRs the subsystem is inert — the solve order stays
        # byte-identical to the flat (-priority, name) sort
        self.quota = QuotaManager(store)
        self._sidecar_client = None
        # per-solve gRPC deadline; past it the sidecar aborts the solve
        # server-side (DEADLINE_EXCEEDED) and we fall back in-process
        self.sidecar_timeout = 120.0
        # observability: rounds solved in-process while the sidecar was
        # down (reattach is automatic — the client is rebuilt per failure)
        self.sidecar_fallbacks = 0
        # per-REQUEST failures (deadline blown, request too big/invalid)
        # are doomed on identical retry: skip the sidecar this long before
        # re-sending, instead of shipping the multi-MB request to fail
        # every round. Connectivity failures (restart) retry immediately.
        self.sidecar_backoff_s = 60.0
        self._sidecar_skip_until = 0.0
        # node-health monitor (controller/nodehealth.py), wired by the
        # harness: gangs it holds in requeue backoff are skipped from the
        # solve until released (rate-limited re-admission after a gang
        # termination). None → no holds (tests that build a bare scheduler).
        self.monitor = None
        # partition admission fence (docs/federation.md "Partition ≠
        # crash"): set by the federation router when this region's lease
        # expires mid-partition; schedule_pending early-returns while set.
        # One boolean check — False (always, outside federation faults)
        # is byte-identical to the pre-fence scheduler.
        self.admission_fenced = False
        # disruption broker (grove_tpu/disruption, docs/robustness.md):
        # preemption and quota reclaim must be GRANTED their victim sets
        # before evicting — per-PCS disruptionBudgets and the storm breaker
        # gate every voluntary eviction. None → ungated (bare schedulers);
        # an un-armed broker (no budgets, no drains) is inert either way.
        self.broker = None
        # incremental delta-solve state (solver/deltastate.py,
        # docs/solver.md): cluster tensors + gang specs folded from watch
        # deltas instead of per-tick full repasses. None → the from-scratch
        # path; enable_delta() attaches it (in-memory stores only). The two
        # paths are BIT-identical — pinned by the delta_selfcheck A/B.
        self.delta = None
        # debug/A-B mode: after every delta solve, re-derive the identical
        # problem from scratch and assert problem + admissions bit-equality
        # (tests, `make delta-smoke`, and the bench "delta" block set it)
        self.delta_selfcheck = False
        # seconds the A/B selfcheck itself spent inside schedule() since
        # the caller last reset this — the check is a verification harness
        # (never on in production), so latency reporters subtract it from
        # the admission path's timing and account for it separately
        self.last_selfcheck_seconds = 0.0
        # (fingerprint + solve opts, result) of the previous delta solve:
        # equal fingerprints ⇒ identical solver input ⇒ the whole device
        # dispatch is skipped and the result reused (_solve_batch_delta)
        self._delta_last = None
        # True while the most recent batch "solve" was a fingerprint reuse
        # (no dispatch ran): gates the gang_solve_seconds observation
        self._solve_reused = False
        # partitioned solver frontier (solver/frontier.py, docs/solver.md
        # "Partitioned frontier"): per-super-domain subproblem
        # decomposition with vmap-batched dispatch. None → the global
        # frontier; enable_frontier() attaches it (requires delta state).
        self.frontier = None
        # debug/A-B mode: after every partitioned solve, re-solve each
        # subproblem alone through the host-loop kernel and assert the
        # batched composite is bit-identical (tests, `make
        # frontier-smoke`, sampled in the bench "frontier" block)
        self.frontier_selfcheck = False
        # True while the most recent solve went through the partitioned
        # frontier (the delta A/B then pins the problem encode only — the
        # frontier selfcheck owns the solve comparison)
        self._frontier_solved = False
        # shards whose pending_namespaces gauge was set last round (they
        # are zeroed when they drain — a gauge never touched again would
        # report phantom pending work forever)
        self._pending_ns_shards: set = set()
        # journey tracing (observability/journey.py): wall stamp of the
        # current round's encode completion, set only while JOURNEYS is
        # enabled — splits encode from solve in the admission decomposition
        self._journey_encode_end = None
        # pods bound by the most recent _commit_admitted pass
        self._last_commit_bound = 0
        # speculative-encode overlap cache (docs/control-plane.md §5):
        # the process-backend drain calls speculate_encode() between
        # dispatching a reconcile round and collecting worker replies
        # (engine.overlap_hook), pre-building the gang specs the next
        # schedule() round would encode. Entries carry the staleness
        # token of every input _build_gang_spec reads; _encode_pending
        # re-validates at consumption and falls back to the serial
        # rebuild on ANY mismatch — admissions stay bit-identical to
        # the serial twin (pinned by sim/parallel.py parallel_ab).
        # (namespace, gang_name) -> (token, sorted-name-tuple, spec,
        # pods_by_pclq).
        self._overlap_cache: Dict[tuple, tuple] = {}
        # specs built per speculate_encode() call — bounds the
        # coordinator's per-batch overhead (the bench's bounded-overhead
        # sweep records the cost honestly)
        self.overlap_budget = 32

    def enable_delta(self) -> bool:
        """Attach the incremental delta-solve state. In-memory stores only:
        the fold consumes the synchronous ``subscribe_system`` watch fanout
        (the HTTP client's watch threads lag live reads — those deployments
        keep the from-scratch path). Safe to call twice."""
        if self.delta is not None:
            return True
        if not isinstance(self.store, Store) or not isinstance(
            self.cluster, SimCluster
        ):
            return False
        from grove_tpu.solver.deltastate import DeltaSolveState

        self.delta = DeltaSolveState(self.store, self.cluster, self.topology)
        return True

    def enable_frontier(self) -> bool:
        """Attach the partitioned solver frontier (solver/frontier.py).
        Requires the delta-solve state (the partition plan rides its
        cached NodeEncoding and maintained free matrix) and an in-process
        solver (the sidecar path keeps the global frontier). Safe to call
        twice."""
        if self.frontier is not None:
            return True
        if self.solver_sidecar is not None or not self.enable_delta():
            return False
        from grove_tpu.solver.frontier import FrontierState

        self.frontier = FrontierState(self.topology)
        return True

    def _solve_batch_delta(self, nodes: List, gang_specs: List[dict]):
        """Delta-solve hot path: assemble this tick's problem from the
        dirty-masked cluster state (no bindings repass, no topology
        re-sort), and skip the device dispatch entirely when the solver
        input is IDENTICAL to the previous tick's (equal fingerprints ⇒
        equal tensors ⇒ the deterministic wave solve returns the same
        result — the steady-state "pending backlog, nothing changed"
        spin). Returns (PackingResult, PackingProblem)."""
        prof = PROFILER.phase("encode") if PROFILER.enabled else None
        try:
            with TRACER.span(
                "solve.delta_encode", gangs=len(gang_specs), nodes=len(nodes)
            ) as span:
                problem, fingerprint = self.delta.encode(
                    nodes,
                    gang_specs,
                    pad_groups=self._pad_groups.grow(gang_specs),
                )
                span.set("reencoded", self.delta.last_reencoded)
        finally:
            if prof is not None:
                prof.end()
        if JOURNEYS.enabled:
            self._journey_encode_end = JOURNEYS.t()
        key = (fingerprint, self.chunk_size, self.max_waves)
        if self._delta_last is not None and self._delta_last[0] == key:
            self.delta.solve_reuses += 1
            METRICS.inc("delta_solve_reuses_total")
            # the cached result's solve_seconds describes the ORIGINAL
            # dispatch — no solve ran this tick, so the latency histogram
            # must not re-observe it (flag checked at the observe site)
            self._solve_reused = True
            return self._delta_last[1], problem
        self._frontier_solved = False
        if self.frontier is not None and self.solver_sidecar is None:
            # partitioned frontier: node-disjoint subproblems solved as
            # batched dispatches + a global residual pass. None ⇒ the
            # tick is degenerate (single super-domain or all-residual)
            # and falls through to the ordinary global solve below.
            prof = PROFILER.phase("solve") if PROFILER.enabled else None
            try:
                result = self.frontier.solve(self, gang_specs, problem)
            finally:
                if prof is not None:
                    prof.end()
            if result is not None:
                self._solve_reused = False
                self._frontier_solved = True
                self._delta_last = (key, result)
                return result, problem
        # the sidecar request is built from free-capacity DICTS — serve
        # them from the maintained matrix so delta state survives
        # _solve_remote without an O(bindings) repass (in-process solves
        # consume the problem tensors directly and need no dicts)
        free = (
            self.delta.free_dicts(nodes)
            if self.solver_sidecar is not None
            else None
        )
        result, problem = self._solve_batch(
            nodes, gang_specs, free, problem=problem
        )
        self._delta_last = (key, result)
        return result, problem

    def _delta_ab_check(self, nodes, gang_specs, problem, result) -> None:
        """A/B equivalence pin (delta_selfcheck): re-derive the identical
        solver input from scratch — full bindings repass, full topology
        re-encode — and assert the problem tensors AND the solve outcome
        are bit-identical to what the delta path produced. Tests, `make
        delta-smoke`, the bench "delta" block, and sanitized chaos runs
        enable this; steady-state production pays only the `if`."""
        import time as _time

        import numpy as np

        from grove_tpu.solver.deltastate import problems_identical

        t0 = _time.perf_counter()
        free = self.cluster.node_free_all(nodes)
        full = build_problem(
            nodes,
            gang_specs,
            self.topology,
            free_capacity=free,
            pad_groups=self._pad_groups.grow(gang_specs),
        )
        mismatch = problems_identical(problem, full)
        if mismatch:
            raise AssertionError(
                f"delta-solve problem diverged from the from-scratch "
                f"encode: {mismatch}"
            )
        if self._frontier_solved:
            # the partitioned frontier's result is semantically its own
            # (partition-confined placements): the delta A/B pins the
            # ENCODE equivalence above, and the frontier selfcheck owns
            # the solve comparison (batched composite vs the sequential
            # per-subproblem reference)
            self.last_selfcheck_seconds += _time.perf_counter() - t0
            return
        full_result = solve_waves(
            full,
            chunk_size=self.chunk_size,
            max_waves=self.max_waves,
            with_alloc=True,
        )
        for field in ("admitted", "placed", "score", "chosen_level", "alloc"):
            a = getattr(result, field)
            b = getattr(full_result, field)
            if (a is None) != (b is None) or (
                a is not None and not np.array_equal(a, b)
            ):
                raise AssertionError(
                    f"delta-solve result diverged from the full solve on "
                    f"{field!r}"
                )
        self.last_selfcheck_seconds += _time.perf_counter() - t0

    def _solve_batch(
        self,
        nodes: List,
        gang_specs: List[dict],
        free_capacity: Optional[Dict[str, Dict[str, float]]],
        with_alloc: bool = True,
        problem=None,
    ):
        """One batch solve against a free-capacity snapshot. In-process by
        default; with ``solver_sidecar`` set, the identical request goes
        over gRPC (cluster/grpcsolver.py) and the response is mapped back
        onto the locally-encoded problem's index space, so every downstream
        consumer (binding, preemption trials, recovery pins) is agnostic to
        where the kernel ran. Returns (PackingResult, PackingProblem).

        ``problem``: a pre-built encode (the delta-solve path) — the
        from-scratch encode is skipped, and ``free_capacity`` is then only
        consumed by the sidecar request builder (None is fine in-process)."""
        self._solve_reused = False  # a real dispatch (or sidecar call) runs
        # STICKY group padding: the encoder pads the group axis exactly
        # (wide pow2 padding wastes fill scans), but the PENDING mix's max
        # group count flips as multi-group gangs drain and re-arrive — and
        # every distinct padded shape is a fresh XLA compile. Remember the
        # widest template seen and keep padding there: compiles stay
        # monotone-few, executables keep getting reused.
        if problem is None:
            prof = PROFILER.phase("encode") if PROFILER.enabled else None
            try:
                with TRACER.span(
                    "scheduler.encode", gangs=len(gang_specs), nodes=len(nodes)
                ):
                    problem = build_problem(
                        nodes, gang_specs, self.topology,
                        free_capacity=free_capacity,
                        pad_groups=self._pad_groups.grow(gang_specs),
                    )
            finally:
                if prof is not None:
                    prof.end()
            if JOURNEYS.enabled:
                self._journey_encode_end = JOURNEYS.t()
        import time as _time

        prof = PROFILER.phase("solve") if PROFILER.enabled else None
        try:
            if (
                self.solver_sidecar is None
                or _time.monotonic() < self._sidecar_skip_until
            ):
                with TRACER.span(
                    "scheduler.solve", gangs=len(gang_specs), where="in-process"
                ):
                    result = solve_waves(
                        problem,
                        chunk_size=self.chunk_size,
                        max_waves=self.max_waves,
                        with_alloc=with_alloc,
                    )
                return result, problem
            with TRACER.span(
                "scheduler.solve", gangs=len(gang_specs), where="sidecar"
            ):
                return self._solve_remote(
                    problem, nodes, gang_specs, free_capacity, with_alloc
                )
        finally:
            if prof is not None:
                prof.end()

    def _solve_remote(
        self, problem, nodes, gang_specs, free_capacity, with_alloc: bool
    ):
        # The local build_problem still runs on this path: its
        # name/level/group index maps AND the problem object itself are what
        # every downstream consumer needs (assignments(), trial usage,
        # recovery pins) — and the encode is pure numpy, no device work, so
        # the duplicate cost vs the sidecar's own encode is tens of
        # microseconds per trial-sized request.
        import grpc
        import numpy as np

        from grove_tpu.cluster.grpcsolver import SolverClient, build_request
        from grove_tpu.sim.cluster import Node
        from grove_tpu.solver.types import PackingResult

        snapshot = [
            Node(
                name=n.name,
                capacity=dict(free_capacity.get(n.name, n.capacity)),
                labels=dict(n.labels),
            )
            for n in nodes
        ]
        request = build_request(snapshot, gang_specs, self.topology)
        request.options.chunk_size = self.chunk_size
        request.options.max_waves = self.max_waves
        request.options.stats_only = not with_alloc
        if self._sidecar_client is None:
            self._sidecar_client = SolverClient(self.solver_sidecar)
        try:
            response = self._sidecar_client.solve(
                request, timeout=self.sidecar_timeout
            )
        except grpc.RpcError as e:
            # a crashed/restarting/slow sidecar must never stall gang
            # admission: solve THIS batch in-process and drop the client so
            # a later round reattaches to the (possibly restarted) sidecar
            import logging
            import time as _time

            self._sidecar_client = None
            self.sidecar_fallbacks += 1
            code = e.code()
            log = logging.getLogger("grove_tpu.solver")
            if code in (
                grpc.StatusCode.DEADLINE_EXCEEDED,
                grpc.StatusCode.RESOURCE_EXHAUSTED,
                grpc.StatusCode.INVALID_ARGUMENT,
            ):
                # per-request failure: the identical retry is doomed —
                # don't re-ship the multi-MB request every round
                self._sidecar_skip_until = (
                    _time.monotonic() + self.sidecar_backoff_s
                )
                log.error(
                    "solver sidecar %s rejected the request (%s); solving "
                    "in-process and skipping the sidecar for %.0fs "
                    "(fallback #%d)",
                    self.solver_sidecar,
                    code,
                    self.sidecar_backoff_s,
                    self.sidecar_fallbacks,
                )
            else:
                log.warning(
                    "solver sidecar %s unavailable (%s); solved in-process "
                    "(fallback #%d), will reattach",
                    self.solver_sidecar,
                    code,
                    self.sidecar_fallbacks,
                )
            result = solve_waves(
                problem,
                chunk_size=self.chunk_size,
                max_waves=self.max_waves,
                with_alloc=with_alloc,
            )
            return result, problem

        g = problem.num_gangs
        p_max = problem.max_groups
        n_nodes = problem.num_nodes
        node_index = {name: i for i, name in enumerate(problem.node_names)}
        admitted = np.zeros((g,), dtype=bool)
        score = np.zeros((g,), dtype=np.float32)
        chosen_level = np.full((g,), -1, dtype=np.int32)
        placed = np.zeros((g, p_max), dtype=np.int32)
        alloc = np.zeros((g, p_max, n_nodes), dtype=np.int32)
        level_index = {key: i for i, key in enumerate(problem.level_keys)}
        for gi, placement in enumerate(response.placements[:g]):
            admitted[gi] = placement.admitted
            score[gi] = placement.placement_score
            chosen_level[gi] = level_index.get(placement.chosen_level_key, -1)
            group_index = {
                name: pi for pi, name in enumerate(problem.group_names[gi])
            }
            for asg in placement.assignments:
                pi = group_index.get(asg.group)
                ni = node_index.get(asg.node)
                if pi is None or ni is None:
                    continue
                alloc[gi, pi, ni] += asg.count
                placed[gi, pi] += asg.count
        result = PackingResult(
            admitted=admitted,
            placed=placed,
            score=score,
            chosen_level=chosen_level,
            alloc=alloc,
            free_after=problem.capacity,  # not consumed on this path
            solve_seconds=response.solve_seconds,
        )
        return result, problem

    # -- main loop -------------------------------------------------------

    def schedule_pending(self, namespace: Optional[str] = None) -> int:
        """Schedule pending work. namespace=None (default) covers EVERY
        namespace with pending pods in ONE priority-ordered global solve —
        nodes are shared cluster-wide, so per-namespace rounds would let a
        low-priority gang in an alphabetically-earlier namespace take
        capacity a high-priority gang elsewhere needs (priority inversion)."""
        if self.admission_fenced:
            # partition fence (docs/federation.md "Partition ≠ crash"): a
            # region cut off from the federation stops admitting NEW gangs
            # the moment its lease expires — running pods are untouched,
            # but no PodGang may flip to Scheduled while fenced, so
            # invariant F3 (never Scheduled in two clusters across a
            # partition/heal cycle) holds by construction
            return 0
        # wall attribution: everything below lands under controller
        # "scheduler" — pending-scan/encode/solve/commit phases open their
        # own rows, this phase's self-time is ordering/quota/round glue
        prof = (
            PROFILER.phase("schedule", controller="scheduler")
            if PROFILER.enabled
            else None
        )
        try:
            with TRACER.span("scheduler.schedule") as span:
                bound = self._schedule_pending(namespace)
                span.set("bound", bound)
                return bound
        finally:
            if prof is not None:
                prof.end()

    def _schedule_pending(self, namespace: Optional[str] = None) -> int:
        if namespace is None:
            # every namespace with pending pods OR existing gangs: gang
            # phase/health maintenance must keep running after everything is
            # scheduled (Starting → Running, Unhealthy upkeep)
            namespaces = sorted(
                {p.metadata.namespace for p in self._pending_pods(None)}
                | {g.metadata.namespace for g in self.store.scan("PodGang")}
            ) or ["default"]
        else:
            namespaces = [namespace]
        if namespace is None and getattr(self.store, "num_shards", 1) > 1:
            # per-shard pending feed (docs/control-plane.md §4): surface
            # how a FULL round's pending namespaces spread over keyspace
            # shards — the partitioned frontier's demand-side analogue of
            # the shard census (one O(namespaces) pass per round).
            # Shards that drained since the last full round are zeroed,
            # or the exposition would report phantom pending work
            # forever; targeted single-namespace calls leave the gauges
            # alone (they see one namespace, not the round's demand).
            #
            # Semantics under the parallel control plane (docs/
            # control-plane.md §5): the gauges describe the most recent
            # FULL scheduling round's demand — the scheduler runs only
            # on the coordination plane, `namespaces` is sorted (the
            # deterministic order the serial twin compares against), and
            # the shard-set swap below is a single atomic assignment so
            # a concurrent reader (explain/introspection off another
            # thread) never observes a torn previous-round set.
            by_shard: Dict[int, int] = {}
            for ns in namespaces:
                idx = self.store.shard_index(ns)
                by_shard[idx] = by_shard.get(idx, 0) + 1
            previous, self._pending_ns_shards = (
                self._pending_ns_shards,
                set(by_shard),
            )
            for idx in sorted(previous - set(by_shard)):
                METRICS.set(f"pending_namespaces@{idx}", 0)
            for idx, count in sorted(by_shard.items()):
                METRICS.set(f"pending_namespaces@{idx}", count)
        self.cluster._gc_bindings()
        if self.delta is not None:
            # BEFORE the pending scan: a topology change (cordon, flap,
            # capacity) must invalidate the spec cache before any spec is
            # served from it (pins/survivor seeds resolve against nodes)
            self.delta.refresh(
                [n for n in self.cluster.nodes if n.schedulable]
            )
        sticky_bound = 0
        gang_specs: List[dict] = []
        gang_pods: Dict[str, Dict[str, List]] = {}
        loose_pods: List = []  # (namespace, pod)
        with TRACER.span("scheduler.pending-scan", namespaces=len(namespaces)):
            for ns in namespaces:
                # per-shard attribution: the scan is the scheduler's only
                # namespace-partitioned work, so its rows are the demand-side
                # per-shard ledger the parallel-CP PR will A/B against
                prof = (
                    PROFILER.phase(
                        "pending-scan", shard=self.store.shard_index(ns)
                    )
                    if PROFILER.enabled
                    and getattr(self.store, "shard_index", None) is not None
                    else None
                )
                try:
                    self.update_gang_phases(ns)
                    self.update_gang_health(ns)
                    pending = self._pending_pods(ns)
                    if not pending:
                        continue
                    sticky, pending = self._bind_with_reused_reservations(
                        ns, pending
                    )
                    sticky_bound += sticky
                    specs, pods, loose = self._encode_pending(ns, pending)
                    gang_specs.extend(specs)
                    gang_pods.update(pods)
                    loose_pods.extend((ns, p) for p in loose)
                    if JOURNEYS.enabled:
                        for spec in specs:
                            JOURNEYS.note_seen(ns, spec["gang_name"])
                finally:
                    if prof is not None:
                        prof.end()

        # global solve order across all namespaces (kernel admits in input
        # order): the quota manager's fair-share pass when Queue CRs exist,
        # else the flat (-priority, name) sort — byte-identical to the
        # pre-quota path (guard rail pinned in tests/test_quota.py)
        gang_specs, held = self._order_with_quota(gang_specs)
        for spec, reason in held:
            # registered reason-detail prefix (events.py REGISTERED_DETAILS,
            # docs/observability.md "Admission explain"): GET /events alone
            # answers the common "why Pending" case with the same slug the
            # explain verdict would cite
            EVENTS.record(
                ("PodGang", spec["namespace"], spec["gang_name"]),
                TYPE_WARNING,
                REASON_QUEUE_PENDING,
                f"{DETAIL_QUOTA_CEILING}: {reason}",
            )

        bound = 0
        if gang_specs:
            # mask cordoned AND unhealthy (NotReady/Lost) nodes out of the
            # dense tensors: the encoder never sees them, so no placement,
            # recovery pin, or preemption trial can target one
            nodes = [n for n in self.cluster.nodes if n.schedulable]
            if nodes:
                jz = JOURNEYS.enabled
                if jz:
                    t_enc0 = JOURNEYS.t()
                    self._journey_encode_end = None
                # wave solver with allocations: cheap-to-compile vmapped
                # decisions (the exact scan kernel stays on the parity/bench
                # paths; unadmitted gangs retry on the next control round)
                if self.delta is not None:
                    result, problem = self._solve_batch_delta(
                        nodes, gang_specs
                    )
                else:
                    # one usage pass over bindings (node_free per node would
                    # be O(nodes × bindings) per round at stress scale)
                    free = self.cluster.node_free_all(nodes)
                    result, problem = self._solve_batch(
                        nodes, gang_specs, free
                    )
                if jz:
                    # this round's batch stamps: every gang in the batch
                    # experienced the same encode/solve walls — the
                    # admitting round's stamps become the gang's journey
                    t_solve1 = JOURNEYS.t()
                    JOURNEYS.note_round(
                        t_enc0, self._journey_encode_end or t_enc0, t_solve1
                    )
                    for spec in gang_specs:
                        JOURNEYS.note_encoded(
                            spec["namespace"], spec["gang_name"]
                        )
                if self.delta is not None and self.delta_selfcheck:
                    self._delta_ab_check(nodes, gang_specs, problem, result)
                if not self._solve_reused:
                    METRICS.observe(
                        "gang_solve_seconds", result.solve_seconds
                    )
                preempted, preempt_free = self._maybe_preempt(
                    gang_specs, result
                )
                if self.quota.active():
                    with TRACER.span("quota.reclaim") as rspan:
                        reclaimed = self._maybe_reclaim(
                            gang_specs, result, preempted, preempt_free
                        )
                        rspan.set("victims", len(reclaimed))
                    preempted |= reclaimed
                assignments = result.assignments(problem)
                # explain-grade deferral details for this round's rejects
                # (one numpy pass over tensors the solve already holds):
                # every GangDeferred event cites the registered detail
                # slug the explain engine would — docs/observability.md
                # "Admission explain"
                defer_details = {}
                if not result.admitted[: len(gang_specs)].all():
                    from grove_tpu.solver.introspect import (
                        classify_rejections,
                    )

                    defer_details = classify_rejections(
                        problem, result, gang_specs
                    )
                to_mark = []
                prof = (
                    PROFILER.phase("commit") if PROFILER.enabled else None
                )
                try:
                    self._commit_admitted(
                        gang_specs, result, assignments, gang_pods,
                        preempted, to_mark, defer_details,
                    )
                    bound += self._last_commit_bound
                finally:
                    if prof is not None:
                        prof.end()
                with TRACER.span("scheduler.status-write", gangs=len(to_mark)):
                    for ns, gang_name, score in to_mark:
                        self._mark_scheduled(ns, gang_name, score)

        # pods not in any gang (shouldn't happen for grove pods): first-fit
        for _ns, pod in loose_pods:
            for node in self.cluster.nodes:
                if node.schedulable and self.cluster.fits(node, pod):
                    self.cluster.bind(pod, node.name)
                    bound += 1
                    break
        return bound + sticky_bound

    def _commit_admitted(
        self, gang_specs, result, assignments, gang_pods, preempted, to_mark,
        defer_details=None,
    ) -> None:
        """Bind every admitted gang's pods and queue its status write —
        the commit phase of one scheduling round, split out so the
        attribution phase covers exactly it. The bound-pod count lands in
        ``self._last_commit_bound`` (the caller's round total)."""
        bound = 0
        with TRACER.span(
            "scheduler.commit", gangs=len(gang_specs)
        ) as commit_span:
            for gi, spec in enumerate(gang_specs):
                ns = spec["namespace"]
                if not result.admitted[gi]:
                    if (ns, spec["gang_name"]) not in preempted:
                        slug, text = (defer_details or {}).get(
                            gi,
                            (
                                None,
                                "insufficient capacity or unsatisfiable"
                                " topology",
                            ),
                        )
                        EVENTS.record(
                            ("PodGang", ns, spec["gang_name"]),
                            TYPE_WARNING,
                            REASON_GANG_DEFERRED,
                            f"not admitted this round"
                            f" ({slug + ': ' if slug else ''}{text})",
                        )
                    continue
                if (ns, spec["gang_name"]) in preempted:
                    # a victim's stale admission from this solve must
                    # not overwrite its Preempted status (its pods
                    # are gone)
                    continue
                for pclq_fqn, node_names in assignments[
                    spec["name"]
                ].items():
                    pods = gang_pods[spec["name"]].get(pclq_fqn, [])
                    for pod, node_name in zip(pods, node_names):
                        self.cluster.bind(pod, node_name)
                        EVENTS.record(
                            ("Pod", ns, pod.metadata.name),
                            TYPE_NORMAL,
                            REASON_POD_BOUND,
                            f"bound to {node_name}",
                        )
                        bound += 1
                # A recovery delta-solve (floors reduced by pods
                # already placed) only covers the missing pods; its
                # score says nothing about the whole gang — keep the
                # original.
                partial = any(g["partial"] for g in spec["groups"])
                EVENTS.record(
                    ("PodGang", ns, spec["gang_name"]),
                    TYPE_NORMAL,
                    REASON_GANG_ADMITTED,
                    f"placement score {float(result.score[gi]):.4f}",
                )
                if JOURNEYS.enabled:
                    JOURNEYS.note_commit(ns, spec["gang_name"])
                to_mark.append(
                    (
                        ns,
                        spec["gang_name"],
                        None if partial else float(result.score[gi]),
                    )
                )
            commit_span.set("bound", bound)
        self._last_commit_bound = bound

    def _bind_with_reused_reservations(self, namespace: str, pending: List):
        """Honor PodGang.reuseReservationRef: a recreated pod of an
        already-scheduled gang whose gang carries the reuse hint goes back to
        its previous node when that node still fits it (scheduler-side
        handling of scheduler podgang.go:67-73)."""
        from grove_tpu.api.meta import get_condition

        remaining = []
        bound = 0
        nodes_by_name = {n.name: n for n in self.cluster.nodes}
        gang_cache: Dict[str, object] = {}
        for pod in pending:
            prev = self._reuse_bind_target(
                namespace, pod, nodes_by_name, gang_cache, self.cluster.fits
            )
            if prev is not None:
                self.cluster.bind(pod, prev)
                EVENTS.record(
                    ("Pod", namespace, pod.metadata.name),
                    TYPE_NORMAL,
                    REASON_POD_BOUND,
                    f"bound to {prev} (reused reservation)",
                )
                bound += 1
            else:
                remaining.append(pod)
        return bound, remaining

    def _reuse_bind_target(
        self, namespace: str, pod, nodes_by_name, gang_cache, fits
    ) -> Optional[str]:
        """The node a pending pod would be sticky-rebound to under the
        reuse-reservation rule, or None. The WHOLE predicate (gang carries
        the hint, gang still Scheduled=True, previous node live/schedulable/
        fitting, pack constraint respected) lives here so the binding loop
        above and the read-only admission-explain replica
        (``solver/introspect.py``) judge reuse identically. ``fits`` is the
        capacity check — ``cluster.fits`` on the live path, a snapshot
        check on the replica (which must also debit the would-be bind)."""
        from grove_tpu.api.meta import get_condition

        gang_name = pod.metadata.labels.get(namegen.LABEL_PODGANG)
        if gang_name and gang_name not in gang_cache:
            gang_cache[gang_name] = self.store.get(
                "PodGang", namespace, gang_name, readonly=True
            )
        gang = gang_cache.get(gang_name) if gang_name else None
        prev = self.cluster.last_node.get((namespace, pod.metadata.name))
        cond = (
            get_condition(gang.status.conditions, COND_PODGANG_SCHEDULED)
            if gang is not None
            else None
        )
        if (
            gang is not None
            and gang.spec.reuse_reservation_ref is not None
            and cond is not None
            and cond.is_true()
            and prev in nodes_by_name
            and nodes_by_name[prev].schedulable
            and fits(nodes_by_name[prev], pod)
            and self._reuse_respects_pack_constraint(
                namespace, gang, nodes_by_name, nodes_by_name[prev]
            )
        ):
            return prev
        return None

    def _reuse_respects_pack_constraint(
        self, namespace: str, gang, nodes_by_name, candidate_node
    ) -> bool:
        """A reused reservation must not break the gang's required pack: the
        candidate node has to share the required-level domain with the gang's
        currently-bound pods (no sticky bind when none are bound — the full
        solver decides instead)."""
        tc = gang.spec.topology_constraint
        required = (
            tc.pack_constraint.required
            if tc is not None and tc.pack_constraint is not None
            else None
        )
        if required is None:
            return True
        for group in gang.spec.pod_groups:
            for ref in group.pod_references:
                bound_node_name = self.cluster.bindings.get(
                    (namespace, ref.name)
                )
                node = nodes_by_name.get(bound_node_name)
                if node is not None:
                    return node.labels.get(required) == candidate_node.labels.get(
                        required
                    )
        return False

    # -- quota ordering & status (grove_tpu/quota, docs/quota.md) --------

    def _order_with_quota(self, gang_specs: List[dict]):
        """Fair-share solve order when Queue CRs exist; the flat
        (-priority, name) sort otherwise. Returns (ordered_specs, held)."""
        if not self.quota.active():
            return (
                sorted(
                    gang_specs,
                    key=lambda s: (-s["priority"], s["name"]),
                ),
                [],
            )
        import time as _time

        t0 = _time.perf_counter()
        with TRACER.span(
            "quota.order", gangs=len(gang_specs)
        ) as span:
            ordered, held = self.quota.order_specs(gang_specs)
            span.set("held", len(held))
            span.set("queues", len(self.quota.last_rows))
        METRICS.observe("quota_order_seconds", _time.perf_counter() - t0)
        self._write_queue_status()
        return ordered, held

    def _write_queue_status(self) -> None:
        """Per-queue status + gauges after an ordering pass (write-on-
        change: the copy-on-write commit suppresses no-op writes)."""
        from grove_tpu.api.types import QueueStatus

        rows = {row["name"]: row for row in self.quota.last_rows}
        admitted: Dict[str, int] = {}
        for gang in self.store.scan("PodGang"):
            cond = get_condition(
                gang.status.conditions, COND_PODGANG_SCHEDULED
            )
            if cond is not None and cond.is_true():
                queue = (
                    gang.metadata.labels.get(namegen.LABEL_QUEUE)
                    or self.quota.default_queue
                )
                admitted[queue] = admitted.get(queue, 0) + 1
        for name, row in rows.items():
            METRICS.set(
                f"queue_dominant_share/{name}", row["dominant_share"]
            )
            METRICS.set(f"queue_pending_gangs/{name}", row["pending"])
            METRICS.set(
                f"queue_admitted_gangs/{name}", admitted.get(name, 0)
            )
            cr = row["cr"]
            if cr is None:
                continue  # implicit queue (no CR to carry status)
            st = QueueStatus(
                usage={r: round(v, 9) for r, v in row["usage"].items()},
                dominant_share=round(row["dominant_share"], 6),
                admitted_gangs=admitted.get(name, 0),
                pending_gangs=row["pending"],
                conditions=list(cr.status.conditions),
            )
            if (
                st.usage == cr.status.usage
                and st.dominant_share == cr.status.dominant_share
                and st.admitted_gangs == cr.status.admitted_gangs
                and st.pending_gangs == cr.status.pending_gangs
            ):
                continue
            self._commit_status_tolerant(cr, st)

    # -- helpers ---------------------------------------------------------

    def _update_status_tolerant(self, obj) -> bool:
        """Status upsert that tolerates optimistic-concurrency conflicts: in
        real-cluster mode the operator writes the same objects concurrently,
        and a 409 simply means the next scheduling round re-reads and
        re-derives the same condition — never a reason to crash the binder
        (the reference's scheduler retries conflicts the same way)."""
        try:
            self.store.update_status(obj)
            return True
        except GroveError as e:
            if e.code != ERR_CONFLICT:
                raise
            METRICS.inc("gang_status_conflicts_total")
            return False

    def _commit_status_tolerant(self, view, status) -> bool:
        """Copy-on-write variant of the tolerant status upsert: commits a
        private `status` against a readonly `view` (runtime/store.py
        commit_status), treating optimistic-concurrency conflicts the same
        way — the next round re-derives."""
        from grove_tpu.runtime.store import commit_status

        try:
            return commit_status(self.store, view, status) is not None
        except GroveError as e:
            if e.code != ERR_CONFLICT:
                raise
            METRICS.inc("gang_status_conflicts_total")
            return False

    def _pending_pods(self, namespace: Optional[str]) -> List:
        # read-only iteration over the cluster's not-Ready working set (a
        # pending pod is never Ready, so the subset relation is exact; the
        # set degrades to a full scan for stores without synchronous
        # events). Pods flow into the encoder; binding always re-reads
        # fresh views (SimCluster.bind).
        return [
            p
            for p in self.cluster._not_ready_pods(namespace)
            if not p.spec.scheduling_gates
            and not is_scheduled(p)
            and not is_terminating(p)
        ]

    def _overlap_token(self, namespace: str, unsched: frozenset) -> tuple:
        """Staleness token over every input ``_build_gang_spec`` reads:
        the namespace shard's emitted-event count (ANY commit or hard
        delete touching the shard moves it — covers the gang CR, pod
        objects/statuses, scheduled counts and binding-backed pins,
        since SimCluster.bind commits status before recording the
        binding), the binding-table rebuild epoch (cold restart), the
        monitor's hold-set epoch, and the cordoned-node name set (node
        schedulability is not store-backed). Token equality ⇒ a spec
        speculated then is byte-identical to one built now."""
        held = self.monitor.holds_epoch if self.monitor is not None else -1
        return (
            self.store.shard_emitted(self.store.shard_index(namespace)),
            self.cluster.bindings_epoch,
            held,
            unsched,
        )

    def speculate_encode(self) -> int:
        """Speculatively encode pending gang specs for the NEXT
        scheduling round — the overlap pump (docs/control-plane.md §5).
        The process-backend drain calls this (via engine.overlap_hook)
        after dispatching a reconcile round's remote batches and before
        blocking on worker replies, so the coordinator spends worker
        flight time on encode instead of idling.

        Pure reads only: nothing here commits, emits events, or touches
        the delta warm-start cache, so running it (or not) cannot
        change observable control-plane state — bit-identity vs the
        serial twin rests on the consumption-side token check alone.
        Returns the number of specs built this call (≤ overlap_budget).
        """
        if not isinstance(self.store, Store) or not isinstance(
            self.cluster, SimCluster
        ):
            return 0
        built = 0
        unsched = frozenset(self.cluster.unschedulable_names())
        pending_by_ns: Dict[str, List] = defaultdict(list)
        pending_gangs = set()
        for p in self._pending_pods(None):
            pending_by_ns[p.metadata.namespace].append(p)
            gname = p.metadata.labels.get(namegen.LABEL_PODGANG)
            if gname:
                pending_gangs.add((p.metadata.namespace, gname))
        if self._overlap_cache:
            # evict entries whose gang left the pending set — they can
            # never be consulted again and would accumulate forever
            for key in [
                k for k in self._overlap_cache if k not in pending_gangs
            ]:
                del self._overlap_cache[key]
        for ns in sorted(pending_by_ns):
            token = self._overlap_token(ns, unsched)
            by_gang: Dict[str, List] = defaultdict(list)
            for pod in pending_by_ns[ns]:
                gang_name = pod.metadata.labels.get(namegen.LABEL_PODGANG)
                if gang_name:
                    by_gang[gang_name].append(pod)
            for gang_name, pods in sorted(by_gang.items()):
                if self.monitor is not None and self.monitor.gang_held(
                    ns, gang_name
                ):
                    continue
                if self.delta is not None and self.delta.has_clean_spec(
                    ns, gang_name
                ):
                    # the warm-start cache wins at consumption anyway —
                    # speculating would be pure waste
                    continue
                key = (ns, gang_name)
                names = tuple(sorted(p.metadata.name for p in pods))
                entry = self._overlap_cache.get(key)
                if (
                    entry is not None
                    and entry[0] == token
                    and entry[1] == names
                ):
                    # already speculated against the current state (the
                    # hook fires once per drain batch — later batches of
                    # a quiet round see the same token)
                    continue
                result = self._build_gang_spec(ns, gang_name, pods)
                if result is None:
                    self._overlap_cache.pop(key, None)
                    continue
                spec, by_pclq = result
                self._overlap_cache[key] = (token, names, spec, dict(by_pclq))
                built += 1
                if built >= self.overlap_budget:
                    return built
        return built

    def _encode_pending(self, namespace: str, pending: List):
        by_gang: Dict[str, List] = defaultdict(list)
        loose = []
        for pod in pending:
            gang_name = pod.metadata.labels.get(namegen.LABEL_PODGANG)
            if gang_name:
                by_gang[gang_name].append(pod)
            else:
                loose.append(pod)

        # overlap-pump consumption: the cordon signature is computed at
        # most once per namespace (only when speculated entries exist)
        unsched = None
        gang_specs: List[dict] = []
        gang_pods: Dict[str, Dict[str, List]] = {}
        for gang_name, pods in sorted(by_gang.items()):
            if self.monitor is not None and self.monitor.gang_held(
                namespace, gang_name
            ):
                # requeued gang in rate-limited backoff: keep its pods
                # pending (NOT loose — they stay gang pods) and let the
                # monitor release it into a later round
                continue
            if self.delta is not None:
                # warm start: a gang with no relevant pod/PodGang delta
                # since its spec was built (and the same pending pod set)
                # reuses the encoded spec — the spec content is canonical
                # in the pod-name SET (members are name-sorted), so the
                # cache key is exact, and every input beyond the watched
                # events (cordons, node changes) clears the whole cache
                # via the topology signature in DeltaSolveState.refresh
                hit = self.delta.cached_spec(namespace, gang_name, pods)
                if hit is not None:
                    spec, pods_by_pclq = hit
                    gang_specs.append(spec)
                    gang_pods[spec["name"]] = dict(pods_by_pclq)
                    continue
            if self._overlap_cache:
                # overlap pump (speculate_encode): reuse a spec built
                # during a worker flight window IFF its staleness token
                # still matches — any write to the shard, binding
                # rebuild, hold change or cordon since speculation
                # forces the serial rebuild below (bit-identity over
                # speed, pinned by parallel_ab). A hit LEAVES the entry
                # in place — it stays valid while its token matches, so
                # quiet rounds keep hitting; a mismatch evicts. The
                # delta cache is fed exactly as the rebuild path would,
                # so warm-start state stays twin-identical.
                entry = self._overlap_cache.get((namespace, gang_name))
                if entry is not None:
                    if unsched is None:
                        unsched = frozenset(
                            self.cluster.unschedulable_names()
                        )
                    names = tuple(sorted(p.metadata.name for p in pods))
                    if (
                        entry[0] == self._overlap_token(namespace, unsched)
                        and entry[1] == names
                    ):
                        METRICS.inc("cp_overlap_hits_total")
                        spec, by_pclq = entry[2], entry[3]
                        gang_specs.append(spec)
                        gang_pods[spec["name"]] = dict(by_pclq)
                        if self.delta is not None:
                            self.delta.store_spec(
                                namespace, gang_name, pods, spec, dict(by_pclq)
                            )
                        continue
                    METRICS.inc("cp_overlap_stale_total")
                    self._overlap_cache.pop((namespace, gang_name), None)
            built = self._build_gang_spec(namespace, gang_name, pods)
            if built is None:
                loose.extend(pods)
                continue
            spec, by_pclq = built
            gang_specs.append(spec)
            gang_pods[f"{namespace}/{gang_name}"] = dict(by_pclq)
            if self.delta is not None:
                self.delta.store_spec(
                    namespace, gang_name, pods, spec, dict(by_pclq)
                )
        return gang_specs, gang_pods, loose

    def _build_gang_spec(self, namespace: str, gang_name: str, pods: List):
        """Encode one pending gang's solver spec from its CR and pending
        pod list — the PURE (read-only) half of ``_encode_pending``,
        shared with the admission explain engine
        (``solver/introspect.py``) so the explain replica and the real
        encode can never diverge. Returns ``(spec, pods_by_pclq)`` or
        None when the PodGang CR is missing (the pods are loose)."""
        gang_cr = self.store.get(
            "PodGang", namespace, gang_name, readonly=True
        )
        if gang_cr is None:
            return None
        groups_cr = {g.name: g for g in gang_cr.spec.pod_groups}
        by_pclq: Dict[str, List] = defaultdict(list)
        for pod in pods:
            by_pclq[pod.metadata.labels.get(namegen.LABEL_PODCLIQUE, "")].append(
                pod
            )
        # PCSG-tier pack groups (scheduler podgang.go:117-126): a config
        # covering EVERY pending group is an exact collective constraint
        # and folds into the gang-level required key; a config covering a
        # subset is approximated by confining each member group to one
        # domain at that level (each member stays packed; the subset as a
        # whole may span domains — conservative per-member, relaxed
        # collectively)
        pending_group_names = set(by_pclq)
        collective_req = None
        group_cfg_req = {}
        for cfg in gang_cr.spec.topology_constraint_group_configs:
            tc = cfg.topology_constraint
            if tc is None or tc.pack_constraint is None:
                continue
            cfg_key = tc.pack_constraint.required
            if set(cfg.pod_group_names) >= pending_group_names:
                collective_req = self._narrower_key(collective_req, cfg_key)
            else:
                for member in cfg.pod_group_names:
                    group_cfg_req[member] = self._narrower_key(
                        group_cfg_req.get(member), cfg_key
                    )

        groups = []
        for pclq_fqn, members in sorted(by_pclq.items()):
            members.sort(key=lambda p: p.metadata.name)
            group_cr = groups_cr.get(pclq_fqn)
            min_replicas = group_cr.min_replicas if group_cr else len(members)
            already = self._scheduled_count(namespace, pclq_fqn)
            own_req = None
            if group_cr is not None and group_cr.topology_constraint is not None:
                pc = group_cr.topology_constraint.pack_constraint
                own_req = pc.required if pc is not None else None
            group_required = self._narrower_key(
                own_req, group_cfg_req.get(pclq_fqn)
            )
            # recovery pin: surviving pods of a constrained group anchor
            # the replacement pods to their domain
            pinned_node = None
            if group_required is not None and already > 0:
                pinned_node = self._any_bound_node(namespace, pclq_fqn)
            groups.append(
                {
                    "name": pclq_fqn,
                    "demand": members[0].spec.total_requests(),
                    "count": len(members),
                    # floor reduced by already-scheduled pods (recovery)
                    "min_count": max(0, min_replicas - already),
                    "partial": already > 0,
                    "required_key": group_required,
                    "pinned_node": pinned_node,
                }
            )
        required_key = preferred_key = None
        spread_key = None
        spread_min = 2
        spread_required = False
        tc = gang_cr.spec.topology_constraint
        if tc is not None and tc.pack_constraint is not None:
            required_key = tc.pack_constraint.required
            preferred_key = tc.pack_constraint.preferred
        spread_survivor_nodes: List[str] = []
        if tc is not None and tc.spread_constraint is not None:
            sc = tc.spread_constraint
            spread_key = sc.topology_key
            spread_min = sc.min_domains
            spread_required = (
                sc.when_unsatisfiable != SPREAD_SCHEDULE_ANYWAY
            )
            # spread recovery: a delta-solve must judge the LIVE gang's
            # spread — survivors' nodes seed the balanced fill so
            # replacements land in un-covered domains (spread analogue
            # of the pack path's gang_pinned_node below)
            if any(g["partial"] for g in groups):
                for grp in groups:
                    spread_survivor_nodes.extend(
                        self._bound_nodes(namespace, grp["name"])
                    )
        required_key = self._narrower_key(required_key, collective_req)
        # gang-level recovery pin: a gang-level required pack (template
        # constraint or collective PCSG fold) with surviving pods must
        # anchor its replacements to the survivors' domain, or the live
        # gang could end up spanning two required-level domains
        gang_pinned_node = None
        if required_key is not None and any(g["partial"] for g in groups):
            # scan ALL groups for a survivor on a live node before
            # settling for an unschedulable fallback (the encoder drops
            # pins resolved to nodes outside the solve's node set)
            cordoned = self.cluster.unschedulable_names()
            for grp in groups:
                node = self._any_bound_node(namespace, grp["name"])
                if node is None:
                    continue
                if node not in cordoned:
                    gang_pinned_node = node
                    break
                gang_pinned_node = gang_pinned_node or node
        spec = (
            {
                # globally-unique solver key (gangs from different
                # namespaces meet in one solve); the bare CR name stays
                # in gang_name
                "name": f"{namespace}/{gang_name}",
                "gang_name": gang_name,
                "namespace": namespace,
                "groups": groups,
                "required_key": required_key,
                "preferred_key": preferred_key,
                "spread_key": spread_key,
                "spread_min_domains": spread_min,
                "spread_required": spread_required,
                "spread_survivor_nodes": spread_survivor_nodes,
                "gang_pinned_node": gang_pinned_node,
                "priority": self.priority_map.get(
                    gang_cr.spec.priority_class_name, 0
                ),
                # tenant queue (quota subsystem): operator-propagated
                # label; unlabeled gangs land in the default queue
                "queue": gang_cr.metadata.labels.get(
                    namegen.LABEL_QUEUE
                )
                or self.quota.default_queue,
            }
        )
        return spec, dict(by_pclq)

    def _narrower_key(self, a: Optional[str], b: Optional[str]) -> Optional[str]:
        """Narrower (higher level index) of two topology keys."""
        keys = [k for k in self.topology.spec.levels]
        order = {lvl.key: i for i, lvl in enumerate(keys)}
        if a is None:
            return b
        if b is None:
            return a
        return a if order.get(a, -1) >= order.get(b, -1) else b

    def _any_bound_node(self, namespace: str, pclq_fqn: str) -> Optional[str]:
        """A node hosting a bound pod of the clique — preferring schedulable
        nodes (cordoned/unhealthy nodes are excluded from the solve's node
        set, so a pin resolved to one would be silently dropped by the
        encoder)."""
        cordoned = self.cluster.unschedulable_names()
        fallback = None
        for p in self.store.scan(
            "Pod", namespace, {namegen.LABEL_PODCLIQUE: pclq_fqn}
        ):
            node = self.cluster.bindings.get((namespace, p.metadata.name))
            if node is None:
                continue
            if node not in cordoned:
                return node
            fallback = fallback or node
        return fallback

    def _bound_nodes(self, namespace: str, pclq_fqn: str) -> List[str]:
        """Every node hosting a bound pod of the clique (with multiplicity)
        — the spread-recovery seed."""
        out: List[str] = []
        for p in self.store.scan(
            "Pod", namespace, {namegen.LABEL_PODCLIQUE: pclq_fqn}
        ):
            node = self.cluster.bindings.get((namespace, p.metadata.name))
            if node is not None:
                out.append(node)
        return out

    def _scheduled_count(self, namespace: str, pclq_fqn: str) -> int:
        return sum(
            1
            for p in self.store.scan(
                "Pod", namespace, {namegen.LABEL_PODCLIQUE: pclq_fqn}
            )
            if is_scheduled(p) and not is_terminating(p)
        )

    def _mark_scheduled(
        self, namespace: str, gang_name: str, score: Optional[float]
    ) -> None:
        # retry-with-fresh-read on conflict: the pods are already BOUND, so
        # skipping this write would strand a placed gang in phase Pending
        # (unlike the periodic health/phase upserts, which re-derive next
        # round anyway)
        for _ in range(4):
            gang = self.store.get("PodGang", namespace, gang_name, readonly=True)
            if gang is None:
                return
            st = clone_status(gang.status)
            if st.phase == PHASE_PENDING:
                st.phase = PHASE_STARTING
            if score is not None:
                st.placement_score = score
            set_condition(
                st.conditions,
                Condition(
                    type=COND_PODGANG_SCHEDULED,
                    status="True",
                    reason="AllPodGroupsPlaced",
                    message=f"placement score {st.placement_score}",
                ),
                self.store.clock.now(),
            )
            # a successfully (re)scheduled gang is no longer a disruption
            # target
            if (
                dt := get_condition(st.conditions, COND_PODGANG_DISRUPTION_TARGET)
            ) is not None and dt.is_true():
                set_condition(
                    st.conditions,
                    Condition(
                        type=COND_PODGANG_DISRUPTION_TARGET,
                        status="False",
                        reason="Rescheduled",
                    ),
                    self.store.clock.now(),
                )
            if self._commit_status_tolerant(gang, st):
                if JOURNEYS.enabled:
                    # Scheduled=True is durable — the journey completes and
                    # its admission decomposition is derived (a re-mark of
                    # an already-completed gang is a no-op pop)
                    JOURNEYS.note_scheduled(namespace, gang_name)
                return

    # -- preemption (SURVEY §7 'hard parts': explicit solver feature) -----

    def _maybe_preempt(self, gang_specs, result):
        """Higher-priority pending gangs that the solver could not admit may
        evict strictly-lower-priority scheduled gangs: victims get the
        DisruptionTarget condition (scheduler podgang.go:157-165) and their
        pods are deleted; the controllers recreate them gated and the gangs
        re-queue, while each preemptor is admitted in a later round against
        the freed capacity. Returns victim (namespace, gang_name) keys.

        Victims are searched across ALL namespaces — nodes are shared
        cluster-wide, so a high-priority gang must never pend behind
        lower-priority gangs that happen to live elsewhere. Multiple
        preemptors are processed per round, highest priority first; each
        preemptor's trial counts only its OWN victims' freed capacity (no
        double-spending another preemptor's evictions).

        Thrash guards: only BOUND victim pods count as freeable capacity, and
        an eviction only proceeds when a TRIAL SOLVE of the preemptor against
        the hypothetically-freed cluster admits it (a topologically
        infeasible preemptor — e.g. a required pack no single domain can ever
        satisfy — must never cost victims their placement). After a
        successful trial the victim set is PRUNED to an inclusion-minimal
        one: victims whose removal keeps the trial admitting are dropped,
        highest-priority candidates first, so a topology-constrained
        preemptor never evicts gangs on nodes irrelevant to its pack.

        Returns (victim_keys, base_free) — base_free is the shared capacity
        snapshot WITH every preemptor's planned placement debited, handed
        to quota reclaim so it never double-spends preemptor-earmarked
        capacity (None when no preemption round ran)."""
        rejected = sorted(
            (
                spec
                for i, spec in enumerate(gang_specs)
                if not result.admitted[i] and spec["priority"] > 0
            ),
            key=lambda s: (-s["priority"], s["name"]),
        )
        if not rejected:
            return set(), None
        nodes = [n for n in self.cluster.nodes if n.schedulable]
        if not nodes:
            return set(), None
        # resolve the broker ONCE per round: active() scans PodCliqueSets
        # while un-armed, and would_allow runs per candidate victim — at
        # bench scale the inert path must not pay O(victims × sets)
        broker = self._active_broker()

        # Snapshot free capacity ONCE: _evict_victim deletes victim pods from
        # the store, which would silently add the freed capacity to every
        # LATER preemptor's solo check and trial solve (double-spending
        # capacity already earmarked for an earlier preemptor — the later
        # preemptor would either skip a needed eviction or evict a
        # too-small victim set that never makes it placeable). Each
        # preemptor's PLANNED PLACEMENT (its trial alloc) is then debited
        # from the snapshot, so a lower-priority preemptor can never clear
        # its trial on base capacity a higher-priority preemptor is about to
        # consume (which would evict victims for a gang that still can't
        # place next round).
        base_free = {
            node.name: dict(self.cluster.node_free(node)) for node in nodes
        }
        all_victim_keys: set = set()
        for preemptor in rejected:
            victims_chosen, free_delta = self._select_preemption_victims(
                preemptor, nodes, base_free, exclude=all_victim_keys,
                broker=broker,
            )
            if (
                victims_chosen
                and broker is not None
                and not broker.grant(victims_chosen, "preemption")
            ):
                # budget/breaker denied the victim set: nothing is evicted
                # and nothing folds into the snapshot — the preemptor
                # simply stays pending and retries a later round
                continue
            for gang in victims_chosen:
                self._evict_victim(gang, preemptor)
                all_victim_keys.add(
                    (gang.metadata.namespace, gang.metadata.name)
                )
            for node_name, caps in free_delta.items():
                acc = base_free.setdefault(node_name, {})
                for r, q in caps.items():
                    acc[r] = acc.get(r, 0.0) + q
        return all_victim_keys, base_free

    @staticmethod
    def _placement_usage(result, problem, preemptor: dict) -> Dict:
        """Per-node resources the preemptor's trial placement consumes, in
        ORIGINAL units (alloc holds pod counts, which are unit-free; the
        quantized kernel capacities never leave the solver)."""
        import numpy as np

        demand_by_group = {g["name"]: g["demand"] for g in preemptor["groups"]}
        usage: Dict[str, Dict[str, float]] = {}
        alloc = result.alloc[0]  # [P, N]
        for p, gname in enumerate(problem.group_names[0]):
            dem = demand_by_group.get(gname, {})
            for n in np.nonzero(alloc[p])[0]:
                k = int(alloc[p][n])
                caps = usage.setdefault(problem.node_names[int(n)], {})
                for r, q in dem.items():
                    caps[r] = caps.get(r, 0.0) - q * k  # negative = consumed
        return usage

    def _active_broker(self):
        """The disruption broker when it is ACTIVE (budgets exist or a
        drain armed it), else None — callers resolve once per round so the
        inert path costs one scan, not one per candidate victim."""
        if self.broker is not None and self.broker.active():
            return self.broker
        return None

    def _select_preemption_victims(
        self,
        preemptor: dict,
        nodes: List,
        base_free: Dict,
        exclude: set,
        broker=None,
    ):
        """Choose an inclusion-minimal set of scheduled lower-priority gangs
        (any namespace, not already in `exclude`) whose eviction makes the
        preemptor placeable; empty when no eviction helps. `base_free` is the
        capacity snapshot shared by all preemptors this round. Returns
        (victims, free_delta) where free_delta is the per-node capacity
        adjustment — victims' freed capacity minus the preemptor's planned
        placement — the caller folds into the snapshot for later
        preemptors."""
        # The wave solver is heuristic: "not admitted" can be a seed/budget
        # artifact, not infeasibility. If the gang fits the CURRENT free
        # capacity on its own, it will simply be placed next round — never
        # evict for it (but DO reserve its planned placement against later
        # preemptors' trials).
        solo, solo_problem = self._solve_batch(nodes, [preemptor], base_free)
        if solo.admitted[0]:
            return [], self._placement_usage(solo, solo_problem, preemptor)

        victims = []
        for gang in self.store.list("PodGang"):  # every namespace
            if (gang.metadata.namespace, gang.metadata.name) in exclude:
                continue
            cond = get_condition(gang.status.conditions, COND_PODGANG_SCHEDULED)
            if cond is None or not cond.is_true():
                continue
            victim_priority = self.priority_map.get(
                gang.spec.priority_class_name, 0
            )
            if victim_priority >= preemptor["priority"]:
                continue
            if broker is not None and not broker.would_allow(gang):
                # its set's disruptionBudget (or the storm breaker) would
                # deny the eviction: keep it out of the trial so a doomed
                # victim set is never selected
                continue
            victims.append((victim_priority, gang))
        if not victims:
            return [], {}
        victims.sort(
            key=lambda v: (v[0], v[1].metadata.namespace, v[1].metadata.name)
        )
        return self._trial_victim_selection(
            preemptor, nodes, base_free, [g for _, g in victims]
        )

    def _trial_victim_selection(
        self, preemptor: dict, nodes: List, base_free: Dict, ordered_victims: List
    ):
        """Shared trial-solve machinery (priority preemption AND quota
        reclaim): accumulate candidate victims in preference order until
        their freed capacity covers the preemptor's aggregate floor demand,
        verify with a trial solve against the hypothetically-freed cluster,
        prune to an inclusion-minimal set (latest-accumulated dropped
        first), and return (victims, free_delta) where free_delta = freed
        capacity − the preemptor's planned placement."""
        demand_total: Dict[str, float] = {}
        for group in preemptor["groups"]:
            for r, q in group["demand"].items():
                demand_total[r] = demand_total.get(r, 0.0) + q * group["min_count"]

        def gang_freed_per_node(gang) -> Dict[str, Dict[str, float]]:
            """Per-node resources released by evicting this gang (bound pods
            only)."""
            per_node: Dict[str, Dict[str, float]] = {}
            for group in gang.spec.pod_groups:
                for ref in group.pod_references:
                    node_name = self.cluster.bindings.get(
                        (ref.namespace, ref.name)
                    )
                    if node_name is None:
                        continue
                    pod = self.store.get(
                        "Pod", ref.namespace, ref.name, readonly=True
                    )
                    if pod is None:
                        continue
                    caps = per_node.setdefault(node_name, {})
                    for r, q in pod.spec.total_requests().items():
                        caps[r] = caps.get(r, 0.0) + q
            return per_node

        # accumulate in preference order until cluster-total freed covers
        # the preemptor's aggregate floor demand (necessary condition)
        freed: Dict[str, float] = {}
        chosen: List = []
        chosen_freed: List[Dict[str, Dict[str, float]]] = []
        for gang in ordered_victims:
            per_node = gang_freed_per_node(gang)
            if not per_node:
                continue  # nothing bound → eviction frees nothing
            chosen.append(gang)
            chosen_freed.append(per_node)
            for caps in per_node.values():
                for r, q in caps.items():
                    freed[r] = freed.get(r, 0.0) + q
            if all(freed.get(r, 0.0) >= q for r, q in demand_total.items()):
                break
        else:
            return [], {}  # evicting everything lower still wouldn't fit

        def run_trial(keep: List[int], with_alloc: bool = False):
            trial_free = {}
            add: Dict[str, Dict[str, float]] = {}
            for i in keep:
                for node_name, caps in chosen_freed[i].items():
                    acc = add.setdefault(node_name, {})
                    for r, q in caps.items():
                        acc[r] = acc.get(r, 0.0) + q
            for node in nodes:
                caps = dict(base_free[node.name])
                for r, q in add.get(node.name, {}).items():
                    caps[r] = caps.get(r, 0.0) + q
                trial_free[node.name] = caps
            return self._solve_batch(
                nodes, [preemptor], trial_free, with_alloc=with_alloc
            )

        keep = list(range(len(chosen)))
        result, _ = run_trial(keep)
        if not result.admitted[0]:
            return [], {}  # eviction would not make the preemptor placeable

        # prune to an inclusion-minimal victim set: drop the most valuable
        # (highest-priority, i.e. latest-accumulated) victims first
        for i in reversed(range(len(chosen))):
            reduced = [j for j in keep if j != i]
            if reduced:
                result, _ = run_trial(reduced)
                if result.admitted[0]:
                    keep = reduced

        # final kept trial with allocations: the free delta for later
        # preemptors = kept victims' freed capacity − this placement
        final, final_problem = run_trial(keep, with_alloc=True)
        delta: Dict[str, Dict[str, float]] = {}
        if final.admitted[0]:
            delta = self._placement_usage(final, final_problem, preemptor)
        for i in keep:
            for node_name, caps in chosen_freed[i].items():
                acc = delta.setdefault(node_name, {})
                for r, q in caps.items():
                    acc[r] = acc.get(r, 0.0) + q
        return [chosen[i] for i in keep], delta

    # -- quota reclaim (docs/quota.md "reclaim vs preemption") ------------

    def _gang_requests_total(self, gang) -> Dict[str, float]:
        """Cluster-total resources the gang's BOUND pods hold (what evicting
        it returns to its queue)."""
        out: Dict[str, float] = {}
        for group in gang.spec.pod_groups:
            for ref in group.pod_references:
                if self.cluster.bindings.get((ref.namespace, ref.name)) is None:
                    continue
                pod = self.store.get(
                    "Pod", ref.namespace, ref.name, readonly=True
                )
                if pod is None:
                    continue
                for r, v in pod.spec.total_requests().items():
                    out[r] = out.get(r, 0.0) + v
        return out

    def _reclaim_pool(self, crs: Dict, exclude: set) -> List:
        """ONE scan's worth of potential reclaim victims for the whole
        round: every scheduled gang with bound capacity, tagged with its
        queue, the queue's deserved shares, freed totals, and priority.
        Per-claimant filtering (shares, budgets) happens against this pool
        — the scan and the per-pod reads must not repeat per claimant."""
        pool = []
        for gang in self.store.scan("PodGang"):
            key = (gang.metadata.namespace, gang.metadata.name)
            if key in exclude:
                continue
            cond = get_condition(gang.status.conditions, COND_PODGANG_SCHEDULED)
            if cond is None or not cond.is_true():
                continue
            queue = (
                gang.metadata.labels.get(namegen.LABEL_QUEUE)
                or self.quota.default_queue
            )
            freed = self._gang_requests_total(gang)
            if not freed:
                continue  # nothing bound -> eviction frees nothing
            cr = crs.get(queue)
            deserved = dict(cr.spec.deserved) if cr is not None else {}
            priority = self.priority_map.get(gang.spec.priority_class_name, 0)
            pool.append((gang, queue, deserved, freed, priority))
        return pool

    @staticmethod
    def _reclaim_candidates(
        pool: List, claimant: dict, usage_sim: Dict, exclude: set
    ) -> List:
        """Victim candidates for one claimant from the round's shared pool,
        in eviction-preference order: scheduled gangs of OTHER queues
        strictly above their deserved share, whose eviction keeps their
        queue at/above deserved (zero-deserved queues are always
        reclaimable — they are entitled to nothing). The stay-above-
        deserved budget is applied sequentially against a running usage
        sim, so multiple victims from one queue can't collectively drag it
        below deserved; pruning only ever REMOVES victims, which keeps the
        invariant. Returns [(gang, freed_totals)]."""
        from grove_tpu.quota.oracle import dominant_share_of

        scored = []
        for gang, queue, deserved, freed, priority in pool:
            if (gang.metadata.namespace, gang.metadata.name) in exclude:
                continue
            if queue == claimant["queue"]:
                continue
            share = dominant_share_of(usage_sim.get(queue, {}), deserved)
            if deserved and share <= 1.0 + 1e-6:
                continue  # at/below deserved: protected from reclaim
            if not deserved and share <= 0.0:
                continue  # zero-deserved queue with no usage
            scored.append((share, priority, queue, deserved, freed, gang))
        # most-over-deserved queue first; within it lowest priority, name
        scored.sort(
            key=lambda t: (
                -t[0],
                t[1],
                t[5].metadata.namespace,
                t[5].metadata.name,
            )
        )
        out = []
        sim = {q: dict(v) for q, v in usage_sim.items()}
        for share, _prio, queue, deserved, freed, gang in scored:
            row = sim.get(queue, {})
            after = {r: row.get(r, 0.0) - freed.get(r, 0.0) for r in row}
            if deserved and dominant_share_of(after, deserved) < 1.0 - 1e-6:
                continue  # would drag the victim queue below deserved
            out.append((gang, freed))
            sim[queue] = after
        return out

    def _maybe_reclaim(
        self,
        gang_specs: List[dict],
        result,
        already_evicted: set,
        preempt_free: Optional[Dict] = None,
    ) -> set:
        """Cross-queue quota reclaim: a pending gang whose queue sits BELOW
        its deserved share may evict gangs from queues ABOVE theirs —
        priority plays no part across queues (that is what distinguishes
        reclaim from preemption; docs/quota.md). Reuses the preemption
        trial-solve machinery, so reclaim never evicts without a feasible
        placement for the claimant; victim queues never drop below their
        deserved share (no reclaim ping-pong), and each claimant's planned
        placement is debited from the shared capacity snapshot so later
        claimants can't double-spend. Returns victim (ns, name) keys."""
        crs = self.quota.queue_crs()
        if not crs:
            return set()
        usage_sim = {
            q: dict(v) for q, v in self.quota.accountant.snapshot().items()
        }
        claimants = []
        for i, spec in enumerate(gang_specs):
            if result.admitted[i]:
                continue
            if (spec["namespace"], spec["gang_name"]) in already_evicted:
                continue
            cr = crs.get(spec["queue"])
            if cr is None or not cr.spec.deserved:
                continue  # no entitlement -> nothing to reclaim toward
            claimants.append(spec)
        if not claimants:
            return set()
        nodes = [n for n in self.cluster.nodes if n.schedulable]
        if not nodes:
            return set()
        from grove_tpu.quota.oracle import dominant_share_of

        # shared capacity snapshot across claimants (same double-spend
        # guard as _maybe_preempt) — and when a preemption round ran this
        # round, START from ITS snapshot: the priority preemptors' planned
        # placements are already debited there, so reclaim trial solves
        # can't clear on capacity a preemptor is about to consume
        base_free = preempt_free or {
            node.name: dict(self.cluster.node_free(node)) for node in nodes
        }
        # one PodGang scan + per-pod reads for the whole round; claimants
        # re-filter this pool against the evolving usage sim
        pool = self._reclaim_pool(crs, already_evicted)
        # one broker-activity resolution per round (see _maybe_preempt)
        broker = self._active_broker()

        def claimant_key(spec):
            share = dominant_share_of(
                usage_sim.get(spec["queue"], {}),
                dict(crs[spec["queue"]].spec.deserved),
            )
            return (share, -spec["priority"], spec["name"])

        evicted: set = set()
        for claimant in sorted(claimants, key=claimant_key):
            deserved = dict(crs[claimant["queue"]].spec.deserved)
            share = dominant_share_of(
                usage_sim.get(claimant["queue"], {}), deserved
            )
            if share >= 1.0 - 1e-6:
                continue  # queue reached deserved (earlier claimant did it)
            candidates = self._reclaim_candidates(
                pool, claimant, usage_sim, evicted
            )
            if broker is not None and candidates:
                # disruptionBudget-protected gangs are not reclaim fodder:
                # filter before the trial so the selection never builds a
                # victim set the broker would refuse to grant
                candidates = [
                    (g, f) for g, f in candidates if broker.would_allow(g)
                ]
            # solo-fit short-circuit lives inside the shared machinery via
            # the solo trial in _trial_victim_selection's caller — here the
            # claimant failing this round's solve is the signal; still, a
            # gang that fits current free capacity places next round on its
            # own, so never evict for it (but debit its placement)
            solo, solo_problem = self._solve_batch(
                nodes, [claimant], base_free
            )
            if solo.admitted[0]:
                delta = self._placement_usage(solo, solo_problem, claimant)
                victims = []
            elif candidates:
                victims, delta = self._trial_victim_selection(
                    claimant, nodes, base_free, [g for g, _ in candidates]
                )
            else:
                continue
            if (
                victims
                and broker is not None
                and not broker.grant(victims, "quota-reclaim")
            ):
                # denied between filter and trial (budgets recount live
                # state): evict nothing, fold nothing, next claimant
                continue
            freed_by_key = {
                (g.metadata.namespace, g.metadata.name): freed
                for g, freed in candidates
            }
            for gang in victims:
                key = (gang.metadata.namespace, gang.metadata.name)
                self._evict_victim(
                    gang,
                    claimant,
                    disruption_reason="QuotaReclaimed",
                    sched_reason="Reclaimed",
                    event_reason=REASON_QUOTA_RECLAIM,
                    message=(
                        f"reclaimed for {claimant['name']} "
                        f"(queue {claimant['queue']} below deserved share)"
                    ),
                    metric="quota_reclaims_total",
                )
                evicted.add(key)
                # return the victim's capacity to the usage sim so later
                # budget checks see it gone
                queue = (
                    gang.metadata.labels.get(namegen.LABEL_QUEUE)
                    or self.quota.default_queue
                )
                row = usage_sim.setdefault(queue, {})
                for r, v in freed_by_key.get(key, {}).items():
                    row[r] = row.get(r, 0.0) - v
            if victims or solo.admitted[0]:
                # charge the claimant's demand to its queue so a sibling
                # claimant doesn't over-reclaim toward the same entitlement
                row = usage_sim.setdefault(claimant["queue"], {})
                for r, v in spec_demand(claimant).items():
                    row[r] = row.get(r, 0.0) + v
            for node_name, caps in delta.items():
                acc = base_free.setdefault(node_name, {})
                for r, q in caps.items():
                    acc[r] = acc.get(r, 0.0) + q
        return evicted

    def _evict_victim(
        self,
        gang,
        preemptor: dict,
        *,
        disruption_reason: str = "PreemptedByHigherPriority",
        sched_reason: str = "Preempted",
        event_reason: str = REASON_PREEMPTED,
        message: Optional[str] = None,
        metric: str = "gang_preemptions_total",
    ) -> None:
        """Evict a scheduled gang — shared by priority preemption (default
        reasons) and quota reclaim (QuotaReclaimed / QuotaReclaim). The
        victim-side Event names the claimant, in the VICTIM's namespace."""
        # retry-with-fresh-read: the eviction status and the pod deletions
        # must land together, or a conflicted write would leave evicted pods
        # with a gang still claiming Scheduled=True
        ns, name = gang.metadata.namespace, gang.metadata.name
        message = message or f"preempted by {preemptor['name']}"
        for _ in range(4):
            fresh = self.store.get("PodGang", ns, name)
            if fresh is None:
                return
            now = self.store.clock.now()
            set_condition(
                fresh.status.conditions,
                Condition(
                    type=COND_PODGANG_DISRUPTION_TARGET,
                    status="True",
                    reason=disruption_reason,
                    message=message,
                ),
                now,
            )
            set_condition(
                fresh.status.conditions,
                Condition(
                    type=COND_PODGANG_SCHEDULED,
                    status="False",
                    reason=sched_reason,
                    message=message,
                ),
                now,
            )
            fresh.status.phase = PHASE_PENDING
            fresh.status.placement_score = None
            if self._update_status_tolerant(fresh):
                break
        # victim pods recreate gated via their PCLQs (concurrent deletion by
        # the operator is fine — the outcome, pod gone, is what matters)
        for group in gang.spec.pod_groups:
            for ref in group.pod_references:
                try:
                    self.store.delete("Pod", ref.namespace, ref.name)
                except GroveError as e:
                    if e.code != ERR_NOT_FOUND:
                        raise
        EVENTS.record(
            ("PodGang", ns, name),
            TYPE_WARNING,
            event_reason,
            f"preempted by higher-priority gang {preemptor['name']}"
            if event_reason == REASON_PREEMPTED
            else message,
        )
        METRICS.inc(metric)

    def update_gang_health(self, namespace: str = "default") -> None:
        """Unhealthy condition: any constituent PCLQ currently breaching
        MinAvailable marks the gang a gang-termination candidate
        (scheduler podgang.go:157-161)."""
        from grove_tpu.api.types import COND_MIN_AVAILABLE_BREACHED

        # readonly scan + change detection: gangs whose Unhealthy condition
        # already reads correctly are not materialized and not written —
        # previously this loop pickled and structurally re-compared EVERY
        # gang EVERY round (the dominant steady-state cost at 10k gangs)
        for gang in self.store.scan("PodGang", namespace):
            breached = False
            for group in gang.spec.pod_groups:
                pclq = self.store.get(
                    "PodClique", namespace, group.name, readonly=True
                )
                if pclq is None:
                    continue
                cond = get_condition(
                    pclq.status.conditions, COND_MIN_AVAILABLE_BREACHED
                )
                if cond is not None and cond.is_true():
                    breached = True
                    break
            want_status = "True" if breached else "False"
            want_reason = (
                "ConstituentBreachedMinAvailable"
                if breached
                else "AllConstituentsHealthy"
            )
            existing = get_condition(
                gang.status.conditions, COND_PODGANG_UNHEALTHY
            )
            if (
                existing is not None
                and existing.status == want_status
                and existing.reason == want_reason
            ):
                continue  # exactly the store's no-op suppression, earlier
            st = clone_status(gang.status)
            set_condition(
                st.conditions,
                Condition(
                    type=COND_PODGANG_UNHEALTHY,
                    status=want_status,
                    reason=want_reason,
                ),
                self.store.clock.now(),
            )
            self._commit_status_tolerant(gang, st)

    def update_gang_phases(self, namespace: str = "default") -> None:
        """Advance Starting → Running (+ Ready condition) once every pod of
        the gang is Ready (scheduler podgang.go:139-151 phase semantics).
        Also level-triggered self-heal: a gang whose pods are ALL bound but
        whose phase still reads Pending had its _mark_scheduled write lost
        to conflict exhaustion — re-derive the Scheduled state here rather
        than stranding it (no other path revisits a fully-bound gang)."""
        from grove_tpu.api.pod import is_ready

        # readonly scan: Running gangs (the steady-state majority) are
        # skipped without materializing a copy; only an actual phase
        # transition builds a private status for the copy-on-write commit
        for gang in self.store.scan("PodGang", namespace):
            if gang.status.phase == PHASE_PENDING and gang.spec.pod_groups:
                # short-circuit at the first unbound pod: this self-heal
                # check re-runs for every still-pending gang every round,
                # and during ramp-up almost every gang fails on pod #1
                all_bound = False
                total = 0
                for group in gang.spec.pod_groups:
                    all_bound = True
                    for ref in group.pod_references:
                        total += 1
                        p = self.store.get(
                            "Pod", ref.namespace, ref.name, readonly=True
                        )
                        if (
                            p is None
                            or not is_scheduled(p)
                            or is_terminating(p)
                        ):
                            all_bound = False
                            break
                    if not all_bound:
                        break
                if total and all_bound:
                    self._mark_scheduled(
                        namespace, gang.metadata.name, None
                    )
                continue
            if gang.status.phase != PHASE_STARTING:
                continue
            all_ready = True
            total = 0
            for group in gang.spec.pod_groups:
                if not all_ready:
                    break
                for ref in group.pod_references:
                    total += 1
                    pod = self.store.get(
                        "Pod", ref.namespace, ref.name, readonly=True
                    )
                    if pod is None or not is_ready(pod):
                        all_ready = False
                        break
            if total and all_ready:
                st = clone_status(gang.status)
                st.phase = PHASE_RUNNING
                set_condition(
                    st.conditions,
                    Condition(
                        type="Ready",
                        status="True",
                        reason="AllPodGroupsReady",
                        message="all constituent pods are ready",
                    ),
                    self.store.clock.now(),
                )
                self._commit_status_tolerant(gang, st)
