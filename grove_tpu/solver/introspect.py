"""Read-only solver introspection: the data layer of the admission
explain engine (observability/explain.py, docs/observability.md
"Admission explain").

Everything here answers "what would the next scheduling round see?"
WITHOUT running it: the pending frontier is re-collected through the very
same spec builder the scheduler encodes with
(``GangScheduler._build_gang_spec``), sticky reservation-reuse is judged
by the same predicate (``_reuse_bind_target``) against a PRIVATE free
snapshot, and trial solves go through ``build_problem``/``solve_waves``
directly — never through the scheduler's stateful ``_solve_batch`` — so
an explain burst leaves the scheduler, the delta-solve state, and the
store untouched (the read-only pin: ``Store.resource_version_vector()``
and ``DeltaSolveState.state_fingerprint()`` byte-identical before and
after; grovelint GL016 locks the module to this contract).

Shared vocabulary: the deferral-detail slugs live in
``observability/events.py`` (``REGISTERED_DETAILS``) because the
scheduler stamps them into ``GangDeferred``/``QueuePending`` events —
``classify_rejections`` is the one implementation both the event
enrichment and the explain funnel cite, so an event's one-line reason and
the full verdict can never disagree.

The per-domain fragmentation statistic (``fragmentation_stats``): at
topology level l, for resource r,

    frag(l, r) = 1 - (largest single-domain free at l) / (total free)

— the fraction of free capacity NOT reachable inside one max-contiguous
domain slab. 0 means one domain holds all free capacity (a contiguous
pack of that size can land); approaching 1 means the free capacity is
shredded across domains (definition shared verbatim with docs/solver.md
and docs/observability.md; ROADMAP's fragmentation-aware scoring will
consume exactly this number).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from grove_tpu.api import names as namegen
from grove_tpu.observability.events import (
    DETAIL_INSUFFICIENT_CAPACITY,
    DETAIL_NODE_FRAGMENTATION,
    DETAIL_NO_NODES,
    DETAIL_TOPOLOGY_FRAGMENTATION,
    DETAIL_UNSATISFIABLE,
)
from grove_tpu.solver.encode import (
    ConstraintError,
    build_problem,
    domain_boundaries,
    encode_nodes,
)
from grove_tpu.solver.kernel import solve_waves


# -- pending-frontier replica ------------------------------------------------


@dataclass
class PendingView:
    """One consistent read-only snapshot of the next round's solver input:
    the schedulable node set, a PRIVATE free-capacity snapshot (sticky
    reservation-reuse binds already debited, exactly as the round would
    apply them before encoding), every encodable pending gang spec, and
    the gangs excluded from the solve (monitor holds)."""

    nodes: List  # schedulable Node objects
    free: Dict[str, Dict[str, float]]  # node -> resource -> free (private)
    specs: List[dict]  # encodable pending specs, pre-order
    held_monitor: List[Tuple[str, str]] = field(default_factory=list)
    # monitor-held gangs' specs (NOT in `specs` — the round skips them at
    # encode, but the explain funnel still judges their intrinsic fit)
    held_specs: Dict[Tuple[str, str], dict] = field(default_factory=dict)
    sticky_rebinds: int = 0  # pods the round would sticky-bind pre-solve
    total_nodes: int = 0  # including unschedulable


def _fits_free(free_row: Dict[str, float], pod) -> bool:
    return all(
        free_row.get(r, 0.0) >= q
        for r, q in pod.spec.total_requests().items()
    )


def collect_pending(
    scheduler,
    nodes: Optional[List] = None,
    free: Optional[Dict[str, Dict[str, float]]] = None,
    all_nodes: Optional[List] = None,
) -> PendingView:
    """Collect the cluster-wide pending frontier exactly as
    ``_schedule_pending`` would see it, without mutating anything:
    namespaces with pending pods, sticky reuse debited against the
    snapshot (never bound), monitor-held gangs excluded, every other gang
    encoded through ``_build_gang_spec``. ``nodes``/``free``/``all_nodes``
    override the live cluster for hypothetical (what-if) views."""
    cluster = scheduler.cluster
    if all_nodes is None:
        all_nodes = list(cluster.nodes)
    if nodes is None:
        nodes = [n for n in all_nodes if n.schedulable]
    if free is None:
        free = cluster.node_free_all(nodes)
    # PRIVATE deep-ish copy: sticky debits below must not leak into a
    # caller-shared dict (node_free_all already returns fresh dicts, but
    # what-if callers hand in composed snapshots they reuse)
    free = {name: dict(caps) for name, caps in free.items()}
    view = PendingView(
        nodes=nodes, free=free, specs=[], total_nodes=len(all_nodes)
    )
    nodes_by_name = {n.name: n for n in all_nodes}
    namespaces = sorted(
        {p.metadata.namespace for p in scheduler._pending_pods(None)}
    )
    for ns in namespaces:
        pending = scheduler._pending_pods(ns)
        gang_cache: Dict[str, object] = {}
        remaining = []
        for pod in pending:
            prev = scheduler._reuse_bind_target(
                ns,
                pod,
                nodes_by_name,
                gang_cache,
                lambda node, p: _fits_free(free.get(node.name, {}), p),
            )
            if prev is not None and prev in free:
                # the round would bind this pod pre-solve: debit the
                # snapshot so the encoded gangs compete for what is left
                row = free[prev]
                for r, q in pod.spec.total_requests().items():
                    row[r] = row.get(r, 0.0) - q
                view.sticky_rebinds += 1
            else:
                remaining.append(pod)
        by_gang: Dict[str, List] = {}
        for pod in remaining:
            gang_name = pod.metadata.labels.get(namegen.LABEL_PODGANG)
            if gang_name:
                by_gang.setdefault(gang_name, []).append(pod)
        for gang_name, pods in sorted(by_gang.items()):
            built = scheduler._build_gang_spec(ns, gang_name, pods)
            if built is None:
                continue
            if scheduler.monitor is not None and scheduler.monitor.gang_held(
                ns, gang_name
            ):
                view.held_monitor.append((ns, gang_name))
                view.held_specs[(ns, gang_name)] = built[0]
                continue
            view.specs.append(built[0])
    return view


def order_view(
    scheduler,
    specs: List[dict],
    queue_crs: Optional[Dict[str, object]] = None,
    usage: Optional[Dict[str, Dict[str, float]]] = None,
):
    """The round's solve order for ``specs``: the quota manager's
    fair-share pass when Queue CRs exist (``queue_crs``/``usage`` override
    the live tree and ledger for what-if trials), the flat
    ``(-priority, name)`` sort otherwise. Goes through the ONE
    ``QuotaManager.order_specs`` implementation — with ``record_rows``
    off, so a concurrent real round's status writer never reads replayed
    rows. Returns (ordered, held)."""
    quota = scheduler.quota
    crs = queue_crs if queue_crs is not None else quota.queue_crs()
    # empty crs included: order_specs owns the flat-sort degenerate case
    # too, so a tiebreak change there can never diverge from this replica
    return quota.order_specs(specs, crs=crs, usage=usage, record_rows=False)


def queue_usage(scheduler) -> Dict[str, Dict[str, float]]:
    """Per-queue usage snapshot (private dict copies) — the ledger the
    ordering pass would consume this round."""
    return {
        q: dict(v) for q, v in scheduler.quota._usage_snapshot().items()
    }


def solve_view(scheduler, nodes: List, free: Dict, specs: List[dict]):
    """One read-only trial solve of ``specs`` against the snapshot —
    ``build_problem`` + ``solve_waves`` directly (never the scheduler's
    stateful ``_solve_batch``), padded exactly as the next real solve will
    pad (``StickyGroupPad.peek``). Returns (result, problem), or
    (None, None) on an empty frontier."""
    if not specs or not nodes:
        return None, None
    problem = build_problem(
        nodes,
        specs,
        scheduler.topology,
        free_capacity=free,
        pad_groups=scheduler._pad_groups.peek(specs),
    )
    result = solve_waves(
        problem,
        chunk_size=scheduler.chunk_size,
        max_waves=scheduler.max_waves,
        with_alloc=False,
    )
    return result, problem


def gang_spec_from_cr(store, scheduler, gang) -> dict:
    """Whole-gang solver spec from the PodGang CR (no recovery pins — the
    entire gang relocates). Shared by the drain controller's trial
    pre-placement and the what-if engine's hypothetical re-pend of a
    drained node's gangs, so the two judge relocation identically."""
    from grove_tpu.api.types import SPREAD_SCHEDULE_ANYWAY

    groups = []
    for group in gang.spec.pod_groups:
        demand: Dict[str, float] = {}
        for ref in group.pod_references:
            pod = store.get("Pod", ref.namespace, ref.name, readonly=True)
            if pod is not None:
                demand = pod.spec.total_requests()
                break
        groups.append(
            {
                "name": group.name,
                "demand": demand,
                "count": len(group.pod_references),
                "min_count": group.min_replicas,
                "partial": False,
                "required_key": (
                    group.topology_constraint.pack_constraint.required
                    if group.topology_constraint is not None
                    and group.topology_constraint.pack_constraint is not None
                    else None
                ),
                "pinned_node": None,
            }
        )
    tc = gang.spec.topology_constraint
    required = preferred = spread_key = None
    spread_min, spread_required = 2, False
    if tc is not None and tc.pack_constraint is not None:
        required = tc.pack_constraint.required
        preferred = tc.pack_constraint.preferred
    if tc is not None and tc.spread_constraint is not None:
        sc = tc.spread_constraint
        spread_key = sc.topology_key
        spread_min = sc.min_domains
        spread_required = sc.when_unsatisfiable != SPREAD_SCHEDULE_ANYWAY
    ns = gang.metadata.namespace
    return {
        "name": f"{ns}/{gang.metadata.name}",
        "gang_name": gang.metadata.name,
        "namespace": ns,
        "groups": groups,
        "required_key": required,
        "preferred_key": preferred,
        "spread_key": spread_key,
        "spread_min_domains": spread_min,
        "spread_required": spread_required,
        "spread_survivor_nodes": [],
        "gang_pinned_node": None,
        "priority": scheduler.priority_map.get(
            gang.spec.priority_class_name, 0
        ),
        "queue": gang.metadata.labels.get(namegen.LABEL_QUEUE)
        or scheduler.quota.default_queue,
    }


# -- capacity & fragmentation ------------------------------------------------


def spec_floor_demand(spec: dict) -> Dict[str, float]:
    """Aggregate floor demand (per-pod demand × ``min_count``, summed over
    groups) in ORIGINAL units — what must fit for the gang to admit."""
    out: Dict[str, float] = {}
    for grp in spec["groups"]:
        for r, q in grp["demand"].items():
            out[r] = out.get(r, 0.0) + q * grp["min_count"]
    return out


def capacity_report(
    scheduler,
    nodes: Optional[List] = None,
    free: Optional[Dict[str, Dict[str, float]]] = None,
    max_domain_rows: int = 64,
) -> dict:
    """Per-topology-level capacity introspection behind
    ``GET /debug/capacity`` / ``cli capacity``: domain counts, per-domain
    free vectors (super-domain level always itemized; other levels only
    up to ``max_domain_rows`` domains), the per-level fragmentation
    statistic, and the largest single-domain free vector. Reuses the
    solver's own topology sort and contiguous-slab boundaries
    (``encode_nodes``/``domain_boundaries``), so the domains reported ARE
    the slabs the kernel and the partitioned frontier pack into."""
    cluster = scheduler.cluster
    total_nodes = len(cluster.nodes)
    if nodes is None:
        nodes = [n for n in cluster.nodes if n.schedulable]
    if free is None:
        free = cluster.node_free_all(nodes)
    level_specs = scheduler.topology.spec.levels
    if not nodes:
        return {
            "nodes": 0,
            "totalNodes": total_nodes,
            "resources": [],
            "totalFree": {},
            "superDomainLevel": None,
            "levels": [],
        }
    capacity, topo, node_names, resource_names, level_keys = encode_nodes(
        nodes, scheduler.topology, free
    )
    seg_starts, seg_ends = domain_boundaries(topo)
    node_by_name = {n.name: n for n in nodes}
    total_free = capacity.astype(np.float64).sum(axis=0)
    levels = []
    super_level = None
    for l, key in enumerate(level_keys):
        width = int(topo[:, l].max()) + 1
        if super_level is None and width >= 2:
            # the partitioned frontier's rule: broadest level with >= 2
            # domains (solver/frontier.py plan_for)
            super_level = key
        dom_free = np.zeros((width, len(resource_names)), dtype=np.float64)
        dom_nodes = []
        names = []
        for d in range(width):
            s, e = int(seg_starts[l, d]), int(seg_ends[l, d])
            dom_free[d] = capacity[s:e].astype(np.float64).sum(axis=0)
            dom_nodes.append(e - s)
            names.append(node_by_name[node_names[s]].labels.get(key, ""))
        frag = {}
        largest = {}
        for r, rname in enumerate(resource_names):
            tot = float(total_free[r])
            mx = float(dom_free[:, r].max())
            largest[rname] = round(mx, 6)
            frag[rname] = round(1.0 - mx / tot, 4) if tot > 0 else 0.0
        row = {
            "key": key,
            "domain": (
                level_specs[l].domain if l < len(level_specs) else key
            ),
            "domainCount": width,
            "fragmentation": frag,
            "largestDomainFree": largest,
        }
        if width <= max_domain_rows or key == super_level:
            row["domains"] = [
                {
                    "name": names[d],
                    "nodes": dom_nodes[d],
                    "free": {
                        rname: round(float(dom_free[d, r]), 6)
                        for r, rname in enumerate(resource_names)
                    },
                }
                for d in range(width)
            ]
        levels.append(row)
    return {
        "nodes": len(nodes),
        "totalNodes": total_nodes,
        "resources": resource_names,
        "totalFree": {
            rname: round(float(total_free[r]), 6)
            for r, rname in enumerate(resource_names)
        },
        "superDomainLevel": super_level,
        "levels": levels,
    }


def fragmentation_stats(report: dict) -> Dict[str, Dict[str, float]]:
    """level key -> resource -> fragmentation fraction, flattened from a
    :func:`capacity_report` (the bench "explain" block's shape)."""
    return {
        lvl["key"]: dict(lvl["fragmentation"]) for lvl in report["levels"]
    }


def federation_score_inputs(
    scheduler, floor: Dict[str, float]
) -> Dict[str, float]:
    """Per-cluster routing-score inputs for the federation tier
    (grove_tpu/federation/router.py): for the gang floor's BINDING
    resource (largest floor share of this cluster's total free),
    headroom = total free − floor, and the pack-into-largest
    fragmentation delta at the super-domain level — frag(l, r)
    recomputed after hypothetically landing the floor in the largest
    free domain (the solver's contiguous-pack heuristic). Read-only:
    one :func:`capacity_report`, no solve, no store touch — the router
    ranks candidate clusters on (frag_delta, −headroom, region) so
    spillover prefers the cluster it fragments least."""
    report = capacity_report(scheduler)
    total_free = report["totalFree"]
    binding, ratio = None, -1.0
    for r in sorted(floor):
        q = floor[r]
        if q <= 0:
            continue
        tot = total_free.get(r, 0.0)
        share = q / tot if tot > 0 else float("inf")
        if share > ratio:
            binding, ratio = r, share
    if binding is None:
        # zero-demand floor: every cluster scores identically
        return {
            "resource": None,
            "headroom": round(sum(total_free.values()), 6),
            "frag_before": 0.0,
            "frag_after": 0.0,
            "frag_delta": 0.0,
        }
    need = floor[binding]
    tot = total_free.get(binding, 0.0)
    frag_before = frag_after = 0.0
    super_key = report["superDomainLevel"]
    for lvl in report["levels"]:
        if lvl["key"] != super_key:
            continue
        rows = sorted(
            (d["free"].get(binding, 0.0) for d in lvl.get("domains", [])),
            reverse=True,
        )
        largest = rows[0] if rows else 0.0
        second = rows[1] if len(rows) > 1 else 0.0
        frag_before = 1.0 - largest / tot if tot > 0 else 0.0
        after_total = tot - need
        after_largest = max(largest - need, second)
        frag_after = (
            1.0 - after_largest / after_total if after_total > 0 else 0.0
        )
        break
    return {
        "resource": binding,
        "headroom": round(tot - need, 6),
        "frag_before": round(frag_before, 4),
        "frag_after": round(frag_after, 4),
        "frag_delta": round(frag_after - frag_before, 4),
    }


# -- rejection classification ------------------------------------------------


def classify_rejections(
    problem, result, specs: List[dict]
) -> Dict[int, Tuple[str, str]]:
    """(detail slug, one-line text) for every REJECTED gang of one solve,
    derived from the problem tensors the solve already holds (quantized
    units; texts cite original units from the specs). One numpy pass —
    cheap enough for the scheduler to stamp into every ``GangDeferred``
    event, and the same classification the explain funnel reports, so the
    event one-liner and the verdict can never disagree."""
    out: Dict[int, Tuple[str, str]] = {}
    if result is None or problem is None:
        return out
    n = problem.num_nodes
    cap = problem.capacity  # [N, R] quantized
    total_free_q = cap.astype(np.float64).sum(axis=0)
    for gi, spec in enumerate(specs):
        if bool(result.admitted[gi]):
            continue
        if n == 0:
            out[gi] = (DETAIL_NO_NODES, "no schedulable nodes")
            continue
        floor_q = (
            problem.demand[gi].astype(np.float64)
            * problem.min_count[gi][:, None]
        ).sum(axis=0)
        floor_orig = spec_floor_demand(spec)
        short = [
            problem.resource_names[r]
            for r in range(len(total_free_q))
            if floor_q[r] > total_free_q[r]
        ]
        if short:
            rname = short[0]
            out[gi] = (
                DETAIL_INSUFFICIENT_CAPACITY,
                f"cluster free {rname} cannot cover the gang floor"
                f" ({floor_orig.get(rname, 0.0):g} {rname} needed)",
            )
            continue
        rl = int(problem.req_level[gi])
        if rl >= 0:
            key = problem.level_keys[rl]
            width = int(problem.topo[:, rl].max()) + 1
            covered = False
            best_cover = 0.0
            for d in range(width):
                s = int(problem.seg_starts[rl, d])
                e = int(problem.seg_ends[rl, d])
                dom = cap[s:e].astype(np.float64).sum(axis=0)
                need = floor_q > 0
                if not need.any():
                    covered = True
                    break
                cover = float((dom[need] / floor_q[need]).min())
                best_cover = max(best_cover, cover)
                if cover >= 1.0:
                    covered = True
                    break
            if not covered:
                out[gi] = (
                    DETAIL_TOPOLOGY_FRAGMENTATION,
                    f"no single {key} domain covers the gang floor"
                    f" (best domain covers {best_cover:.0%}); free"
                    " capacity is fragmented across domains",
                )
                continue
        sl = int(problem.spread_level[gi])
        if sl >= 0 and bool(problem.spread_required[gi]):
            width = int(problem.topo[:, sl].max()) + 1
            if width < int(problem.spread_min[gi]):
                out[gi] = (
                    DETAIL_UNSATISFIABLE,
                    f"hard spread needs {int(problem.spread_min[gi])}"
                    f" {problem.level_keys[sl]} domains; the cluster has"
                    f" {width}",
                )
                continue
        out[gi] = (
            DETAIL_NODE_FRAGMENTATION,
            "aggregate capacity covers the floor, but no feasible"
            " packing exists on current per-node free capacity",
        )
    return out


def solve_view_safe(scheduler, nodes, free, specs):
    """:func:`solve_view` that degrades an unsatisfiable constraint
    DECLARATION (ConstraintError) to (None, None, error) instead of
    raising — a direct-wire gang with a broken constraint must explain as
    blocked, not 500 the endpoint. Returns (result, problem, error)."""
    try:
        result, problem = solve_view(scheduler, nodes, free, specs)
        return result, problem, None
    except ConstraintError as e:
        return None, None, str(e)
