"""JAX kernel wrapper: PackingProblem → PackingResult (device execution).

Compilation is AOT-cached per shape signature so `solve_seconds` measures
steady-state device execution only; compile time is recorded separately in
the `gang_solve_compile_seconds` metric (one entry per new size bucket).
"""

from __future__ import annotations

import time
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from grove_tpu.observability.metrics import METRICS
from grove_tpu.ops.packing import solve_packing
from grove_tpu.solver.types import PackingProblem, PackingResult

_compiled_cache: Dict[Tuple, object] = {}


def _get_compiled(args, with_alloc: bool):
    sig = tuple((a.shape, str(a.dtype)) for a in args) + (with_alloc,)
    compiled = _compiled_cache.get(sig)
    if compiled is None:
        t0 = time.perf_counter()
        compiled = solve_packing.lower(*args, with_alloc=with_alloc).compile()
        METRICS.observe("gang_solve_compile_seconds", time.perf_counter() - t0)
        _compiled_cache[sig] = compiled
    return compiled


def solve(problem: PackingProblem, with_alloc: bool = True) -> PackingResult:
    args = (
        jnp.asarray(problem.capacity),
        jnp.asarray(problem.topo),
        jnp.asarray(problem.demand),
        jnp.asarray(problem.count),
        jnp.asarray(problem.min_count),
        jnp.asarray(problem.req_level),
        jnp.asarray(problem.pref_level),
    )
    compiled = _get_compiled(args, with_alloc)
    t0 = time.perf_counter()
    out = compiled(*args)
    admitted = np.asarray(out["admitted"])  # device sync
    elapsed = time.perf_counter() - t0
    return PackingResult(
        admitted=admitted,
        placed=np.asarray(out["placed"]),
        score=np.asarray(out["score"]),
        chosen_level=np.asarray(out["chosen_level"]),
        alloc=None if out["alloc"] is None else np.asarray(out["alloc"]),
        free_after=np.asarray(out["free_after"]),
        solve_seconds=elapsed,
    )
