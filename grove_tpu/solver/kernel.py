"""JAX kernel wrapper: PackingProblem → PackingResult (device execution).

Compilation is AOT-cached per shape signature so `solve_seconds` measures
steady-state device execution only; compile time is recorded separately in
the `gang_solve_compile_seconds` metric (one entry per new size bucket).
"""

from __future__ import annotations

import os
import time
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from grove_tpu.observability.metrics import METRICS
from grove_tpu.observability.tracing import TRACER
from grove_tpu.ops.packing import (
    solve_packing,
    solve_wave_chunk,
    solve_waves_device,
)
from grove_tpu.solver.types import PackingProblem, PackingResult

_compiled_cache: Dict[Tuple, object] = {}
_disk_cache_enabled = False


def _maybe_enable_disk_cache() -> None:
    """Point JAX at the persistent executable cache LAZILY, right before the
    first compile in this process (no import-time side effects; honors
    GROVE_TPU_NO_COMPILE_CACHE at call time). The full-size wave program
    compiles in minutes; every later process (bench, CLI, tests, driver
    gates) loads the binary from disk instead. With the cache active,
    `gang_solve_compile_seconds` measures the disk load on a hit."""
    global _disk_cache_enabled
    if _disk_cache_enabled or os.environ.get("GROVE_TPU_NO_COMPILE_CACHE"):
        return
    _disk_cache_enabled = True
    try:
        from grove_tpu.utils.platform import enable_compile_cache

        enable_compile_cache()
    except OSError:  # read-only cache dir: compile-per-process still works
        pass


def _get_compiled(
    args, with_alloc: bool, grouped: bool, pinned: bool, spread: bool,
    uniform: bool, level_widths: tuple = None,
):
    sig = tuple((a.shape, str(a.dtype)) for a in args) + (
        with_alloc,
        grouped,
        pinned,
        spread,
        uniform,
        level_widths,
    )
    compiled = _compiled_cache.get(sig)
    if compiled is None:
        _maybe_enable_disk_cache()
        t0 = time.perf_counter()
        with TRACER.span("solver.compile", kernel="solve_packing"):
            compiled = solve_packing.lower(
                *args, with_alloc=with_alloc, grouped=grouped, pinned=pinned,
                spread=spread, uniform=uniform, level_widths=level_widths,
            ).compile()
        METRICS.observe("gang_solve_compile_seconds", time.perf_counter() - t0)
        _compiled_cache[sig] = compiled
    return compiled


def _spread_arrays(problem: PackingProblem):
    """Spread tensors with sentinel defaults (problems built before the
    spread feature, or by hand in tests, may leave them None)."""
    g = problem.num_gangs
    sl = (
        problem.spread_level
        if problem.spread_level is not None
        else np.full((g,), -1, dtype=np.int32)
    )
    sm = (
        problem.spread_min
        if problem.spread_min is not None
        else np.zeros((g,), dtype=np.int32)
    )
    sr = (
        problem.spread_required
        if problem.spread_required is not None
        else np.zeros((g,), dtype=bool)
    )
    # zero-width = no seeds; the encoder already collapses all-zero seed
    # tensors to [G, 0], so no per-solve O(G*D) rescan here
    ss = (
        problem.spread_seed
        if problem.spread_seed is not None
        else np.zeros((g, 0), dtype=np.int32)
    )
    return sl, sm, sr, ss


def solve(problem: PackingProblem, with_alloc: bool = True) -> PackingResult:
    spread_level, spread_min, spread_required, spread_seed = _spread_arrays(
        problem
    )
    args = (
        jnp.asarray(problem.capacity),
        jnp.asarray(problem.topo),
        jnp.asarray(problem.seg_starts),
        jnp.asarray(problem.seg_ends),
        jnp.asarray(problem.demand),
        jnp.asarray(problem.count),
        jnp.asarray(problem.min_count),
        jnp.asarray(problem.req_level),
        jnp.asarray(problem.pref_level),
        jnp.asarray(problem.group_req),
        jnp.asarray(problem.group_pin),
        jnp.asarray(problem.gang_pin),
        jnp.asarray(spread_level),
        jnp.asarray(spread_min),
        jnp.asarray(spread_required),
        jnp.asarray(spread_seed),
    )
    grouped = bool((problem.group_req >= 0).any())
    pinned = bool((problem.gang_pin >= 0).any())
    spread = bool((spread_level >= 0).any())
    uniform = bool((problem.min_count == problem.count).all())
    compiled = _get_compiled(
        args, with_alloc, grouped, pinned, spread, uniform,
        level_widths_of(problem),
    )
    t0 = time.perf_counter()
    with TRACER.span(
        "solver.execute", kernel="solve_packing", gangs=problem.num_gangs
    ):
        out = compiled(*args)
        admitted = np.asarray(out["admitted"])  # device sync
    elapsed = time.perf_counter() - t0
    return PackingResult(
        admitted=admitted,
        placed=np.asarray(out["placed"]),
        score=np.asarray(out["score"]),
        chosen_level=np.asarray(out["chosen_level"]),
        alloc=None if out["alloc"] is None else np.asarray(out["alloc"]),
        free_after=np.asarray(out["free_after"]),
        solve_seconds=elapsed,
    )


def dedup_demand(demand: np.ndarray, count: np.ndarray, chunk_size: int):
    """Encode-time (demand, count)-pair dedup for the wave solvers.

    Template-stamped gang populations repeat identical (demand row, count)
    pairs (the 10k-gang stress mix has ~30 unique pairs across 30k rows):
    the wave kernel computes the candidate scan's capped-fit prefix sums
    once per UNIQUE pair per chunk and turns each gang's level loop into
    boundary gathers of the SAME integer values — bit-exact, no semantics
    change (packing.wave_chunk_core). Returns
    `(pair_demand [U,R], pair_count [U], pair_idx [G,P])` with row 0
    reserved all-zero (gangs masked out by the pending filter redirect
    there on device), or `(None, None, None)` when dedup cannot pay: the
    shared table is recomputed per chunk (capacity changes), so it only
    wins when U is well below the chunk's own C*P row count.
    """
    g, p, r = demand.shape
    key = np.concatenate(
        [
            np.ascontiguousarray(demand.reshape(g * p, r)),
            count.reshape(g * p, 1).astype(demand.dtype),
        ],
        axis=1,
    )
    uniq, inv = np.unique(key, axis=0, return_inverse=True)
    if (uniq[0] != 0).any():
        # demands/counts are non-negative, so an all-zero row sorts first
        # when present; otherwise reserve index 0 explicitly
        uniq = np.vstack([np.zeros((1, r + 1), dtype=uniq.dtype), uniq])
        inv = inv + 1
    if uniq.shape[0] * 2 > chunk_size * p:
        return None, None, None
    return (
        uniq[:, :r].astype(demand.dtype, copy=False),
        uniq[:, r].astype(np.int32),
        inv.reshape(g, p).astype(np.int32),
    )


def dedup_extra_args(
    demand: np.ndarray, count: np.ndarray, n_chunks: int, pinned: bool,
    place=None,
) -> dict:
    """The ONE home for the dedup guard + decline heuristic + packaging:
    kwargs for the wave solvers' `pair_*` params ({} when dedup is off).
    Shared by the stats path, the binding path, and the node-sharded
    multi-chip path so the three can never diverge. `pinned` problems skip
    dedup (per-gang capacity views break the shared-snapshot premise);
    `place` overrides device placement (the sharded path replicates)."""
    if pinned:
        return {}
    pdem, pcnt, pidx = dedup_demand(
        demand, count, demand.shape[0] // n_chunks
    )
    if pdem is None:
        return {}
    place = place or jnp.asarray
    return {
        "pair_demand": place(pdem),
        "pair_count": place(pcnt),
        "pair_idx": place(pidx),
    }


def solve_waves(
    problem: PackingProblem,
    chunk_size: int = 32,
    max_waves: int = 16,
    with_alloc: bool = True,
) -> PackingResult:
    """Wave-parallel solve WITH per-pod allocations (the binding path).

    Same algorithm as the device-resident stats solver (single-fill parallel
    decisions, strided domain spread, prefix-acceptance commit, narrow-cap
    retry walk), driven chunk-by-chunk from the host so allocations stream
    out per chunk. Gangs still pending when the wave budget ends simply stay
    pending — in the control loop they are re-solved on the next scheduling
    round (no exact tail here; that kernel's compile cost is only paid on
    the stats/bench path where alloc isn't materialized).
    """
    g = problem.num_gangs
    chunk_size = min(chunk_size, max(g, 1))
    n_chunks = max(1, (g + chunk_size - 1) // chunk_size)
    g_pad = n_chunks * chunk_size

    def pad(a, value=0):
        if a.shape[0] == g_pad:
            return a
        width = [(0, g_pad - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, width, constant_values=value)

    spread_level_a, spread_min_a, spread_required_a, spread_seed_a = (
        _spread_arrays(problem)
    )
    demand = pad(problem.demand)
    count = pad(problem.count)
    min_count = pad(problem.min_count)
    req_level = pad(problem.req_level, -1)
    pref_level = pad(problem.pref_level, -1)
    group_req = pad(problem.group_req, -1)
    group_pin = pad(problem.group_pin, -1)
    gang_pin = pad(problem.gang_pin, -1)
    spread_level = pad(spread_level_a, -1)
    spread_min = pad(spread_min_a)
    spread_required = pad(spread_required_a)
    spread_seed = pad(spread_seed_a)

    _maybe_enable_disk_cache()  # solve_wave_chunk compiles via plain jit
    free = jnp.asarray(problem.capacity)
    topo = jnp.asarray(problem.topo)
    seg_starts = jnp.asarray(problem.seg_starts)
    seg_ends = jnp.asarray(problem.seg_ends)
    n_levels = problem.num_levels
    pending = np.ones((g_pad,), dtype=bool)
    pending[g:] = False
    narrow_cap = np.full((g_pad,), n_levels - 1, dtype=np.int32)

    admitted = np.zeros((g_pad,), dtype=bool)
    placed = np.zeros_like(count)
    score = np.zeros((g_pad,), dtype=np.float32)
    chosen_level = np.full((g_pad,), -1, dtype=np.int32)
    alloc = (
        np.zeros((g_pad, problem.max_groups, problem.num_nodes), dtype=np.int32)
        if with_alloc
        else None
    )

    grouped = bool((problem.group_req >= 0).any())
    pinned = bool((problem.gang_pin >= 0).any())
    spread = bool((spread_level >= 0).any())
    # padded gangs have min_count == count == 0, preserving uniformity
    uniform = bool((problem.min_count == problem.count).all())
    level_widths = level_widths_of(problem)
    dedup_extra = dedup_extra_args(demand, count, n_chunks, pinned)
    pidx_chunks = None
    if dedup_extra:
        pidx_full = dedup_extra.pop("pair_idx")
        pidx_chunks = [
            pidx_full[c * chunk_size : (c + 1) * chunk_size]
            for c in range(n_chunks)
        ]
    # immutable chunk tensors go to the device ONCE (only mask/cap/seeds
    # change between waves; re-uploading per wave would pay the remote-link
    # latency this path exists to avoid)
    chunk_const = [
        tuple(
            jnp.asarray(a[c * chunk_size : (c + 1) * chunk_size])
            for a in (demand, count, min_count, req_level, pref_level)
        )
        + tuple(
            jnp.asarray(a[c * chunk_size : (c + 1) * chunk_size])
            for a in (
                group_req, group_pin, gang_pin,
                spread_level, spread_min, spread_required, spread_seed,
            )
        )
        for c in range(n_chunks)
    ]

    t0 = time.perf_counter()
    waves_used = 0
    for wave in range(max_waves):
        if not pending.any():
            break
        # per-wave span (single enabled check per wave; chunk execs nest
        # inside by time containment on this thread)
        wave_span = (
            TRACER.span(
                "solver.wave", wave=wave, pending=int(pending.sum())
            )
            if TRACER.enabled
            else None
        )
        progress = False
        waves_used += 1
        seeds = np.arange(g_pad, dtype=np.int32) + np.int32(wave * 7919)
        try:
            for c in range(n_chunks):
                sl = slice(c * chunk_size, (c + 1) * chunk_size)
                mask = pending[sl]
                if not mask.any():
                    continue
                (
                    dem_c, cnt_c, mn_c, rq_c, pf_c, grq_c, gpin_c, gangpin_c,
                    slvl_c, smin_c, sreq_c, sseed_c,
                ) = chunk_const[c]
                out = solve_wave_chunk(
                    free,
                    topo,
                    seg_starts,
                    seg_ends,
                    dem_c,
                    cnt_c,
                    mn_c,
                    rq_c,
                    pf_c,
                    jnp.asarray(mask),
                    jnp.asarray(narrow_cap[sl]),
                    jnp.asarray(seeds[sl]),
                    group_req=grq_c,
                    group_pin=gpin_c,
                    gang_pin=gangpin_c,
                    spread_level=slvl_c,
                    spread_min=smin_c,
                    spread_required=sreq_c,
                    spread_seed=sseed_c,
                    pair_demand=dedup_extra.get("pair_demand"),
                    pair_count=dedup_extra.get("pair_count"),
                    pair_idx=None if pidx_chunks is None else pidx_chunks[c],
                    grouped=grouped,
                    pinned=pinned,
                    spread=spread,
                    uniform=uniform,
                    level_widths=level_widths,
                )
                committed = np.asarray(out["admitted"])
                retry = np.asarray(out["retry"])
                free = out["free_after"]
                admitted[sl] |= committed
                placed[sl] = np.where(
                    committed[:, None], out["placed"], placed[sl]
                )
                score[sl] = np.where(committed, out["score"], score[sl])
                chosen_level[sl] = np.where(
                    committed, out["chosen_level"], chosen_level[sl]
                )
                narrow_cap[sl] = np.asarray(out["new_cap"])
                if with_alloc:
                    alloc[sl] = np.where(
                        committed[:, None, None],
                        np.asarray(out["alloc"]),
                        alloc[sl],
                    )
                pending[sl] = mask & retry
                # retry counts as progress: the narrow-cap fallback walk
                # admits gangs in LATER waves even when this one committed
                # nothing (device-loop parity)
                progress |= committed.any() or retry.any()
        finally:
            # end even on a backend error: a leaked span would mis-parent
            # every later span on this thread
            if wave_span is not None:
                wave_span.set("admitted", int(admitted.sum()))
                wave_span.end()
        if not progress:
            break
    elapsed = time.perf_counter() - t0
    METRICS.set("gang_solve_waves", waves_used)

    return PackingResult(
        admitted=admitted[:g],
        placed=placed[:g],
        score=score[:g],
        chosen_level=chosen_level[:g],
        alloc=None if alloc is None else alloc[:g],
        free_after=np.asarray(free),
        solve_seconds=elapsed,
    )


def solve_waves_stacked(
    stack: Dict[str, np.ndarray],
    chunk_size: int = 32,
    max_waves: int = 16,
    device=None,
) -> Dict[str, np.ndarray]:
    """Wave-parallel solve of a STACK of same-shape subproblems — the
    partitioned frontier's batch execution (solver/frontier.py).

    ``stack`` holds the per-lane problem tensors with a leading batch axis
    (``capacity [B,N,R]``, ``topo [B,N,L]``, ``seg_starts``/``seg_ends
    [B,L,D]``, gang tensors ``[B,G,...]``). The host loop reproduces
    :func:`solve_waves` EXACTLY per lane — same chunk clamp and padding,
    same per-wave seeds (lane-local ``arange + wave*7919``), same commit
    semantics — but every (wave, chunk) step is ONE
    ``solve_wave_chunk_stack`` dispatch covering all B lanes, so B small
    solves cost ~one solve's dispatch count. Returns per-lane result
    arrays (``admitted [B,G]``, ``placed``, ``score``, ``chosen_level``,
    ``alloc [B,G,P,N]``) plus ``dispatches`` and ``solve_seconds``.

    Bit-identity per lane vs a solo ``solve_waves`` run on the same
    subproblem tensors is the frontier selfcheck's contract
    (tests/test_frontier.py, ``make frontier-smoke``).

    ``device``: an explicit jax device to pin every operand (and so the
    jitted dispatch) to — the frontier's multi-device lane spread
    (docs/solver.md "Multi-device dispatch") runs one stack per device
    concurrently. None keeps default placement — byte-identical to the
    single-device path."""
    from grove_tpu.ops.packing import solve_wave_chunk_stack

    if device is None:
        _put = jnp.asarray
    else:
        import jax as _jax

        def _put(a, _dev=device):
            return _jax.device_put(a, _dev)

    demand = stack["demand"]
    b, g, p_max, _r = demand.shape
    n = stack["capacity"].shape[1]
    chunk_size = min(chunk_size, max(g, 1))
    n_chunks = max(1, (g + chunk_size - 1) // chunk_size)
    g_pad = n_chunks * chunk_size

    def pad(a, value=0):
        if a.shape[1] == g_pad:
            return a
        width = [(0, 0), (0, g_pad - a.shape[1])] + [(0, 0)] * (a.ndim - 2)
        return np.pad(a, width, constant_values=value)

    demand = pad(demand)
    count = pad(stack["count"])
    min_count = pad(stack["min_count"])
    req_level = pad(stack["req_level"], -1)
    pref_level = pad(stack["pref_level"], -1)
    group_req = pad(stack["group_req"], -1)
    group_pin = pad(stack["group_pin"], -1)
    gang_pin = pad(stack["gang_pin"], -1)
    spread_level = pad(stack["spread_level"], -1)
    spread_min = pad(stack["spread_min"])
    spread_required = pad(stack["spread_required"])
    spread_seed = pad(stack["spread_seed"])

    _maybe_enable_disk_cache()
    free = _put(stack["capacity"])
    topo = _put(stack["topo"])
    seg_starts = _put(stack["seg_starts"])
    seg_ends = _put(stack["seg_ends"])
    n_levels = stack["topo"].shape[2]
    pending = np.zeros((b, g_pad), dtype=bool)
    pending[:, :g] = True
    narrow_cap = np.full((b, g_pad), n_levels - 1, dtype=np.int32)

    admitted = np.zeros((b, g_pad), dtype=bool)
    placed = np.zeros_like(count)
    score = np.zeros((b, g_pad), dtype=np.float32)
    chosen_level = np.full((b, g_pad), -1, dtype=np.int32)
    alloc = np.zeros((b, g_pad, p_max, n), dtype=np.int32)

    grouped = bool((group_req >= 0).any())
    pinned = bool((gang_pin >= 0).any())
    spread = bool((spread_level >= 0).any())
    # the AND over lanes, not the OR: `uniform` asserts min == count for
    # every gang (padded gangs are 0 == 0, preserving it)
    uniform = bool((min_count == count).all())

    chunk_const = [
        tuple(
            _put(a[:, c * chunk_size : (c + 1) * chunk_size])
            for a in (
                demand, count, min_count, req_level, pref_level,
                group_req, group_pin, gang_pin,
                spread_level, spread_min, spread_required, spread_seed,
            )
        )
        for c in range(n_chunks)
    ]

    t0 = time.perf_counter()
    dispatches = 0
    for wave in range(max_waves):
        if not pending.any():
            break
        # lane-LOCAL seeds, exactly solve_waves' per-problem sequence: a
        # lane's gang keeps the seed it would have had solving alone
        seeds = np.broadcast_to(
            np.arange(g_pad, dtype=np.int32) + np.int32(wave * 7919),
            (b, g_pad),
        )
        for c in range(n_chunks):
            sl = slice(c * chunk_size, (c + 1) * chunk_size)
            mask = pending[:, sl]
            if not mask.any():
                continue
            (
                dem_c, cnt_c, mn_c, rq_c, pf_c, grq_c, gpin_c, gangpin_c,
                slvl_c, smin_c, sreq_c, sseed_c,
            ) = chunk_const[c]
            with TRACER.span(
                "solver.execute", kernel="solve_wave_chunk_stack", gangs=g
            ):
                out = solve_wave_chunk_stack(
                    free, topo, seg_starts, seg_ends,
                    dem_c, cnt_c, mn_c, rq_c, pf_c,
                    _put(mask),
                    _put(narrow_cap[:, sl]),
                    _put(np.ascontiguousarray(seeds[:, sl])),
                    grq_c, gpin_c, gangpin_c,
                    slvl_c, smin_c, sreq_c, sseed_c,
                    grouped=grouped, pinned=pinned, spread=spread,
                    uniform=uniform,
                )
            dispatches += 1
            (
                free, accept_d, retry_d, new_cap_d,
                placed_d, score_d, chosen_d, alloc_d,
            ) = out
            committed = np.asarray(accept_d)
            retry = np.asarray(retry_d)
            admitted[:, sl] |= committed
            placed[:, sl] = np.where(
                committed[:, :, None], np.asarray(placed_d), placed[:, sl]
            )
            score[:, sl] = np.where(
                committed, np.asarray(score_d), score[:, sl]
            )
            chosen_level[:, sl] = np.where(
                committed, np.asarray(chosen_d), chosen_level[:, sl]
            )
            narrow_cap[:, sl] = np.asarray(new_cap_d)
            alloc[:, sl] = np.where(
                committed[:, :, None, None],
                np.asarray(alloc_d),
                alloc[:, sl],
            )
            pending[:, sl] = mask & retry
    elapsed = time.perf_counter() - t0
    return {
        "admitted": admitted[:, :g],
        "placed": placed[:, :g],
        "score": score[:, :g],
        "chosen_level": chosen_level[:, :g],
        "alloc": alloc[:, :g],
        "free_after": np.asarray(free),
        "dispatches": dispatches,
        "solve_seconds": elapsed,
    }


def level_widths_of(problem: PackingProblem) -> tuple:
    """Per-level REAL domain counts (dense ids ⇒ max id + 1), the static
    `level_widths` for the wave solvers' ragged candidate scan. Derived
    from the topology only — stable for a given cluster, so repeat solves
    keep hitting one executable."""
    topo = np.asarray(problem.topo)
    if topo.size == 0:
        return tuple(1 for _ in range(topo.shape[1]))
    return tuple(int(topo[:, l].max()) + 1 for l in range(topo.shape[1]))


def pad_problem_for_waves(
    problem: PackingProblem, chunk_size: int
) -> Tuple[Tuple[np.ndarray, ...], int, bool, bool, bool]:
    """SINGLE home for the wave solver's input-prep contract: clamp the
    chunk size, pad the gang axis to a chunk multiple (sentinel -1 for the
    level/pin fields, 0 elsewhere), and decide the `grouped`/`pinned`/
    `spread`/`uniform` compile flags. Returns (args, n_chunks, grouped,
    pinned, spread, uniform) where args is the positional tuple of
    solve_waves_device.
    Shared by the stats path, the node-sharded multi-chip path, and the
    parity tests — a padding-contract change lands exactly once."""
    g = problem.num_gangs
    chunk_size = min(chunk_size, max(g, 1))
    n_chunks = max(1, (g + chunk_size - 1) // chunk_size)
    g_pad = n_chunks * chunk_size

    def pad(a, value=0):
        if a.shape[0] == g_pad:
            return a
        width = [(0, g_pad - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, width, constant_values=value)

    spread_level, spread_min, spread_required, spread_seed = _spread_arrays(
        problem
    )
    args = (
        problem.capacity,
        problem.topo,
        problem.seg_starts,
        problem.seg_ends,
        pad(problem.demand),
        pad(problem.count),
        pad(problem.min_count),
        pad(problem.req_level, -1),
        pad(problem.pref_level, -1),
        pad(problem.group_req, -1),
        pad(problem.group_pin, -1),
        pad(problem.gang_pin, -1),
        pad(spread_level, -1),
        pad(spread_min),
        pad(spread_required),
        pad(spread_seed),
    )
    grouped = bool((problem.group_req >= 0).any())
    pinned = bool((problem.gang_pin >= 0).any())
    spread = bool((spread_level >= 0).any())
    # all-or-nothing population (padded gangs are 0 == 0): half the fill
    # scans compile away, bit-exactly (ops.packing._fill_floors_first)
    uniform = bool((problem.min_count == problem.count).all())
    return args, n_chunks, grouped, pinned, spread, uniform


# The BASELINE bench configuration (bench.py runs solve_waves_stats with
# these defaults). Single source shared with the committed TPU lowering
# proof (scripts/export_tpu_lowering.py) and its drift test
# (tests/test_tpu_lowering.py) so a re-tune here forces the lowering
# artifacts to be regenerated instead of silently diverging from the
# program the bench actually times. Chunk 48: the sweep optimum kept
# sliding down as per-gang work shrank (128 pre-dedup → 64 post-dedup →
# 48 after the uniform shortcut + exact group padding; docs/benchmarks.md
# round-4 re-tune tables).
BENCH_CHUNK_SIZE = 48
BENCH_MAX_WAVES = 32


def solve_waves_stats(
    problem: PackingProblem,
    chunk_size: int = BENCH_CHUNK_SIZE,
    max_waves: int = BENCH_MAX_WAVES,
) -> PackingResult:
    """Device-resident wave solve (ops.packing.solve_waves_device): the whole
    multi-wave loop runs as one XLA program — the stress-bench path. Returns
    stats only (no per-pod alloc); use solve_waves/solve for binding."""
    g = problem.num_gangs
    raw_args, n_chunks, grouped, pinned, spread, uniform = (
        pad_problem_for_waves(problem, chunk_size)
    )
    args = tuple(jnp.asarray(a) for a in raw_args)
    # encode-time demand dedup (exact semantics; packing.wave_chunk_core)
    extra = dedup_extra_args(raw_args[4], raw_args[5], n_chunks, pinned)
    # ragged candidate scan: per-level REAL domain counts (static, derived
    # from the topology — stable for a given cluster, so no compile churn)
    level_widths = level_widths_of(problem)
    sig = tuple((a.shape, str(a.dtype)) for a in args) + (
        tuple(extra["pair_demand"].shape) if extra else None,
        n_chunks,
        max_waves,
        grouped,
        pinned,
        spread,
        uniform,
        level_widths,
    )  # lazy_rescue == uniform, so the sig needs no extra field
    compiled = _compiled_cache.get(sig)
    if compiled is None:
        _maybe_enable_disk_cache()
        t0 = time.perf_counter()
        with TRACER.span("solver.compile", kernel="solve_waves_device"):
            compiled = solve_waves_device.lower(
                *args,
                **extra,
                n_chunks=n_chunks,
                max_waves=max_waves,
                grouped=grouped,
                pinned=pinned,
                spread=spread,
                uniform=uniform,
                # all-or-nothing populations defer cluster rescues to the
                # next compacted wave instead of paying an in-wave second
                # fill
                lazy_rescue=uniform,
                level_widths=level_widths,
            ).compile()
        METRICS.observe("gang_solve_compile_seconds", time.perf_counter() - t0)
        _compiled_cache[sig] = compiled
    t0 = time.perf_counter()
    with TRACER.span(
        "solver.execute", kernel="solve_waves_device", gangs=g
    ):
        out = compiled(*args, **extra)
        admitted = np.array(out["admitted"])[:g]
    elapsed = time.perf_counter() - t0  # wave execution (sync on admitted)
    placed = np.array(out["placed"])[:g]
    score = np.array(out["score"])[:g]
    chosen_level = np.array(out["chosen_level"])[:g]
    free_after = np.asarray(out["free_after"])
    pending = np.asarray(out["pending"])[:g]

    # Hybrid tail: under extreme contention a handful of gangs can keep
    # colliding past the wave budget — finish them with the exact sequential
    # kernel against the remaining capacity (small G → cheap), guaranteeing
    # convergence to near-greedy admissions.
    n_pending = int(pending.sum())
    if n_pending:
        idx = np.flatnonzero(pending)
        # pad the tail to a pow2 bucket (min 32) so repeat solves reuse one
        # executable across varying tail sizes
        t_pad = 32
        while t_pad < n_pending:
            t_pad *= 2

        def tpad(a, value=0):
            width = [(0, t_pad - n_pending)] + [(0, 0)] * (a.ndim - 1)
            return np.pad(a[idx], width, constant_values=value)

        sl_a, sm_a, sr_a, ss_a = _spread_arrays(problem)
        tail = PackingProblem(
            capacity=free_after,
            topo=problem.topo,
            demand=tpad(problem.demand),
            count=tpad(problem.count),
            min_count=tpad(problem.min_count),
            req_level=tpad(problem.req_level, -1),
            pref_level=tpad(problem.pref_level, -1),
            group_req=tpad(problem.group_req, -1),
            group_pin=tpad(problem.group_pin, -1),
            gang_pin=tpad(problem.gang_pin, -1),
            spread_level=tpad(sl_a, -1),
            spread_min=tpad(sm_a),
            spread_required=tpad(sr_a),
            spread_seed=tpad(ss_a),
            priority=tpad(problem.priority),
            seg_starts=problem.seg_starts,
            seg_ends=problem.seg_ends,
        )
        tail_res = solve(tail, with_alloc=False)
        # solve() excludes its own compile time; add execution only so
        # solve_seconds keeps the steady-state-execution contract
        elapsed += tail_res.solve_seconds
        tail_admit = tail_res.admitted[:n_pending]
        admitted[idx] = tail_admit
        placed[idx] = np.where(
            tail_admit[:, None], tail_res.placed[:n_pending], placed[idx]
        )
        score[idx] = np.where(tail_admit, tail_res.score[:n_pending], score[idx])
        chosen_level[idx] = np.where(
            tail_admit, tail_res.chosen_level[:n_pending], chosen_level[idx]
        )
        free_after = tail_res.free_after
        METRICS.set("gang_solve_tail", n_pending)
    METRICS.set("gang_solve_waves", int(np.asarray(out["waves"])))
    return PackingResult(
        admitted=admitted,
        placed=placed,
        score=score,
        chosen_level=chosen_level,
        alloc=None,
        free_after=free_after,
        solve_seconds=elapsed,
    )
