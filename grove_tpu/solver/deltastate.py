"""Incremental delta-solve state: resident cluster tensors folded from
watch deltas instead of re-derived per tick.

Every ``GangScheduler._schedule_pending`` tick used to re-derive the whole
solver input from scratch: one pass over ALL bindings (``node_free_all``,
O(bound pods) store reads), a full topology re-sort/re-id of every node
(``encode_nodes``), and a per-gang re-read of every pending gang's CR,
pods, and scheduled counts (``_encode_pending``). At production churn the
per-tick delta is tiny — a few gangs arrive, a few pods bind, a node flaps
— which is exactly the regime this module exploits (the scheduler analogue
of ``runtime/aggregate.py`` and the quota accountant, folded from the same
``subscribe_system`` watch fanout).

State maintained (all dirty-masked):

- **Binding mirror** — per-node insertion-ordered pod sets mirroring
  ``SimCluster.bindings``. The per-node order equals the restriction of
  the global binding order, so a dirty node's usage recount sums requests
  in EXACTLY the order ``node_free_all`` would — float accumulation and
  the float32 rows are bit-identical, not merely close.
- **Free-capacity matrix** ``[N, R]`` — rows recomputed only for dirty
  nodes; clean rows carried across ticks. The encode-side analogue of the
  "warm-start from the previous tick's surviving placements": every
  surviving placement is already debited, nothing is recounted.
- **Node encoding** (``encode.NodeEncoding``) — topology sort, dense ids,
  domain boundaries, reusable static tensors. Invalidated only by a
  node-signature change (set/labels/capacity/schedulability): a topology
  change falls back to a FULL re-encode, counted in
  ``delta_full_fallbacks_total``.
- **Gang-spec cache** — encoded specs reused for gangs with no relevant
  pod/PodGang delta since they were built (``delta_warm_start_hits_total``).

Fallback ladder: topology change, resource-name-space change, or drift
detection (periodic exact recount audit) ⇒ full re-encode through the very
same assembly code — so the delta and full paths can never diverge
semantically, and the A/B equivalence (delta problem bit-identical to a
from-scratch ``build_problem``; admissions bit-identical) is pinned by
tests/test_deltastate.py, ``make delta-smoke``, and the bench ``"delta"``
block.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from grove_tpu.api import names as namegen
from grove_tpu.api.pod import is_schedule_gated, is_scheduled, is_terminating
from grove_tpu.observability.metrics import METRICS
from grove_tpu.runtime.store import Store
from grove_tpu.solver.encode import NodeEncoding, build_problem_cached


_PROBLEM_TENSORS = (
    "capacity", "topo", "seg_starts", "seg_ends", "demand", "count",
    "min_count", "req_level", "pref_level", "priority", "group_req",
    "group_pin", "gang_pin", "spread_level", "spread_min",
    "spread_required", "spread_seed",
)
_PROBLEM_NAMES = (
    "node_names", "gang_names", "group_names", "resource_names",
    "level_keys",
)


def problems_identical(a, b) -> Optional[str]:
    """BIT-equality check of two PackingProblems (every tensor, every name
    list). Returns None when identical, else the first mismatching field —
    the delta-solve A/B contract (GangScheduler._delta_ab_check, tests,
    `make delta-smoke`)."""
    for field in _PROBLEM_TENSORS:
        x, y = getattr(a, field), getattr(b, field)
        if (x is None) != (y is None):
            return field
        if x is not None and (
            x.shape != y.shape
            or x.dtype != y.dtype
            or not np.array_equal(x, y)
        ):
            return field
    for field in _PROBLEM_NAMES:
        if getattr(a, field) != getattr(b, field):
            return field
    return None


def _binding_feature(pod) -> Optional[str]:
    """The node this pod charges capacity to, or None while it charges
    nothing — mirrors the ``bindings`` + ``_used_by_node`` contract
    (bound, not terminating)."""
    if pod is None or pod.metadata.deletion_timestamp is not None:
        return None
    if not is_scheduled(pod):
        return None
    return pod.status.node_name or None


def _gang_feature(pod) -> Optional[tuple]:
    """The pod's contribution to its gang's encoded spec: existence,
    pending-set membership inputs (gates / scheduled / terminating), and
    its binding. Readiness is deliberately absent — a Ready flip changes
    neither the pending set (ready pods are bound) nor scheduled counts,
    so it must not dirty the gang (the steady-state common case)."""
    if pod is None or pod.metadata.deletion_timestamp is not None:
        return None
    return (
        is_scheduled(pod),
        is_schedule_gated(pod),
        pod.status.node_name,
    )


class DeltaSolveState:
    """Dirty-masked incremental encode state for one GangScheduler.

    Attach via ``GangScheduler.enable_delta()`` (in-memory :class:`Store`
    only — its watch events fire synchronously at commit, so the fold is
    always exact; the HTTP client's watch threads lag live reads and keep
    the full path).
    """

    def __init__(
        self,
        store: Store,
        cluster,
        topology,
        drift_check_every: int = 64,
    ) -> None:
        self.store = store
        self.cluster = cluster
        self.topology = topology
        self.drift_check_every = drift_check_every
        # node-side state
        self._enc: Optional[NodeEncoding] = None
        # encodings retired by a signature change, keyed by
        # (node signature, resource names): a flap BACK to a previously
        # seen signature (cordon/uncordon, node rejoin) reuses the
        # retired encoding instead of re-sorting and re-deriving 5k
        # nodes — NodeEncoding is deterministic in (nodes, topology,
        # resource_names), so an equal key IS the identical encoding
        self._enc_cache: Dict[tuple, NodeEncoding] = {}
        self._node_sig: Optional[tuple] = None
        self._node_resources: frozenset = frozenset()
        self._free: Optional[np.ndarray] = None
        self._free_version = 0
        self._enc_epoch = 0
        # binding mirror: node -> {(ns, name): None} insertion-ordered
        self._node_pods: Dict[str, Dict[Tuple[str, str], None]] = {}
        self._pod_node: Dict[Tuple[str, str], str] = {}
        self._dirty_nodes: set = set()
        self._mirror_built = False
        # gang-spec cache: (ns, gang) -> {"spec", "pods", "names", "rev"}
        self._specs: Dict[Tuple[str, str], dict] = {}
        self._dirty_gangs: set = set()
        self._spec_rev = 0
        # bookkeeping / observability
        self._ticks = 0
        self._bindings_epoch = getattr(cluster, "bindings_epoch", 0)
        self.warm_start_hits = 0  # specs served from cache (lifetime)
        self.solve_reuses = 0  # whole solves skipped (identical tick)
        self.full_fallbacks = 0
        self.drift_detected = 0
        self.last_reencoded = 0  # specs rebuilt THIS tick
        self.last_reused = 0  # specs served from cache THIS tick
        # sharded stores deliver per shard (docs/control-plane.md): the
        # fold is per-pod/per-gang and an object's events never straddle
        # shards, so per-shard delivery preserves every order the fold
        # depends on (storm-equivalence pinned in tests/test_shards.py)
        if getattr(store, "num_shards", 1) > 1:
            store.subscribe_system_per_shard(self._on_event)
        else:
            store.subscribe_system(self._on_event)

    # -- watch-delta fold ------------------------------------------------

    def _on_event(self, ev) -> None:
        if ev.kind == "PodGang":
            if (
                ev.type == "Updated"
                and ev.old is not None
                and ev.old.spec is ev.obj.spec
                and ev.old.metadata.labels == ev.obj.metadata.labels
            ):
                # STATUS-only write (copy-on-write commits share the spec
                # subtree structurally — the O(1) identity check the WAL
                # patch op uses): phase/condition upserts happen every
                # round at steady state and change no encode input, so
                # they must not cost every gang its warm start
                return
            key = (ev.obj.metadata.namespace, ev.obj.metadata.name)
            self._dirty_gangs.add(key)
            if ev.type == "Deleted":
                self._specs.pop(key, None)
            return
        if ev.kind != "Pod":
            return
        old = ev.old if ev.old is not None else (
            ev.obj if ev.type == "Deleted" else None
        )
        new = None if ev.type == "Deleted" else ev.obj
        key = (ev.obj.metadata.namespace, ev.obj.metadata.name)
        # usage fold: the MIRROR is the authority for where the pod was
        # charged (the event's old view says where the pod THOUGHT it was,
        # which disagrees once a pod turns terminating-then-deleted — two
        # events, one charge release). A pod charges capacity while bound
        # and not terminating; any transition in or out of that state, or
        # a node move, dirties the affected rows.
        if self._mirror_built:
            new_node = _binding_feature(new)
            mirrored = self._pod_node.get(key)
            if new_node != mirrored:
                if mirrored is not None:
                    pods = self._node_pods.get(mirrored)
                    if pods is not None:
                        pods.pop(key, None)
                    self._pod_node.pop(key, None)
                    self._dirty_nodes.add(mirrored)
                if new_node is not None:
                    self._node_pods.setdefault(new_node, {})[key] = None
                    self._pod_node[key] = new_node
                    self._dirty_nodes.add(new_node)
        # spec fold: dirty the gang when pending-set inputs changed
        if _gang_feature(old) != _gang_feature(new):
            for side in (old, new):
                if side is None:
                    continue
                gang = side.metadata.labels.get(namegen.LABEL_PODGANG)
                if gang:
                    self._dirty_gangs.add((side.metadata.namespace, gang))

    # -- node signature / topology-change detection ----------------------

    def _signature(self, nodes) -> Tuple[tuple, frozenset]:
        """Signature of the solve's node set: name, topology path, and
        capacity of every schedulable node (in the caller's order — the
        encoder re-sorts, so order changes are harmless but cheap to
        include). Any change is a TOPOLOGY change: the dense ids, domain
        slabs, and pin resolutions may all shift, so the delta state falls
        back to a full re-encode."""
        level_keys = [lvl.key for lvl in self.topology.spec.levels]
        sig = []
        rset = set()
        for n in nodes:
            caps = tuple(sorted(n.capacity.items()))
            rset.update(n.capacity)
            sig.append(
                (n.name, tuple(n.labels.get(k, "") for k in level_keys), caps)
            )
        return tuple(sig), frozenset(rset)

    # -- full resync ------------------------------------------------------

    def _resync_mirror(self) -> None:
        """Rebuild the binding mirror from ``cluster.bindings`` in its own
        (global insertion) order, so per-node restriction order matches the
        recount order ``node_free_all`` would use."""
        self._node_pods = {}
        self._pod_node = {}
        for key, node_name in self.cluster.bindings.items():
            self._node_pods.setdefault(node_name, {})[key] = None
            self._pod_node[key] = node_name
        self._mirror_built = True

    def invalidate(self, reason: str = "manual") -> None:
        """Registration API for out-of-band mutations (grovelint GL012):
        code that must touch cluster-tensor inputs outside the watched
        channels (store commits, node attributes seen by the signature)
        calls this so the next tick re-derives everything."""
        self._enc = None
        self._enc_cache.clear()
        self._node_sig = None
        self._free = None
        self._mirror_built = False
        self._specs.clear()
        self._dirty_gangs.clear()
        self._dirty_nodes.clear()
        if reason != "init":
            self.full_fallbacks += 1
            METRICS.inc("delta_full_fallbacks_total")

    def mark_node_dirty(self, node_name: str) -> None:
        """Registration API (GL012): a node's free capacity was changed
        outside the store-watched channels — recount its row next tick."""
        self._dirty_nodes.add(node_name)

    def mark_gang_dirty(self, namespace: str, gang_name: str) -> None:
        """Registration API (GL012): a gang's encode inputs were changed
        outside the watched channels — re-encode its spec next tick."""
        self._dirty_gangs.add((namespace, gang_name))

    # -- drift audit -------------------------------------------------------

    def check_drift(self, nodes) -> bool:
        """Exact audit: recount every node's free capacity from the live
        binding map and compare to the incrementally-maintained rows.
        O(bound pods) — run periodically (and per-tick under the runtime
        sanitizer), not per solve. Returns True when drift was found (the
        state then resyncs itself and counts a fallback)."""
        if self._enc is None or self._free is None:
            return False
        oracle = self.cluster.node_free_all(nodes)
        expect = np.zeros_like(self._free)
        for name, i in self._enc.node_index.items():
            caps = oracle.get(name, {})
            for r, rname in enumerate(self._enc.resource_names):
                expect[i, r] = caps.get(rname, 0.0)
        if np.array_equal(expect, self._free):
            return False
        self.drift_detected += 1
        METRICS.inc("delta_drift_detected_total")
        self.invalidate(reason="drift")
        return True

    # -- spec cache --------------------------------------------------------

    def cached_spec(
        self, namespace: str, gang_name: str, pods: List
    ) -> Optional[tuple]:
        """The cached (spec, gang_pods) for a clean gang whose pending pod
        set is unchanged; None forces a re-encode. ``pods`` is this tick's
        pending pod list for the gang (pre-grouping)."""
        key = (namespace, gang_name)
        if key in self._dirty_gangs:
            return None
        entry = self._specs.get(key)
        if entry is None:
            return None
        # SORTED name tuple: the encoded spec is canonical in the pod-name
        # set (group members are name-sorted), while the incoming list's
        # order follows working-set iteration — order changes must not
        # miss, content changes must
        names = tuple(sorted(p.metadata.name for p in pods))
        if entry["names"] != names:
            # pod-set change the dirty tracking missed (belt and braces —
            # re-encode rather than trust a stale spec)
            return None
        self.warm_start_hits += 1
        self.last_reused += 1
        METRICS.inc("delta_warm_start_hits_total")
        return entry["spec"], entry["pods"]

    def has_clean_spec(self, namespace: str, gang_name: str) -> bool:
        """Read-only peek at whether ``cached_spec`` COULD hit for this
        gang (clean + present; the pod-name check still runs at the real
        lookup). The scheduler's overlap pump uses it to skip speculating
        gangs the warm-start cache already covers — without this pure
        variant the speculation pass would perturb the warm-start hit
        accounting relative to the serial twin."""
        key = (namespace, gang_name)
        return key not in self._dirty_gangs and key in self._specs

    def store_spec(
        self,
        namespace: str,
        gang_name: str,
        pods: List,
        spec: dict,
        gang_pods: dict,
    ) -> None:
        key = (namespace, gang_name)
        self._spec_rev += 1
        self.last_reencoded += 1
        self._specs[key] = {
            "spec": spec,
            "pods": gang_pods,
            "names": tuple(sorted(p.metadata.name for p in pods)),
            "rev": self._spec_rev,
        }
        self._dirty_gangs.discard(key)

    def spec_rev(self, spec: dict) -> int:
        """Cache revision of an encoded spec (0 for uncached) — one
        component of the warm-start solve fingerprint."""
        entry = self._specs.get((spec["namespace"], spec["gang_name"]))
        if entry is not None and entry["spec"] is spec:
            return entry["rev"]
        return 0

    # -- per-tick refresh + encode ----------------------------------------

    def _recount_row(self, node, resource_names: List[str]) -> None:
        """Recompute one node's free row exactly as ``node_free_all`` would:
        accumulate a usage dict in binding order, subtract once per
        resource, then fill the float32 row."""
        used: Dict[str, float] = {}
        for key in self._node_pods.get(node.name, ()):  # insertion order
            pod = self.store.get("Pod", key[0], key[1], readonly=True)
            if pod is None or is_terminating(pod):
                continue
            for k, v in self.cluster.pod_requests(pod).items():
                used[k] = used.get(k, 0.0) + v
        free = dict(node.capacity)
        for k, v in used.items():
            free[k] = free.get(k, 0.0) - v
        i = self._enc.node_index[node.name]
        for r, rname in enumerate(resource_names):
            self._free[i, r] = free.get(rname, 0.0)

    def _fold_dirty(self, nodes) -> int:
        """Recount every dirty node's free row against the current encoding
        (O(dirty), not O(nodes)). Idle ticks fold eagerly via refresh so
        dirt never accumulates across quiet rounds; encode folds again for
        any rows dirtied mid-tick (e.g. gang-teardown pod deletes inside
        the pending scan)."""
        if self._enc is None or self._free is None:
            return 0
        dirty = self._dirty_nodes & set(self._enc.node_index)
        if dirty:
            if len(dirty) * 4 >= len(self._enc.node_index):
                # full-re-derive regime (fallback tick dirtied every row):
                # one global usage pass beats per-node store walks. Same
                # bits — node_free_all accumulates per node in global
                # binding order, the restriction of which IS the mirror's
                # per-node order (see _resync_mirror)
                free_all = self.cluster.node_free_all(nodes)
                rn = self._enc.resource_names
                for node in nodes:
                    if node.name not in dirty:
                        continue
                    caps = free_all[node.name]
                    i = self._enc.node_index[node.name]
                    for r, rname in enumerate(rn):
                        self._free[i, r] = caps.get(rname, 0.0)
            else:
                by_name = {n.name: n for n in nodes}
                for name in dirty:
                    node = by_name.get(name)
                    if node is not None:
                        self._recount_row(node, self._enc.resource_names)
            self._free_version += 1
        self._dirty_nodes.clear()
        return len(dirty)

    def refresh(self, nodes) -> None:
        """Per-tick maintenance BEFORE an encode: detect topology change,
        run the periodic drift audit, lazily (re)build the mirror, and fold
        any dirty free-capacity rows."""
        from grove_tpu.analysis.sanitize import enabled as sanitize_enabled

        self._ticks += 1
        self.last_reencoded = 0
        self.last_reused = 0
        epoch = getattr(self.cluster, "bindings_epoch", 0)
        if epoch != self._bindings_epoch:
            # rebuild_bindings rewrote the binding map out-of-band
            # (failover/cold restart) — the mirror's fold no longer covers
            # it; resync rather than trust pre-rewrite state
            self._bindings_epoch = epoch
            self.invalidate(reason="bindings-rebuilt")
        sig, rset = self._signature(nodes)
        if sig != self._node_sig:
            had = self._enc is not None
            self._enc = None
            self._free = None
            self._specs.clear()  # pins/survivor seeds resolve against the
            self._dirty_gangs.clear()  # new node set — rebuild every spec
            self._node_sig = sig
            self._node_resources = rset
            if had:
                self.full_fallbacks += 1
                METRICS.inc("delta_full_fallbacks_total")
        if not self._mirror_built:
            self._resync_mirror()
            return
        # fold BEFORE the audit: rows dirtied by the previous tick's binds
        # are folded lazily here, so auditing first would read legitimately
        # pending dirt as drift and pay a spurious full re-derive (observed
        # at bench scale: every audit after a bind tick false-positived)
        self._fold_dirty(nodes)
        every = 1 if sanitize_enabled() else self.drift_check_every
        if every and self._ticks % every == 0:
            if self.check_drift(nodes):
                self._resync_mirror()
                # the drift invalidate nulled the signature, but the
                # TOPOLOGY did not change — restore it so the next tick
                # doesn't misread the unchanged node set as a second
                # fallback, and so the rebuilt encoding caches under its
                # true signature (drift is a usage-rows problem; the
                # encoding is usage-independent)
                self._node_sig = sig
                self._node_resources = rset

    def encode(
        self,
        nodes,
        gang_specs: List[dict],
        pad_gangs: Optional[int] = None,
        pad_groups: Optional[int] = None,
    ):
        """Build this tick's PackingProblem incrementally. Returns
        (problem, fingerprint) where the fingerprint identifies the exact
        solver input — two ticks with equal fingerprints are guaranteed to
        produce identical solver results (the warm-start reuse key)."""
        resource_names = sorted(
            self._node_resources.union(
                *(
                    grp["demand"].keys()
                    for spec in gang_specs
                    for grp in spec["groups"]
                )
            )
            if gang_specs
            else self._node_resources
        )
        if self._enc is None or self._enc.resource_names != resource_names:
            # first build, topology fallback, or a new resource axis: the
            # matrix width changes, so every row re-derives. A signature
            # seen before (flap-back) reuses the retired encoding — only
            # the free matrix re-derives. A None signature (encode before
            # the next refresh re-signs, e.g. right after a manual
            # invalidate) must not key the cache: it would alias distinct
            # node sets
            key = (self._node_sig, tuple(resource_names))
            enc = (
                self._enc_cache.get(key)
                if self._node_sig is not None
                else None
            )
            if enc is None:
                enc = NodeEncoding(nodes, self.topology, resource_names)
                if self._node_sig is not None:
                    self._enc_cache[key] = enc
                    while len(self._enc_cache) > 4:  # oldest-first bound
                        self._enc_cache.pop(next(iter(self._enc_cache)))
            self._enc = enc
            self._free = self._enc.base_capacity.copy()
            self._dirty_nodes = {n.name for n in nodes}
            self._enc_epoch += 1
        dirty = self._fold_dirty(nodes)
        METRICS.set("delta_dirty_nodes", dirty)
        METRICS.set("delta_dirty_gangs", len(self._dirty_gangs))
        problem = build_problem_cached(
            self._enc, self._free, gang_specs, pad_gangs, pad_groups
        )
        fingerprint = (
            self._enc_epoch,
            self._free_version,
            tuple(
                (spec["name"], self.spec_rev(spec)) for spec in gang_specs
            ),
            pad_gangs,
            pad_groups,
        )
        return problem, fingerprint

    def state_fingerprint(self) -> tuple:
        """Deterministic digest of EVERY piece of mutable delta state —
        the read-only pin the admission explain engine is tested against
        (docs/observability.md "Admission explain"): an explain/what-if
        burst must leave this byte-identical, or the "strictly read-only"
        contract is a lie. Pure read; no fold, no audit."""
        import zlib

        free_crc = (
            None
            if self._free is None
            else zlib.crc32(self._free.tobytes())
        )
        return (
            self._enc_epoch,
            self._free_version,
            free_crc,
            self._spec_rev,
            tuple(sorted(self._specs)),
            tuple(sorted(self._dirty_nodes)),
            tuple(sorted(self._dirty_gangs)),
            self._mirror_built,
            len(self._pod_node),
            self._bindings_epoch,
        )

    def encoding_view(self) -> tuple:
        """Read-only (NodeEncoding, free matrix) pair for sibling solver
        tiers (the partitioned frontier rides the cached topology slabs
        and the maintained free rows instead of re-deriving them). The
        matrix is the live maintained state — callers must not mutate it
        (copy before composing); both are None until the first encode."""
        return self._enc, self._free

    def free_dicts(self, nodes) -> Dict[str, Dict[str, float]]:
        """Per-node free-capacity dicts from the maintained matrix — the
        gRPC sidecar path's request builder consumes dicts, so delta state
        survives ``_solve_remote`` without a bindings repass."""
        out: Dict[str, Dict[str, float]] = {}
        if self._enc is None or self._free is None:
            return self.cluster.node_free_all(nodes)
        rn = self._enc.resource_names
        for node in nodes:
            i = self._enc.node_index.get(node.name)
            if i is None:
                out[node.name] = dict(node.capacity)
                continue
            out[node.name] = {
                r: float(self._free[i, j]) for j, r in enumerate(rn)
            }
        return out
