"""Partitioned solver frontier: per-super-domain subproblem decomposition
with batched device dispatch (docs/solver.md "Partitioned frontier").

PR 9 sharded every control-plane structure, but the pending-gang frontier
stayed one global solve: every wave of every tick pays O(gangs × nodes)
even though almost every gang's placement lands inside ONE narrow
topology domain. This module decomposes the solve the way Tesserae
decomposes placement policies (PAPERS.md) — the cluster is partitioned
into **topology super-domains** (the broadest level of the encoded
topology that has ≥ 2 domains; each domain is already a contiguous node
slab of the :class:`~grove_tpu.solver.encode.NodeEncoding` sort), each
pending gang is routed to one partition, and the partitions are solved
as independent node-disjoint subproblems:

- **Assignment** (deterministic, capacity-aware, host-side): a gang whose
  recovery pins / survivor seeds resolve inside one partition is FORCED
  there; a gang whose pins span partitions, carries a spread constraint,
  prefers a level broader than the frontier level, demands a resource no
  node supplies, or does not fit any single partition's remaining free
  capacity goes to the **residual**; every other gang is placed in the
  feasible partition with the most remaining headroom (greedy balance,
  its aggregate demand debited so assignment spreads load).
- **Independence**: a subproblem contains ONLY its slab's nodes and its
  assigned gangs, so no subproblem can read or write another's capacity
  rows — solving them in any order (or all at once) composes to the same
  result as solving them one by one. That composition is the frontier's
  semantic; the **residual solve** then runs the leftover gangs through
  the ordinary global kernel against the post-partition free capacity,
  in their original DRF-relative order, so any gang the local solve
  rejected (or could not be confined) still gets the full cluster.
- **Parallel execution**, two layers: (a) same-shape subproblems (gang
  axis padded to sticky pow2 buckets, node/domain axes padded per
  bucket) are STACKED and solved in single ``jax.vmap``-batched kernel
  dispatches (``ops.packing.solve_wave_chunk_stack`` driven by
  ``kernel.solve_waves_stacked``); (b) host-side encode of bucket k+1
  overlaps device execution of bucket k through a one-worker
  double-buffer thread (JAX releases the GIL during device compute).

The A/B contract (``GangScheduler.frontier_selfcheck``, the analogue of
PR 8's ``delta_selfcheck``): re-solve every subproblem ALONE through the
trusted host-loop :func:`~grove_tpu.solver.kernel.solve_waves`, recompose
sequentially, and assert the batched/overlapped composite is
BIT-identical — admissions, placements, scores, allocations. Degenerate
ticks (a single super-domain, or every gang residual) bypass to the
global solve path entirely, byte-identical by code path (pinned by
``make frontier-smoke``).

Frontier partition state (the plan cache, per-partition sub-encodings,
assignment scratch) is PRIVATE to this module — grovelint GL014 flags any
outside write; out-of-band invalidation goes through :meth:`invalidate`.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from grove_tpu.observability.journey import JOURNEYS
from grove_tpu.observability.metrics import METRICS
from grove_tpu.observability.tracing import TRACER
from grove_tpu.solver.encode import (
    _assemble_problem,
    _next_pow2,
    level_index_for_key,
    slice_encoding,
)
from grove_tpu.solver.types import PackingResult

# subproblems are small (a slab's worth of gangs): pad the gang axis to
# pow2 buckets with this floor instead of the global MIN_GANG_BUCKET (32)
# so a two-gang partition is not solved 16x padded
MIN_SUB_GANG_BUCKET = 8
RESIDUAL = -1


def frontier_devices() -> list:
    """Devices the stacked lanes spread over (docs/solver.md
    "Multi-device dispatch"): ``GROVE_TPU_FRONTIER_DEVICES=N`` pins the
    first N local devices, ``all`` takes every one. Default is the
    single-device path — byte-identical to PR 10's dispatch, and the
    right call on the test rig's VIRTUAL 8-device CPU mesh, where every
    "device" shares one physical core and spreading buys only compile
    time. ``[None]`` means default placement (no device pinning at
    all)."""
    import os

    raw = os.environ.get("GROVE_TPU_FRONTIER_DEVICES", "").strip().lower()
    if raw in ("", "0", "1"):
        return [None]
    import jax

    devs = list(jax.devices())
    if raw != "all":
        try:
            devs = devs[: max(int(raw), 1)]
        except ValueError:
            return [None]
    return devs if len(devs) > 1 else [None]


class FrontierPlan:
    """Partition table for one NodeEncoding: the frontier level, its
    contiguous node slabs, and lazily-built per-slab sub-encodings."""

    __slots__ = (
        "level", "starts", "ends", "num_partitions", "_sub_encodings",
    )

    def __init__(self, level: int, starts: np.ndarray, ends: np.ndarray):
        self.level = level
        self.starts = starts  # [K] int, slab [start, end) per partition
        self.ends = ends
        self.num_partitions = len(starts)
        # (partition, pad_to) -> slice_encoding(...) result
        self._sub_encodings: Dict[Tuple[int, int], tuple] = {}

    def partition_of_node(self, idx: int) -> int:
        """Partition owning global (topology-sorted) node index `idx`."""
        return int(np.searchsorted(self.starts, idx, side="right") - 1)

    def sub_encoding(self, enc, k: int, pad_to: int) -> tuple:
        key = (k, pad_to)
        sub = self._sub_encodings.get(key)
        if sub is None:
            sub = slice_encoding(
                enc, int(self.starts[k]), int(self.ends[k]), pad_to
            )
            self._sub_encodings[key] = sub
        return sub


class FrontierState:
    """Partitioned-frontier solve state for one GangScheduler. Attach via
    ``GangScheduler.enable_frontier()`` (requires the delta-solve state:
    the plan rides its cached NodeEncoding and maintained free matrix)."""

    def __init__(self, topology) -> None:
        self.topology = topology
        self._plan: Optional[FrontierPlan] = None
        self._plan_enc = None  # NodeEncoding identity the plan was cut from
        # lifetime counters (the bench "frontier" sub-block)
        self.solves = 0  # partitioned solves executed
        self.degenerate = 0  # ticks bypassed to the global path
        self.subproblems_total = 0
        self.assigned_total = 0
        self.residual_total = 0
        self.dispatches_total = 0
        self.last_subproblems = 0
        self.last_residual_fraction = 0.0
        self.last_overlap_occupancy = 0.0
        self.selfcheck_seconds = 0.0
        # multi-device lane spread (docs/solver.md "Multi-device
        # dispatch"): the devices stacks are pinned to; [None] = the
        # single-device default-placement path, byte-identical to PR 10
        self.devices = frontier_devices()
        self.last_devices_used = 1
        # persistent device-dispatch pool (multi-device runs only):
        # per-bucket executor construction would pay thread spawn/join on
        # every dispatch of every solve — built lazily once, state-lifetime
        self._device_pool = None
        # residual-overlap ledger (docs/solver.md "Residual overlap"):
        # hits = the speculative gang encode (overlapped with device
        # execution) was reused; misses = local rejects forced a
        # re-encode on the serial path
        self.residual_overlap_hits = 0
        self.residual_overlap_misses = 0

    # -- registration API (GL014) ----------------------------------------

    def invalidate(self) -> None:
        """Out-of-band invalidation hook: code that must touch frontier
        inputs outside the watched channels calls this so the next solve
        re-derives the plan (grovelint GL014 locks the private state to
        this module)."""
        self._plan = None
        self._plan_enc = None

    def close(self) -> None:
        """Release the device-dispatch pool (created only by multi-device
        runs; the mirror of Engine.close's ParallelDrain release —
        processes that build many schedulers should close retired ones)."""
        if self._device_pool is not None:
            self._device_pool.shutdown(wait=False, cancel_futures=True)
            self._device_pool = None

    # -- plan ------------------------------------------------------------

    def plan_for(self, enc) -> Optional[FrontierPlan]:
        """The partition plan for this NodeEncoding: slabs of the broadest
        topology level with ≥ 2 domains. None when every level is a single
        domain (nothing to partition — the degenerate global case). The
        outcome is cached per encoding IDENTITY either way: a degenerate
        topology must not re-scan the topo matrix every tick just to
        re-conclude there is nothing to partition."""
        if self._plan_enc is enc:
            return self._plan
        self._plan = None
        self._plan_enc = enc
        topo = enc.topo
        if topo.size == 0:
            return None
        for level in range(topo.shape[1]):
            width = int(topo[:, level].max()) + 1
            if width >= 2:
                starts = enc.seg_starts[level, :width].astype(np.int64)
                ends = enc.seg_ends[level, :width].astype(np.int64)
                self._plan = FrontierPlan(level, starts, ends)
                return self._plan
        return None

    # -- assignment ------------------------------------------------------

    def _pin_nodes(self, spec: dict) -> List[str]:
        pins = []
        if spec.get("gang_pinned_node"):
            pins.append(spec["gang_pinned_node"])
        for grp in spec["groups"]:
            if grp.get("pinned_node"):
                pins.append(grp["pinned_node"])
        pins.extend(spec.get("spread_survivor_nodes") or ())
        return pins

    def assign(
        self, plan: FrontierPlan, enc, free: np.ndarray,
        gang_specs: List[dict],
    ) -> np.ndarray:
        """Deterministic gang → partition map (RESIDUAL = -1), in the
        caller's (global DRF) order. Pure host work over the maintained
        free matrix: per-partition aggregates are slab prefix reductions,
        and each assignment debits its gang's aggregate demand so the
        greedy balance spreads load."""
        g = len(gang_specs)
        part_of = np.full((g,), RESIDUAL, dtype=np.int64)
        if g == 0:
            return part_of
        rindex = {r: j for j, r in enumerate(enc.resource_names)}
        # remaining free per partition, debited as gangs are assigned
        remaining = np.add.reduceat(free, plan.starts, axis=0).astype(
            np.float64
        )
        level_keys = enc.level_keys
        for i, spec in enumerate(gang_specs):
            if spec.get("spread_key"):
                continue  # balanced fills want the broad view: residual
            pref = level_index_for_key(
                level_keys, spec.get("preferred_key")
            )
            if 0 <= pref < plan.level:
                continue  # prefers a broader domain than a partition
            pins = self._pin_nodes(spec)
            forced = {
                plan.partition_of_node(enc.node_index[n])
                for n in pins
                if n in enc.node_index
            }
            if len(forced) > 1:
                continue  # multi-domain gang: survivors span partitions
            dvec = np.zeros((free.shape[1],), dtype=np.float64)
            unknown = False
            for grp in spec["groups"]:
                for r, q in grp["demand"].items():
                    j = rindex.get(r)
                    if j is None:
                        if q > 0:
                            unknown = True
                        continue
                    dvec[j] += q * grp["count"]
            if unknown:
                continue  # demands a resource no node supplies
            if forced:
                k = forced.pop()
            else:
                pos = dvec > 0
                if pos.any():
                    with np.errstate(divide="ignore"):
                        head = np.min(
                            remaining[:, pos] / dvec[pos], axis=1
                        )
                else:
                    head = remaining.sum(axis=1)
                k = int(np.argmax(head))
                if pos.any() and head[k] < 1.0:
                    continue  # fits no single partition: residual
            part_of[i] = k
            remaining[k] -= dvec
        return part_of

    # -- solve -----------------------------------------------------------

    def solve(self, sched, gang_specs: List[dict], problem):
        """Partitioned solve of the tick's pending frontier. Returns a
        composite :class:`PackingResult` in the global problem's index
        space, or None when the tick is degenerate (single super-domain,
        or every gang residual) — the caller then runs the ordinary
        global solve, byte-identical by code path."""
        enc, free = sched.delta.encoding_view()
        if enc is None or free is None:
            return None
        plan = self.plan_for(enc)
        if plan is None:
            self.degenerate += 1
            METRICS.inc("frontier_degenerate_total")
            return None
        part_of = self.assign(plan, enc, free, gang_specs)
        if JOURNEYS.enabled:
            # journey lane stamp: which frontier partition will solve each
            # gang this round (-1 = the global residual pass) — the per-gang
            # answer to "which solver lane held my admission"
            for i, spec in enumerate(gang_specs):
                JOURNEYS.note_partition(
                    spec["namespace"], spec["gang_name"], int(part_of[i])
                )
        parts_used = sorted({int(k) for k in part_of if k >= 0})
        if not parts_used:
            self.degenerate += 1
            METRICS.inc("frontier_degenerate_total")
            return None
        with TRACER.span(
            "solve.partition",
            subproblems=len(parts_used),
            gangs=len(gang_specs),
        ) as span:
            result = self._solve_partitioned(
                sched, gang_specs, problem, enc, free, plan, part_of,
                parts_used,
            )
            span.set("residual", int((part_of < 0).sum()))
        return result

    def _build_lane(
        self, enc, free, plan, k: int, idxs: List[int],
        gang_specs: List[dict], pad_gangs: int, pad_groups: int,
        n_pad: int, resource_names: List[str],
    ):
        """One partition's subproblem at the bucket's padded node shape."""
        s, e = int(plan.starts[k]), int(plan.ends[k])
        topo_local, seg_starts, seg_ends, node_names, node_index = (
            plan.sub_encoding(enc, k, n_pad)
        )
        capacity = np.zeros((n_pad, free.shape[1]), dtype=np.float32)
        capacity[: e - s] = free[s:e]
        sub_specs = [gang_specs[i] for i in idxs]
        return _assemble_problem(
            capacity,
            topo_local,
            seg_starts,
            seg_ends,
            node_names,
            resource_names,
            list(enc.level_keys),
            node_index,
            sub_specs,
            pad_gangs,
            pad_groups,
        )

    @staticmethod
    def _stack_bucket(problems: List) -> Dict[str, np.ndarray]:
        """Stack same-(G,P,N)-shape subproblems on a leading batch axis,
        padding the domain axis to the bucket max and the batch axis to
        pow2 with inert all-zero lanes."""
        d_max = max(p.seg_starts.shape[1] for p in problems)
        b_pad = _next_pow2(len(problems))

        def seg(a):
            out = np.zeros((a.shape[0], d_max), dtype=a.dtype)
            out[:, : a.shape[1]] = a
            return out

        fields = {
            "capacity": [p.capacity for p in problems],
            "topo": [p.topo for p in problems],
            "seg_starts": [seg(p.seg_starts) for p in problems],
            "seg_ends": [seg(p.seg_ends) for p in problems],
            "demand": [p.demand for p in problems],
            "count": [p.count for p in problems],
            "min_count": [p.min_count for p in problems],
            "req_level": [p.req_level for p in problems],
            "pref_level": [p.pref_level for p in problems],
            "group_req": [p.group_req for p in problems],
            "group_pin": [p.group_pin for p in problems],
            "gang_pin": [p.gang_pin for p in problems],
            "spread_level": [p.spread_level for p in problems],
            "spread_min": [p.spread_min for p in problems],
            "spread_required": [p.spread_required for p in problems],
            # assigned gangs never carry spread state: collapse every
            # lane's seed to the zero-width placeholder
            "spread_seed": [
                np.zeros(
                    (p.spread_level.shape[0], 0), dtype=np.int32
                )
                for p in problems
            ],
        }
        stack = {}
        for name, mats in fields.items():
            arr = np.stack(mats)
            if b_pad > arr.shape[0]:
                pad = np.zeros(
                    (b_pad - arr.shape[0],) + arr.shape[1:], dtype=arr.dtype
                )
                if name in ("req_level", "pref_level", "group_req",
                            "group_pin", "gang_pin", "spread_level"):
                    pad -= 1  # sentinel -1 axes
                arr = np.concatenate([arr, pad])
            stack[name] = arr
        return stack

    def _solve_partitioned(
        self, sched, gang_specs, problem, enc, free, plan, part_of,
        parts_used,
    ):
        t0 = time.perf_counter()
        pad_groups = problem.max_groups
        resource_names = list(problem.resource_names)
        # lanes grouped into sticky-pow2 buckets keyed by the padded
        # (gang, node) shape so each bucket is ONE stacked dispatch set.
        # ONE pass over the assignment builds every partition's index
        # list (a rescan per partition would be O(partitions × gangs) —
        # ~400M iterations at the 100k-node shape)
        idxs_by_part: Dict[int, List[int]] = {}
        for i, k in enumerate(part_of):
            if k >= 0:
                idxs_by_part.setdefault(int(k), []).append(i)
        lanes: List[dict] = []
        for k in parts_used:
            idxs = idxs_by_part[k]
            n_real = int(plan.ends[k] - plan.starts[k])
            lanes.append(
                {
                    "k": k,
                    "idxs": idxs,
                    "n_real": n_real,
                    "g_pad": _next_pow2(
                        max(len(idxs), MIN_SUB_GANG_BUCKET)
                    ),
                }
            )
        buckets: Dict[Tuple[int, int], List[dict]] = {}
        for lane in lanes:
            n_pad = _next_pow2(max(lane["n_real"], 8))
            lane["n_pad"] = n_pad
            buckets.setdefault((lane["g_pad"], n_pad), []).append(lane)
        bucket_keys = sorted(buckets)

        devices = self.devices
        devices_used = 1

        def encode_bucket(key):
            """One bucket's lane problems + its per-device stacks:
            [(device, stack, real_lane_count)]. With one device (the
            default) this is exactly the PR 10 single-stack path; with
            D devices the bucket's lanes split into contiguous groups in
            lane order — each lane's tensors, chunking and seeds are
            lane-local, so the split composes bit-identically (the same
            inert-lane property the pow2 batch padding already relies
            on, and the selfcheck below re-verifies per lane)."""
            g_pad, n_bucket = key
            lanes_k = buckets[key]
            for lane in lanes_k:
                lane["problem"] = self._build_lane(
                    enc, free, plan, lane["k"], lane["idxs"], gang_specs,
                    g_pad, pad_groups, n_bucket, resource_names,
                )
            n_groups = min(len(devices), len(lanes_k))
            if n_groups <= 1:
                return [
                    (
                        devices[0],
                        self._stack_bucket([l["problem"] for l in lanes_k]),
                        len(lanes_k),
                    )
                ]
            per = (len(lanes_k) + n_groups - 1) // n_groups
            groups = [
                lanes_k[i : i + per] for i in range(0, len(lanes_k), per)
            ]
            return [
                (
                    devices[d],
                    self._stack_bucket([l["problem"] for l in grp]),
                    len(grp),
                )
                for d, grp in enumerate(groups)
            ]

        # double-buffered pipeline: the device executes bucket k while the
        # host encodes bucket k+1 (JAX releases the GIL in device compute);
        # after the LAST bucket's submit the host instead pre-encodes the
        # residual pass's gang tensors (the "Residual overlap" half)
        from concurrent.futures import ThreadPoolExecutor

        from grove_tpu.solver.kernel import solve_waves_stacked

        dispatches = 0
        execute_wall = 0.0
        overlapped = 0.0
        bucket_results: Dict[tuple, dict] = {}
        # gangs KNOWN residual at assignment time — the speculative
        # encode target (local rejects, unknowable until results, force
        # the miss path)
        assigned_residual = [
            i for i in range(len(part_of)) if part_of[i] == RESIDUAL
        ]
        pre_encoded = None
        pre_encoded_idxs = None

        def run(stacks):
            nonlocal devices_used
            t = time.perf_counter()
            if len(stacks) == 1:
                # single stack (the default single-device path): return
                # the kernel output dict directly, exactly PR 10 —
                # consumers index only real lanes/gangs, so trimming the
                # padded batch lanes would just copy every result tensor
                # (alloc is [B,G,P,N]) for nothing
                dev, stack, _n_real = stacks[0]
                out = solve_waves_stacked(
                    stack,
                    chunk_size=sched.chunk_size,
                    max_waves=sched.max_waves,
                    device=dev,
                )
                out["wall"] = time.perf_counter() - t
                return out
            else:
                devices_used = max(devices_used, len(stacks))
                if self._device_pool is None:
                    self._device_pool = ThreadPoolExecutor(
                        max_workers=len(self.devices),
                        thread_name_prefix="frontier-dev",
                    )
                futs = [
                    self._device_pool.submit(
                        solve_waves_stacked,
                        stack,
                        chunk_size=sched.chunk_size,
                        max_waves=sched.max_waves,
                        device=dev,
                    )
                    for dev, stack, _n in stacks
                ]
                outs = [
                    (fut.result(), n)
                    for fut, (_d, _s, n) in zip(futs, stacks)
                ]
            # merge per-device groups back in lane order (groups are
            # contiguous lane ranges; padded batch lanes trimmed)
            merged = {
                field: np.concatenate(
                    [out[field][:n] for out, n in outs]
                )
                for field in (
                    "admitted", "placed", "score", "chosen_level", "alloc"
                )
            }
            merged["dispatches"] = sum(out["dispatches"] for out, _n in outs)
            merged["wall"] = time.perf_counter() - t
            return merged

        def encode_residual():
            """Speculative residual gang encode, overlapped with device
            execution; reused by build_problem_cached on the hit path
            (encode_gangs is pure — bit-identical either way)."""
            nonlocal pre_encoded, pre_encoded_idxs
            from grove_tpu.solver.encode import encode_gangs

            pre_encoded_idxs = list(assigned_residual)
            pre_encoded = encode_gangs(
                [gang_specs[i] for i in pre_encoded_idxs],
                resource_names,
                list(enc.level_keys),
                None,
                pad_groups,
            )

        if len(bucket_keys) == 1 and not assigned_residual:
            # one bucket and nothing to pre-encode ⇒ nothing to overlap:
            # run inline rather than paying thread spawn/join on the
            # common small-tick path
            key = bucket_keys[0]
            out = run(encode_bucket(key))
            bucket_results[key] = out
            dispatches += out["dispatches"]
            execute_wall += out["wall"]
        elif bucket_keys:
            with ThreadPoolExecutor(max_workers=1) as pool:
                pending = list(bucket_keys)
                next_stacks = encode_bucket(pending[0])
                while pending:
                    key = pending.pop(0)
                    stacks = next_stacks
                    t_submit = time.perf_counter()
                    future = pool.submit(run, stacks)
                    next_stacks = None
                    if pending:
                        next_stacks = encode_bucket(pending[0])
                    elif assigned_residual and pre_encoded is None:
                        encode_residual()
                    encode_elapsed = time.perf_counter() - t_submit
                    out = future.result()
                    bucket_results[key] = out
                    dispatches += out["dispatches"]
                    execute_wall += out["wall"]
                    overlapped += min(encode_elapsed, out["wall"])

        # residual: the leftover gangs against the post-partition free
        # capacity (original units), through the ordinary global kernel.
        # LOCAL REJECTS join it: a gang the greedy assignment confined to
        # a partition that turned out too fragmented for it must still
        # see the whole cluster THIS tick (the admission-completeness
        # half of the independence argument — docs/solver.md), not
        # starve behind a deterministic re-confinement next tick.
        rejected: set = set()
        for key, out in bucket_results.items():
            for li, lane in enumerate(buckets[key]):
                for gi, g_global in enumerate(lane["idxs"]):
                    if not out["admitted"][li, gi]:
                        rejected.add(g_global)
        residual_idxs = [
            i
            for i in range(len(part_of))
            if part_of[i] == RESIDUAL or i in rejected
        ]
        free_after = np.array(free, dtype=np.float32)
        rindex = {r: j for j, r in enumerate(enc.resource_names)}
        for key, out in bucket_results.items():
            for li, lane in enumerate(buckets[key]):
                s = int(plan.starts[lane["k"]])
                n_real = lane["n_real"]
                for gi, g_global in enumerate(lane["idxs"]):
                    if not out["admitted"][li, gi]:
                        continue
                    spec = gang_specs[g_global]
                    for p, grp in enumerate(spec["groups"]):
                        counts = out["alloc"][li, gi, p, :n_real]
                        if not counts.any():
                            continue
                        for r, q in grp["demand"].items():
                            j = rindex.get(r)
                            if j is not None:
                                free_after[s : s + n_real, j] -= (
                                    counts * np.float32(q)
                                )
        residual_result = None
        residual_problem = None
        if residual_idxs:
            from grove_tpu.solver.encode import build_problem_cached
            from grove_tpu.solver.kernel import solve_waves

            if pre_encoded is not None and residual_idxs == pre_encoded_idxs:
                # overlap HIT: the gang tensors were encoded while the
                # device executed the partition solves — only the
                # capacity half (which needed the post-partition fold)
                # is assembled now
                self.residual_overlap_hits += 1
                METRICS.inc("frontier_residual_overlap_hits_total")
                residual_problem = build_problem_cached(
                    enc,
                    free_after,
                    [gang_specs[i] for i in residual_idxs],
                    None,
                    pad_groups,
                    pre_encoded=pre_encoded,
                )
            else:
                # miss: local rejects joined the residual after the
                # speculative encode (or no bucket overlapped it) —
                # re-encode on the serial path, exactly PR 10's behavior
                if pre_encoded is not None:
                    self.residual_overlap_misses += 1
                    METRICS.inc("frontier_residual_overlap_misses_total")
                residual_problem = build_problem_cached(
                    enc,
                    free_after,
                    [gang_specs[i] for i in residual_idxs],
                    None,
                    pad_groups,
                )
            residual_result = solve_waves(
                residual_problem,
                chunk_size=sched.chunk_size,
                max_waves=sched.max_waves,
                with_alloc=True,
            )

        composite = self._compose(
            problem, gang_specs, plan, buckets, bucket_results,
            residual_idxs, residual_result,
        )
        composite.solve_seconds = execute_wall + (
            residual_result.solve_seconds if residual_result else 0.0
        )

        # bookkeeping
        self.solves += 1
        self.last_subproblems = len(parts_used)
        self.subproblems_total += len(parts_used)
        self.assigned_total += int((part_of >= 0).sum())
        self.residual_total += len(residual_idxs)
        self.dispatches_total += dispatches + (
            0 if residual_result is None else 1
        )
        self.last_residual_fraction = (
            len(residual_idxs) / max(len(gang_specs), 1)
        )
        self.last_overlap_occupancy = overlapped / max(execute_wall, 1e-9)
        self.last_devices_used = devices_used
        METRICS.set("frontier_devices", devices_used)
        METRICS.inc("frontier_solves_total")
        METRICS.set("frontier_subproblems", self.last_subproblems)
        METRICS.set(
            "frontier_residual_fraction",
            round(self.last_residual_fraction, 4),
        )
        METRICS.set("frontier_batched_dispatches", dispatches)
        METRICS.set(
            "frontier_overlap_occupancy",
            round(self.last_overlap_occupancy, 4),
        )
        METRICS.observe(
            "frontier_solve_seconds", time.perf_counter() - t0
        )

        if sched.frontier_selfcheck:
            self._selfcheck(
                sched, gang_specs, problem, plan, buckets, bucket_results,
                residual_idxs, residual_result, composite,
            )
        return composite

    def _compose(
        self, problem, gang_specs, plan, buckets, bucket_results,
        residual_idxs, residual_result,
    ) -> PackingResult:
        """Fold per-subproblem and residual results back into the global
        problem's [G, P, N] index space (subproblem node columns map
        through their slab offsets; residual columns are already global)."""
        g_pad = problem.num_gangs
        p_max = problem.max_groups
        n = problem.num_nodes
        admitted = np.zeros((g_pad,), dtype=bool)
        placed = np.zeros((g_pad, p_max), dtype=np.int32)
        score = np.zeros((g_pad,), dtype=np.float32)
        chosen_level = np.full((g_pad,), -1, dtype=np.int32)
        alloc = np.zeros((g_pad, p_max, n), dtype=np.int32)
        for key, out in bucket_results.items():
            for li, lane in enumerate(buckets[key]):
                s = int(plan.starts[lane["k"]])
                n_real = lane["n_real"]
                for gi, g_global in enumerate(lane["idxs"]):
                    admitted[g_global] = out["admitted"][li, gi]
                    placed[g_global] = out["placed"][li, gi]
                    score[g_global] = out["score"][li, gi]
                    chosen_level[g_global] = out["chosen_level"][li, gi]
                    alloc[g_global, :, s : s + n_real] = out["alloc"][
                        li, gi, :, :n_real
                    ]
        if residual_result is not None:
            for ri, g_global in enumerate(residual_idxs):
                admitted[g_global] = residual_result.admitted[ri]
                placed[g_global] = residual_result.placed[ri]
                score[g_global] = residual_result.score[ri]
                chosen_level[g_global] = residual_result.chosen_level[ri]
                alloc[g_global] = residual_result.alloc[ri]
        return PackingResult(
            admitted=admitted,
            placed=placed,
            score=score,
            chosen_level=chosen_level,
            alloc=alloc,
            free_after=None,  # composite; per-subproblem units differ
            solve_seconds=0.0,
        )

    def _selfcheck(
        self, sched, gang_specs, problem, plan, buckets, bucket_results,
        residual_idxs, residual_result, composite,
    ) -> None:
        """The frontier A/B (delta_selfcheck's analogue): re-solve every
        subproblem ALONE through the trusted host-loop solve_waves on the
        SAME tensors, recompose sequentially, and assert the batched +
        overlapped composite is bit-identical. The residual already ran
        through solve_waves, so the check pins exactly the new machinery:
        the vmap-batched dispatch, the stacking/padding, the double-buffer
        thread, and the composition."""
        from grove_tpu.solver.kernel import solve_waves

        t0 = time.perf_counter()
        ref_results: Dict[tuple, dict] = {}
        for key, out in bucket_results.items():
            lanes = buckets[key]
            ref = {
                f: np.zeros_like(out[f])
                for f in ("admitted", "placed", "score", "chosen_level",
                          "alloc")
            }
            for li, lane in enumerate(lanes):
                solo = solve_waves(
                    lane["problem"],
                    chunk_size=sched.chunk_size,
                    max_waves=sched.max_waves,
                    with_alloc=True,
                )
                for field, got in (
                    ("admitted", solo.admitted),
                    ("placed", solo.placed),
                    ("score", solo.score),
                    ("chosen_level", solo.chosen_level),
                    ("alloc", solo.alloc),
                ):
                    ref[field][li] = got
                    if not np.array_equal(out[field][li], got):
                        raise AssertionError(
                            "partitioned frontier diverged from the solo"
                            f" solve on {field!r} (partition"
                            f" {lane['k']}, bucket {key})"
                        )
            ref_results[key] = ref
        ref_composite = self._compose(
            problem, gang_specs, plan, buckets, ref_results,
            residual_idxs, residual_result,
        )
        for field in ("admitted", "placed", "score", "chosen_level",
                      "alloc"):
            if not np.array_equal(
                getattr(composite, field), getattr(ref_composite, field)
            ):
                raise AssertionError(
                    "partitioned frontier composite diverged from the"
                    f" sequential recomposition on {field!r}"
                )
        elapsed = time.perf_counter() - t0
        self.selfcheck_seconds += elapsed
        sched.last_selfcheck_seconds += elapsed

    # -- reporting -------------------------------------------------------

    def stats(self) -> dict:
        """Lifetime counters for the bench "frontier" sub-block."""
        return {
            "solves": self.solves,
            "degenerate_ticks": self.degenerate,
            "subproblems_total": self.subproblems_total,
            "assigned_gangs_total": self.assigned_total,
            "residual_gangs_total": self.residual_total,
            "residual_fraction": round(
                self.residual_total
                / max(self.assigned_total + self.residual_total, 1),
                4,
            ),
            "batched_dispatches_total": self.dispatches_total,
            "last_overlap_occupancy": round(
                self.last_overlap_occupancy, 4
            ),
            "devices": len(self.devices),
            "last_devices_used": self.last_devices_used,
            "residual_overlap_hits": self.residual_overlap_hits,
            "residual_overlap_misses": self.residual_overlap_misses,
            "ab_overhead_ms": round(self.selfcheck_seconds * 1e3, 1),
        }
