"""Multi-cluster federation tier (docs/federation.md).

A :class:`FederationRouter` owns K independent simulated clusters —
each a full ``SimHarness`` with its own store shards, WAL dir, quota
accountant, monitor/broker/drainer, and optional workers — and places
incoming PodGangs across them: home-cluster affinity first, spillover
when the home cluster's explain verdict says it cannot admit now,
candidate targets ranked by the frontier-style (headroom,
fragmentation delta, queue age) score in global DRF order, and
cross-cluster tenant quota as a level-3 fold over the per-cluster
accountants (:class:`GlobalQuotaFold`, the ShardSummaryTree idiom one
level up).
"""

from grove_tpu.federation.quota import GlobalQuotaFold
from grove_tpu.federation.router import (
    FederatedCluster,
    FederationRouter,
    federation_artifact,
)

__all__ = [
    "FederatedCluster",
    "FederationRouter",
    "GlobalQuotaFold",
    "federation_artifact",
]
