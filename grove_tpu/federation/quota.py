"""Cross-cluster tenant quota: the level-3 fold.

Level 1 is the per-shard pod aggregate, level 2 the per-store
``ShardSummaryTree`` (runtime/shards.py) — this is the same idiom one
level up: each CLUSTER's quota accountant snapshot (queue → resource →
usage) is a leaf partial, folded upward with fan-in ``FOLD_FAN_IN`` so
no fold at any level sees more than ``fan_in`` rows and a global
usage read is O(K) over partials, never a scan of any cluster's pod
population. The root is what makes a tenant's deserved share GLOBAL:
the router feeds it as the ``usage`` argument to the DRF ordering, so
a tenant saturated in one region is ordered behind hungrier tenants
everywhere (docs/federation.md "Global quota fold").
"""

from __future__ import annotations

from typing import Dict, List

from grove_tpu.runtime.shards import FOLD_FAN_IN

# queue → resource → usage; the shape accountant.snapshot() returns
QuotaPartial = Dict[str, Dict[str, float]]


def _merge(rows: List[QuotaPartial]) -> QuotaPartial:
    out: QuotaPartial = {}
    for row in rows:
        for queue, usage in row.items():
            acc = out.setdefault(queue, {})
            for res, val in usage.items():
                acc[res] = acc.get(res, 0.0) + val
    return out


class GlobalQuotaFold:
    """Level-3 hierarchical fold over per-cluster quota partials."""

    __slots__ = ("num_clusters", "fan_in", "levels")

    def __init__(self, num_clusters: int, fan_in: int = FOLD_FAN_IN) -> None:
        self.num_clusters = max(1, num_clusters)
        self.fan_in = max(2, fan_in)
        # levels[0] = per-cluster leaves, levels[-1] = single root
        self.levels: List[List[QuotaPartial]] = []
        width = self.num_clusters
        while True:
            self.levels.append([{} for _ in range(width)])
            if width == 1:
                break
            width = (width + self.fan_in - 1) // self.fan_in

    @property
    def depth(self) -> int:
        return len(self.levels)

    def refold(self, partials: List[QuotaPartial]) -> None:
        """Fold fresh leaf partials up the tree (one call per router
        scoring round — O(K) over partials)."""
        self.levels[0] = list(partials)
        for li in range(1, len(self.levels)):
            below = self.levels[li - 1]
            level = []
            # each parent folds at most fan_in children
            for i in range(0, len(below), self.fan_in):
                level.append(_merge(below[i : i + self.fan_in]))
            self.levels[li] = level

    def update_leaf(self, index: int, partial: QuotaPartial) -> None:
        """Path refold: one cluster's accountant moved — refold only its
        ancestor chain, O(depth × fan_in) instead of O(K)."""
        self.levels[0][index] = partial
        child = index
        for li in range(1, len(self.levels)):
            parent = child // self.fan_in
            base = parent * self.fan_in
            below = self.levels[li - 1]
            self.levels[li][parent] = _merge(below[base : base + self.fan_in])
            child = parent

    def root(self) -> QuotaPartial:
        return self.levels[-1][0]

    def fold_depth_histogram(self) -> List[int]:
        """Nodes per fold level, leaves first — the proof the global
        usage read is a tree fold, not a flat rescan."""
        return [len(level) for level in self.levels]
