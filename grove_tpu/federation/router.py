"""FederationRouter: the global gang router over K simulated clusters.

Each cluster is a full :class:`SimHarness` — its own store shards, WAL
dir, quota accountant, monitor/broker/drainer, and optional workers —
sharing ONE virtual clock so the federation converge loop can drive
them in lockstep (``SimHarness.tick_once``/``next_wake``). Placement
policy (docs/federation.md):

- **home affinity** — a PodCliqueSet lands in its home region (the
  ``federation.grove.io/home`` label, an explicit argument, or the
  first region) whenever that region is Ready; data gravity means the
  router never proactively load-balances a placeable workload away.
- **spillover** — reactive: a gang pending past ``spill_after`` whose
  home cluster's explain verdict says it cannot admit now (and is not
  quota-capped or disruption-held — those block everywhere) moves to
  the best admissible sibling, ranked on (fragmentation delta,
  −headroom, region) from ``introspect.federation_score_inputs``, in
  GLOBAL DRF order over the union frontier with the level-3 quota fold
  as the usage ledger.
- **cluster_crash** — a whole region dies; every placement it held
  re-routes to surviving clusters through the same scoring core and
  re-admits under the ordinary broker/budget machinery. ``rejoin``
  rebuilds a fresh harness on the shared clock; placements do NOT fail
  back (the decision ledger records where everything went and why).

K=1 is provably inert: the converge loop reduces exactly to the bare
harness's (no spill pass, same idle-jump arithmetic), pinned
byte-identical in tests/test_federation.py. All ``_``-prefixed state
is private to this package — grovelint GL021 ``federation-state``.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from grove_tpu.api import names as namegen
from grove_tpu.api.meta import deep_copy, get_condition
from grove_tpu.api.types import COND_PODGANG_SCHEDULED, PodCliqueSet
from grove_tpu.federation.quota import GlobalQuotaFold
from grove_tpu.observability.events import (
    EVENTS,
    REASON_CLUSTER_HEALED,
    REASON_CLUSTER_LOST,
    REASON_CLUSTER_PARTITIONED,
    REASON_CLUSTER_REJOINED,
    REASON_GANG_REQUEUED,
    REASON_GANG_SPILLED,
)
from grove_tpu.observability.metrics import METRICS
from grove_tpu.runtime.clock import VirtualClock
from grove_tpu.runtime.store import Store
from grove_tpu.sim.harness import SimHarness
from grove_tpu.solver import introspect

# explain-verdict detail slugs that block admission EVERYWHERE — quota
# is global (the level-3 fold), and a monitor hold releases locally —
# so spilling on them would burn a move without unblocking anything
_NO_SPILL_DETAILS = ("quota-ceiling", "disruption-hold")


@dataclass
class FederatedCluster:
    """One region's registry row: the live harness (None while Lost),
    its diurnal phase offset, and lifecycle bookkeeping."""

    region: str
    harness: Optional[SimHarness]
    phase_offset: float = 0.0
    index: int = 0
    state: str = "Ready"  # Ready | Lost | Partitioned
    lost_at: Optional[float] = None
    crashes: int = 0
    # partition ≠ crash (docs/federation.md): an unreachable region's
    # harness stays ALIVE and keeps converging on the shared clock — the
    # router just cannot talk to it. `reachable` is the fault plane;
    # `state` flips to Partitioned only once the router's suspicion
    # timeout expires.
    reachable: bool = True
    unreachable_since: Optional[float] = None
    partitions: int = 0


def pcs_floor_demand(pcs: PodCliqueSet) -> Dict[str, float]:
    """Aggregate floor demand of one PCS template (per-clique floor ×
    template replicas) — the routing score's demand vector when no live
    PodGang spec exists (initial placement, crash re-route)."""
    out: Dict[str, float] = {}
    replicas = max(1, int(getattr(pcs.spec, "replicas", 1) or 1))
    for clq in pcs.spec.template.cliques:
        n = (
            clq.spec.min_available
            if clq.spec.min_available is not None
            else clq.spec.replicas
        )
        for c in clq.spec.pod_spec.containers:
            for r, q in c.requests.items():
                out[r] = out.get(r, 0.0) + float(q) * n * replicas
    return out


class FederationRouter:
    """Owns K clusters and every cross-cluster placement decision."""

    def __init__(
        self,
        regions: List[str],
        num_nodes: int = 16,
        phase_offsets: Optional[List[float]] = None,
        spill_after: float = 30.0,
        partition_suspect_after: float = 30.0,
        durability_root: Optional[str] = None,
        harness_factory: Optional[Callable] = None,
    ) -> None:
        if not regions:
            raise ValueError("federation: at least one region required")
        if len(set(regions)) != len(regions):
            raise ValueError("federation: duplicate region names")
        self.clock = VirtualClock()
        self.spill_after = spill_after
        # how long a region may be unreachable before the router suspects
        # a partition (fences + spills its still-pending gangs); matches
        # the region's own lease expiry on the shared clock
        self.partition_suspect_after = partition_suspect_after
        self.num_nodes = num_nodes
        self._durability_root = durability_root
        self._factory = harness_factory
        # region -> FederatedCluster, in registration order (the
        # deterministic tick / tie-break order)
        self._clusters: "OrderedDict[str, FederatedCluster]" = OrderedDict()
        # (ns, pcs name) -> (pristine pre-defaulting template, home region)
        self._specs: Dict[Tuple[str, str], Tuple[PodCliqueSet, str]] = {}
        # (ns, pcs name) -> current region
        self._placements: Dict[Tuple[str, str], str] = {}
        # queue name -> pristine Queue template (applied to every cluster)
        self._queues: Dict[str, object] = {}
        # the routing ledger: every place/spill/reroute/strand/rejoin,
        # vt-stamped, with score inputs and the home verdict that drove it
        self._decisions: List[dict] = []
        # (ns, pcs name) spilled off a partitioned region, by region: the
        # stale copies still sitting in the unreachable store that heal
        # reconciliation must delete (the one write a partition forbids)
        self._partition_spills: Dict[str, List[Tuple[str, str]]] = {}
        # lifetime counters (bench "federation" block / GET /federation)
        self.spillovers = 0
        self.reroutes = 0
        self.partition_spills = 0
        self.fold = GlobalQuotaFold(len(regions))
        offsets = phase_offsets or [0.0] * len(regions)
        if len(offsets) != len(regions):
            raise ValueError("federation: one phase offset per region")
        for i, region in enumerate(regions):
            cl = FederatedCluster(
                region=region,
                harness=self._build_harness(region),
                phase_offset=float(offsets[i]),
                index=i,
            )
            self._install_context(cl)
            self._clusters[region] = cl
        METRICS.set("federation_clusters_ready", float(len(regions)))
        METRICS.set(
            "federation_quota_fold_depth", float(self.fold.depth)
        )

    # -- construction ----------------------------------------------------

    def _build_harness(self, region: str) -> SimHarness:
        if self._factory is not None:
            return self._factory(region, self.clock)
        durability_dir = None
        if self._durability_root is not None:
            import os

            durability_dir = os.path.join(self._durability_root, region)
        return SimHarness(
            num_nodes=self.num_nodes,
            store=Store(self.clock, cache_lag=True),
            durability_dir=durability_dir,
        )

    def _install_context(self, cl: FederatedCluster) -> None:
        """Arm this cluster's explain engine with the funnel's "which
        cluster and why" stage (observability/explain.py stage 0)."""
        region = cl.region
        router = self

        def _ctx(namespace: str, name: str) -> str:
            why = "home placement"
            holder = router._clusters.get(region)
            if holder is not None and holder.harness is not None:
                gang = holder.harness.store.get(
                    "PodGang", namespace, name, readonly=True
                )
                if gang is not None:
                    pcs_name = gang.metadata.labels.get(
                        namegen.LABEL_PART_OF
                    )
                    d = router._decision_for(namespace, pcs_name)
                    if d is not None:
                        if d["kind"] == "spill":
                            why = (
                                f"spilled from {d['from']}"
                                f" ({d.get('why', 'home cannot admit')})"
                            )
                        elif d["kind"] == "reroute":
                            why = (
                                f"re-routed from lost cluster {d['from']}"
                            )
                        else:
                            why = (
                                "home-affinity placement"
                                f" (home {d['home']})"
                            )
            return (
                f"cluster {region} of {len(router._clusters)}: {why}"
            )

        if cl.harness is not None:
            cl.harness.explain.cluster_context = _ctx

    def _decision_for(
        self, namespace: str, pcs_name: Optional[str]
    ) -> Optional[dict]:
        if not pcs_name:
            return None
        for d in reversed(self._decisions):
            if d["namespace"] == namespace and d["name"] == pcs_name:
                return d
        return None

    # -- registry faces --------------------------------------------------

    def clusters(self) -> List[FederatedCluster]:
        return list(self._clusters.values())

    def cluster(self, region: str) -> Optional[FederatedCluster]:
        return self._clusters.get(region)

    def placements(self) -> Dict[Tuple[str, str], str]:
        return dict(self._placements)

    def decisions(self) -> List[dict]:
        return [dict(d) for d in self._decisions]

    def _ready(self) -> List[FederatedCluster]:
        """Clusters the router may ROUTE to/through: Ready AND reachable.
        An unreachable region drops out of routing the instant the fault
        lands (the router's calls to it would hang), even before the
        suspicion timeout flips its state to Partitioned."""
        return [
            cl
            for cl in self._clusters.values()
            if cl.state == "Ready" and cl.reachable
        ]

    def _live(self) -> List[FederatedCluster]:
        """Clusters whose control plane is RUNNING (everything but Lost):
        a partitioned region keeps converging on the shared clock — it
        just cannot be routed to or read by the router."""
        return [
            cl
            for cl in self._clusters.values()
            if cl.state != "Lost" and cl.harness is not None
        ]

    def _record(self, kind: str, namespace: str, name: str, **kw) -> dict:
        d = dict(
            {
                "vt": self.clock.now(),
                "kind": kind,
                "namespace": namespace,
                "name": name,
            },
            **kw,
        )
        self._decisions.append(d)
        return d

    # -- user actions ----------------------------------------------------

    def apply(self, pcs, home: Optional[str] = None):
        """Route one PodCliqueSet (or tenant Queue — fanned out to every
        cluster): home affinity first, score-ranked fallback only when
        the home region is Lost."""
        from grove_tpu.api.types import Queue

        if isinstance(pcs, Queue):
            return self.apply_queue(pcs)
        home = (
            home
            or pcs.metadata.labels.get(namegen.LABEL_FEDERATION_HOME)
            or next(iter(self._clusters))
        )
        if home not in self._clusters:
            raise ValueError(f"federation: unknown region {home!r}")
        key = (pcs.metadata.namespace or "default", pcs.metadata.name)
        template = deep_copy(pcs)
        target = home
        why = "home ready"
        if self._clusters[home].state != "Ready":
            ranked = self._rank_targets(
                pcs_floor_demand(template), exclude=None
            )
            if not ranked:
                raise ValueError(
                    "federation: no Ready cluster to place"
                    f" {key[0]}/{key[1]} (home {home} is Lost)"
                )
            target = ranked[0][1]
            why = f"home {home} is Lost; best surviving score"
        applied = self._clusters[target].harness.apply(pcs)
        self._specs[key] = (template, home)
        self._placements[key] = target
        self._record(
            "place", key[0], key[1], home=home, to=target, why=why
        )
        return applied

    def apply_queue(self, queue):
        """Tenant Queues are GLOBAL: the same CR lands in every Ready
        cluster (and re-lands on rejoin), so the per-cluster DRF trees
        agree and the level-3 fold is comparing like with like."""
        self._queues[queue.metadata.name] = deep_copy(queue)
        applied = None
        for cl in self._ready():
            applied = cl.harness.apply_queue(deep_copy(queue))
        return applied

    def delete(self, name: str, namespace: str = "default") -> None:
        key = (namespace, name)
        region = self._placements.pop(key, None)
        self._specs.pop(key, None)
        if region is not None:
            cl = self._clusters.get(region)
            if cl is not None and cl.harness is not None:
                cl.harness.delete(name, namespace)

    # -- convergence -----------------------------------------------------

    def converge(
        self, max_ticks: int = 60, tick_seconds: float = 1.0
    ) -> int:
        """Drive every Ready cluster in lockstep on the shared clock —
        per tick: each harness's tick_once() in region order, then (only
        when siblings exist) one spillover pass. With K=1 this loop IS
        ``SimHarness.converge`` — same idle test, same wake jump, same
        store guard — the byte-identity pin in tests/test_federation.py.
        """
        ticks = 0
        for _ in range(max_ticks):
            work = self._partition_suspect_tick()
            ready = self._ready()
            live = self._live()
            bound = started = 0
            # EVERY live harness ticks — a partitioned region's control
            # plane keeps converging on the shared clock (partition ≠
            # crash); only routing below is restricted to `ready`
            for cl in live:
                w, b, s = cl.harness.tick_once()
                work += w
                bound += b
                started += s
            if len(ready) > 1:
                work += self._spill_tick(ready)
            ticks += 1
            if bound == 0 and started == 0 and work == 0:
                wakes = [
                    w
                    for w in (
                        cl.harness.next_wake() for cl in live
                    )
                    if w is not None
                ]
                suspect_wake = self._next_suspect_deadline()
                if suspect_wake is not None:
                    wakes.append(suspect_wake)
                if len(ready) > 1:
                    # a pending gang becomes spill-eligible at
                    # creation + spill_after: that moment is a wake
                    # deadline too, or the loop idles out before the
                    # spillover pass ever gets to judge it
                    spill_wake = self._next_spill_deadline(ready)
                    if spill_wake is not None:
                        wakes.append(spill_wake)
                wake = min(wakes) if wakes else None
                if wake is not None and wake - self.clock.now() <= 120.0:
                    self.clock.advance(
                        max(wake - self.clock.now(), 0.0)
                    )
                    continue
                break
            self.clock.advance(tick_seconds)
        from grove_tpu.analysis.sanitize import store_guard_enabled

        if store_guard_enabled():
            for cl in self._ready():
                cl.harness.store.verify_readonly_integrity()
        return ticks

    # -- partition suspicion ---------------------------------------------

    def _next_suspect_deadline(self) -> Optional[float]:
        """Earliest instant an unreachable-but-not-yet-Partitioned region
        crosses ``partition_suspect_after`` — a converge wake deadline,
        or the loop would idle out before ever suspecting."""
        best: Optional[float] = None
        for cl in self._clusters.values():
            if (
                cl.state == "Ready"
                and not cl.reachable
                and cl.unreachable_since is not None
            ):
                due = cl.unreachable_since + self.partition_suspect_after
                if best is None or due < best:
                    best = due
        return best

    def _partition_suspect_tick(self) -> int:
        """Flip Ready-but-unreachable regions past the suspicion timeout
        to Partitioned: fence their admission (the region's own lease
        expiry on the shared clock — it may no longer flip gangs to
        Scheduled), then spill ONLY its still-pending placements.
        Anything the region already Scheduled stays bound there —
        invariant F3: no PodGang is ever Scheduled in two clusters
        across a partition/heal cycle. Because the fence lands before
        the store read, the Scheduled set cannot grow under us, so
        "pending at suspect time" is an honest one-shot judgment."""
        now = self.clock.now()
        work = 0
        for cl in self._clusters.values():
            if (
                cl.state != "Ready"
                or cl.reachable
                or cl.unreachable_since is None
                or now - cl.unreachable_since
                < self.partition_suspect_after
            ):
                continue
            cl.state = "Partitioned"
            cl.partitions += 1
            # fence FIRST: a fenced scheduler cannot newly bind, so the
            # pending/Scheduled split read below is final (F3 holds by
            # construction, not by luck of tick ordering)
            cl.harness.scheduler.admission_fenced = True
            METRICS.inc("federation_cluster_partitions_total")
            METRICS.set(
                "federation_clusters_ready", float(len(self._ready()))
            )
            EVENTS.record(
                ("Cluster", "", cl.region),
                "Warning",
                REASON_CLUSTER_PARTITIONED,
                f"region {cl.region} partitioned after"
                f" {self.partition_suspect_after:.0f}s unreachable;"
                " admission fenced, spilling pending gangs",
            )
            work += 1
            work += self._spill_partitioned(cl)
        return work

    def _spill_partitioned(self, cl: FederatedCluster) -> int:
        """Move the partitioned region's placements whose PCS has NO
        Scheduled gang to the best surviving sibling. The stale copy
        cannot be deleted from the unreachable store — heal
        reconciliation does that — so remember each spilled key in
        ``_partition_spills``."""
        region = cl.region
        moved = 0
        victims = sorted(
            key for key, r in self._placements.items() if r == region
        )
        for key in victims:
            ns, pcs_name = key
            gangs = [
                g
                for g in cl.harness.store.list("PodGang")
                if g.metadata.labels.get(namegen.LABEL_PART_OF)
                == pcs_name
                and g.metadata.namespace == ns
            ]
            if any(
                (
                    c := get_condition(
                        g.status.conditions, COND_PODGANG_SCHEDULED
                    )
                )
                is not None
                and c.is_true()
                for g in gangs
            ):
                # already Scheduled inside the partition: it stays bound
                # there (F3) — the region keeps running it behind the
                # partition and nothing re-routes
                continue
            template, home = self._specs[key]
            ranked = self._rank_targets(
                pcs_floor_demand(template), exclude=region
            )
            if not ranked:
                continue  # stays pending behind the fence until heal
            _sortkey, target, inputs, _admits = ranked[0]
            self._clusters[target].harness.apply(deep_copy(template))
            self._placements[key] = target
            self._partition_spills.setdefault(region, []).append(key)
            self.partition_spills += 1
            METRICS.inc("federation_partition_spills_total")
            EVENTS.record(
                ("PodCliqueSet", ns, pcs_name),
                "Warning",
                REASON_GANG_SPILLED,
                f"partition-spilled {region} -> {target}"
                " (pending behind partition)",
            )
            self._record(
                "partition-spill",
                ns,
                pcs_name,
                home=home,
                to=target,
                score=dict(inputs),
                **{"from": region},
            )
            moved += 1
        return moved

    # -- spillover core --------------------------------------------------

    def _next_spill_deadline(
        self, ready: List[FederatedCluster]
    ) -> Optional[float]:
        """Earliest FUTURE instant a currently-pending gang crosses the
        ``spill_after`` age threshold (None when nothing is pending or
        everything eligible was already judged this tick — an
        already-eligible gang the spill pass declined stays declined
        until some other wake changes cluster state)."""
        now = self.clock.now()
        best: Optional[float] = None
        for cl in ready:
            for gang in self._pending_gangs(cl.harness):
                due = gang.metadata.creation_timestamp + self.spill_after
                if due > now and (best is None or due < best):
                    best = due
        return best

    def global_usage(self) -> Dict[str, Dict[str, float]]:
        """The level-3 fold's root: per-queue usage summed across every
        Ready cluster's accountant — the DRF ledger that makes a
        tenant's deserved share global."""
        partials: List[dict] = [{} for _ in range(self.fold.num_clusters)]
        for cl in self._clusters.values():
            if (
                cl.state == "Ready"
                and cl.reachable
                and cl.index < len(partials)
            ):
                partials[cl.index] = introspect.queue_usage(
                    cl.harness.scheduler
                )
        self.fold.refold(partials)
        return self.fold.root()

    def _pending_gangs(self, harness: SimHarness) -> List:
        out = []
        for gang in harness.store.list("PodGang"):
            cond = get_condition(
                gang.status.conditions, COND_PODGANG_SCHEDULED
            )
            if cond is None or not cond.is_true():
                out.append(gang)
        return out

    def _rank_targets(
        self,
        floor: Dict[str, float],
        exclude: Optional[str],
        spec: Optional[dict] = None,
    ) -> List[tuple]:
        """Candidate Ready clusters ranked best-first on the frontier-
        style score: (fragmentation delta, −headroom, region). When a
        solver ``spec`` is given, clusters whose read-only trial solve
        rejects it rank strictly behind every admitting cluster."""
        ranked = []
        for cl in self._ready():
            if cl.region == exclude:
                continue
            inputs = introspect.federation_score_inputs(
                cl.harness.scheduler, floor
            )
            admits = True
            if spec is not None:
                view = introspect.collect_pending(cl.harness.scheduler)
                res, _prob, err = introspect.solve_view_safe(
                    cl.harness.scheduler, view.nodes, view.free, [spec]
                )
                admits = bool(
                    err is None
                    and res is not None
                    and res.admitted[0]
                )
            ranked.append(
                (
                    (
                        0 if admits else 1,
                        inputs["frag_delta"],
                        -inputs["headroom"],
                        cl.region,
                    ),
                    cl.region,
                    inputs,
                    admits,
                )
            )
        ranked.sort(key=lambda row: row[0])
        return ranked

    def _spill_tick(self, ready: List[FederatedCluster]) -> int:
        """One spillover pass: walk the union pending frontier in global
        DRF order (cross-cluster fold as the usage ledger) and move at
        most ONE gang whose home explain verdict blocks local admission
        to its best admissible sibling. One move per tick keeps every
        collected view consistent and the decision ledger replayable."""
        now = self.clock.now()
        usage = self.global_usage()
        specs: List[dict] = []
        origin_of: Dict[Tuple[str, str], str] = {}
        crs = None
        order_sched = None
        for cl in ready:
            sched = cl.harness.scheduler
            if order_sched is None:
                order_sched = sched
                crs = sched.quota.queue_crs()
            view = introspect.collect_pending(sched)
            for spec in view.specs:
                specs.append(spec)
                origin_of[(spec["namespace"], spec["gang_name"])] = (
                    cl.region
                )
        if not specs:
            return 0
        ordered, _held = introspect.order_view(
            order_sched, specs, queue_crs=crs, usage=usage
        )
        for spec in ordered:
            ns, gname = spec["namespace"], spec["gang_name"]
            origin_region = origin_of.get((ns, gname))
            if origin_region is None:
                continue
            origin = self._clusters[origin_region]
            gang = origin.harness.store.get(
                "PodGang", ns, gname, readonly=True
            )
            if gang is None:
                continue
            if now - gang.metadata.creation_timestamp < self.spill_after:
                continue
            pcs_name = gang.metadata.labels.get(namegen.LABEL_PART_OF)
            if not pcs_name:
                continue
            key = (ns, pcs_name)
            if self._placements.get(key) != origin_region:
                continue  # already moved (zombie pending deletion)
            # the move is PCS-whole (data gravity: a workload's gangs
            # stay together) — only spill when nothing is placed yet
            siblings = [
                g
                for g in origin.harness.store.list("PodGang")
                if g.metadata.labels.get(namegen.LABEL_PART_OF)
                == pcs_name
                and g.metadata.namespace == ns
            ]
            if any(
                (
                    c := get_condition(
                        g.status.conditions, COND_PODGANG_SCHEDULED
                    )
                )
                is not None
                and c.is_true()
                for g in siblings
            ):
                continue
            verdict = origin.harness.explain.explain(ns, gname)
            if verdict is None or verdict.get("fits_now"):
                continue
            if verdict.get("state") != "pending":
                continue
            if verdict.get("detail") in _NO_SPILL_DETAILS:
                continue
            floor = introspect.spec_floor_demand(spec)
            ranked = self._rank_targets(
                floor, exclude=origin_region, spec=spec
            )
            ranked = [row for row in ranked if row[3]]  # admitting only
            if not ranked:
                continue
            _sortkey, target, inputs, _admits = ranked[0]
            template, home = self._specs[key]
            origin.harness.delete(pcs_name, ns)
            self._clusters[target].harness.apply(deep_copy(template))
            self._placements[key] = target
            self.spillovers += 1
            METRICS.inc("federation_spillovers_total")
            why = (
                f"home verdict {verdict.get('detail')}"
                f" ({verdict.get('binding_constraint')})"
            )
            EVENTS.record(
                ("PodGang", ns, gname),
                "Normal",
                REASON_GANG_SPILLED,
                f"spilled {origin_region} -> {target}: {why}",
            )
            self._record(
                "spill",
                ns,
                pcs_name,
                home=home,
                to=target,
                why=why,
                score=dict(inputs),
                home_verdict={
                    "fits_now": verdict.get("fits_now"),
                    "detail": verdict.get("detail"),
                    "binding_constraint": verdict.get(
                        "binding_constraint"
                    ),
                },
            )
            return 1
        return 0

    # -- region lifecycle ------------------------------------------------

    def crash_cluster(self, region: str) -> dict:
        """Kill a whole region mid-traffic: the harness (store, WAL
        buffer, controllers) is gone; every placement it held re-routes
        to surviving clusters through the scoring core and re-admits
        under the ordinary broker/budget machinery. Placements that find
        no Ready cluster are stranded (re-placeable via apply)."""
        cl = self._clusters.get(region)
        if cl is None or cl.state != "Ready":
            raise ValueError(
                f"federation: cannot crash {region!r} (not Ready)"
            )
        victims = sorted(
            key for key, r in self._placements.items() if r == region
        )
        cl.harness.engine.close()
        cl.harness = None
        cl.state = "Lost"
        cl.lost_at = self.clock.now()
        cl.crashes += 1
        METRICS.inc("federation_cluster_crashes_total")
        METRICS.set(
            "federation_clusters_ready", float(len(self._ready()))
        )
        EVENTS.record(
            ("Cluster", "", region),
            "Warning",
            REASON_CLUSTER_LOST,
            f"region {region} lost with {len(victims)} placements",
        )
        rerouted, stranded = [], []
        for key in victims:
            ns, name = key
            template, home = self._specs[key]
            ranked = self._rank_targets(
                pcs_floor_demand(template), exclude=region
            )
            if not ranked:
                del self._placements[key]
                stranded.append(key)
                self._record(
                    "strand", ns, name, home=home, **{"from": region}
                )
                continue
            _sortkey, target, inputs, _admits = ranked[0]
            self._clusters[target].harness.apply(deep_copy(template))
            self._placements[key] = target
            self.reroutes += 1
            METRICS.inc("federation_reroutes_total")
            EVENTS.record(
                ("PodCliqueSet", ns, name),
                "Warning",
                REASON_GANG_REQUEUED,
                f"re-routed {region} -> {target} (cluster lost)",
            )
            self._record(
                "reroute",
                ns,
                name,
                home=home,
                to=target,
                score=dict(inputs),
                **{"from": region},
            )
            rerouted.append(key)
        return {
            "region": region,
            "victims": [list(k) for k in victims],
            "rerouted": [list(k) for k in rerouted],
            "stranded": [list(k) for k in stranded],
        }

    def partition_cluster(self, region: str) -> FederatedCluster:
        """Cut the router's link to a Ready region. Unlike
        ``crash_cluster`` the harness stays ALIVE and keeps converging
        on the shared clock — only the router's view goes dark. Nothing
        moves yet: the suspicion timeout in ``_partition_suspect_tick``
        decides when (and what) to spill."""
        cl = self._clusters.get(region)
        if cl is None or cl.state != "Ready" or not cl.reachable:
            raise ValueError(
                f"federation: cannot partition {region!r}"
                " (not Ready/reachable)"
            )
        cl.reachable = False
        cl.unreachable_since = self.clock.now()
        METRICS.set(
            "federation_clusters_ready", float(len(self._ready()))
        )
        return cl

    def heal_cluster(self, region: str) -> dict:
        """Heal a partition: unfence admission, reconcile by deleting
        the stale copies of PCS keys the suspect pass spilled elsewhere
        (the one write the partition forbade), and return the region to
        routing. Spilled placements do NOT fail back — same no-fail-back
        rule as crash/rejoin — so each key ends Scheduled in exactly one
        cluster (F3)."""
        cl = self._clusters.get(region)
        if cl is None or cl.reachable or cl.harness is None:
            raise ValueError(
                f"federation: cannot heal {region!r} (not partitioned)"
            )
        stale = self._partition_spills.pop(region, [])
        for ns, pcs_name in stale:
            cl.harness.delete(pcs_name, ns)
        # tenant Queues applied while the region was dark never reached
        # it — re-apply the full set so the DRF trees agree again
        for queue in self._queues.values():
            cl.harness.apply_queue(deep_copy(queue))
        cl.reachable = True
        cl.unreachable_since = None
        cl.state = "Ready"
        cl.harness.scheduler.admission_fenced = False
        METRICS.set(
            "federation_clusters_ready", float(len(self._ready()))
        )
        EVENTS.record(
            ("Cluster", "", region),
            "Normal",
            REASON_CLUSTER_HEALED,
            f"region {region} healed; reconciled {len(stale)} stale"
            " spilled copies",
        )
        self._record(
            "heal", "", region, reconciled=[list(k) for k in stale]
        )
        return {
            "region": region,
            "reconciled": [list(k) for k in stale],
        }

    def rejoin_cluster(self, region: str) -> FederatedCluster:
        """Restore a Lost region with a FRESH harness on the shared
        clock (tenant Queues re-applied so the DRF trees agree again).
        No fail-back: placements stay where the crash re-routed them.
        The Ready flip is LAST — a spillover walk interleaved with this
        call must never route into a half-built region (the rejoin/spill
        race pin in tests/test_grayfail.py)."""
        cl = self._clusters.get(region)
        if cl is None or cl.state != "Lost":
            raise ValueError(
                f"federation: cannot rejoin {region!r} (not Lost)"
            )
        cl.harness = self._build_harness(region)
        self._install_context(cl)
        for queue in self._queues.values():
            cl.harness.apply_queue(deep_copy(queue))
        cl.state = "Ready"
        cl.lost_at = None
        METRICS.set(
            "federation_clusters_ready", float(len(self._ready()))
        )
        EVENTS.record(
            ("Cluster", "", region),
            "Normal",
            REASON_CLUSTER_REJOINED,
            f"region {region} rejoined with a fresh control plane",
        )
        self._record("rejoin", "", region)
        return cl

    # -- inspection ------------------------------------------------------

    def explain(self, namespace: str, name: str) -> Optional[dict]:
        """The federated explain verdict: find the cluster holding the
        gang and return ITS verdict (the funnel's opening stage already
        answers "which cluster and why"), annotated with the region."""
        for cl in self._ready():
            doc = cl.harness.explain.explain(namespace, name)
            if doc is not None:
                doc["cluster"] = cl.region
                return doc
        return None

    def status(self) -> dict:
        """``GET /federation`` / ``cli federation``: registry + ledger
        roll-up."""
        clusters = []
        for cl in self._clusters.values():
            row = {
                "region": cl.region,
                "state": cl.state,
                "phaseOffset": cl.phase_offset,
                "crashes": cl.crashes,
                "reachable": cl.reachable,
                "partitions": cl.partitions,
                "placements": sum(
                    1
                    for r in self._placements.values()
                    if r == cl.region
                ),
            }
            if cl.harness is not None:
                row["nodes"] = len(cl.harness.cluster.nodes)
                row["resourceVersion"] = getattr(
                    cl.harness.store, "resource_version", None
                )
                row["pendingGangs"] = len(
                    self._pending_gangs(cl.harness)
                )
            if cl.lost_at is not None:
                row["lostAt"] = cl.lost_at
            clusters.append(row)
        return {
            "kind": "FederationStatus",
            "clusters": clusters,
            "spillovers": self.spillovers,
            "reroutes": self.reroutes,
            "partitionSpills": self.partition_spills,
            "decisions": len(self._decisions),
            "foldDepthHistogram": self.fold.fold_depth_histogram(),
            "globalUsage": self.global_usage(),
        }


def federation_artifact(
    seed: int = 2026,
    regions: int = 3,
    num_nodes: int = 8,
    rounds: int = 3,
) -> dict:
    """The bench ``"federation"`` block's isolated scenario: seeded
    multi-region placement storm with one mid-run region crash +
    rejoin. Deterministic in (seed, shape) — the routing ledger length
    and counters are replayable."""
    import random
    import time as _time

    from grove_tpu.sim.chaos import chaos_workload

    t0 = _time.perf_counter()
    names = [f"r{i}" for i in range(regions)]
    router = FederationRouter(
        names,
        num_nodes=num_nodes,
        phase_offsets=[i * 200.0 for i in range(regions)],
        spill_after=5.0,
    )
    rng = random.Random(seed)
    applied = 0
    for rnd in range(rounds):
        for pcs in chaos_workload(n_each=1):
            pcs.metadata.name = f"{pcs.metadata.name}-{rnd}"
            pcs.metadata.labels[namegen.LABEL_FEDERATION_HOME] = (
                rng.choice(names)
            )
            router.apply(pcs)
            applied += 1
        router.converge(max_ticks=40)
        if rnd == rounds // 2 and regions > 1:
            crash = router.crash_cluster(names[0])
            router.converge(max_ticks=40)
            router.rejoin_cluster(names[0])
            router.converge(max_ticks=20)
    status = router.status()
    return {
        "seed": seed,
        "regions": regions,
        "nodes_per_region": num_nodes,
        "applied": applied,
        "spillovers": router.spillovers,
        "reroutes": router.reroutes,
        "decisions": len(router.decisions()),
        "fold_depth_histogram": status["foldDepthHistogram"],
        "crash": {
            "victims": len(crash["victims"]),
            "rerouted": len(crash["rerouted"]),
            "stranded": len(crash["stranded"]),
        }
        if regions > 1
        else None,
        "wall_s": round(_time.perf_counter() - t0, 3),
    }
