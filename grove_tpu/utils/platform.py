"""Subprocess environment for forced-CPU JAX children.

Single home for the sitecustomize workaround (this image's axon TPU plugin
pins the platform before user code runs — see tests/conftest.py): child
processes that must run on host CPU devices get a sanitized env from here.
"""

from __future__ import annotations

import os
from typing import Dict, Optional


# Diagnostics of the most recent probe_device_health call: verdict, the
# human-readable failure reason, and the child's output tail (the actual
# traceback when the accelerator plugin blew up). The probe has silently
# fallen back to CPU in every bench round so far — this record is what the
# bench's "backend" artifact block and the startup log surface instead of
# swallowing it.
_last_probe: Optional[dict] = None


def last_probe_detail() -> Optional[dict]:
    """Diagnostics of the most recent probe (None before any probe)."""
    return _last_probe


def check_platform_available(
    env: Optional[dict] = None, timeout_s: float = 20.0
) -> Optional[str]:
    """Fast-fail precheck: does every platform named by ``JAX_PLATFORMS``
    have a registered PJRT factory at all?

    Returns None when the pin is satisfiable (or nothing/cpu is pinned),
    else a human-readable reason. Runs ``import jax`` + plugin discovery in
    a subprocess — discovery mutates global registries and a pinned parent
    must stay pristine — but never INITIALIZES a backend, so it cannot
    wedge in device init the way the full probe can. A missing factory is a
    deterministic config error: retrying the 60-90s jit probe against it is
    how past bench rounds burned three timeout rounds on a platform that
    was never going to appear (the ``JAX_PLATFORMS=axon`` runs, BENCH_r01+).
    """
    import subprocess
    import sys

    want = [
        p.strip()
        for p in (env or os.environ).get("JAX_PLATFORMS", "").split(",")
        if p.strip()
    ]
    if not want or all(p == "cpu" for p in want):
        return None
    code = (
        "import os, sys\n"
        "import jax\n"
        "from jax._src import xla_bridge as xb\n"
        "try:\n"
        "    xb.discover_pjrt_plugins()\n"
        "except Exception as e:\n"
        "    print('DISCOVER-ERR', e)\n"
        "known = sorted(xb._backend_factories)\n"
        "want = [p.strip() for p in"
        " os.environ.get('JAX_PLATFORMS', '').split(',') if p.strip()]\n"
        "missing = [w for w in want if w not in known]\n"
        "print('KNOWN', ','.join(known))\n"
        "if missing:\n"
        "    print('MISSING', ','.join(missing))\n"
        "    sys.exit(3)\n"
        "print('OK')\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
        )
    except subprocess.TimeoutExpired:
        return None  # can't conclude — let the full probe decide
    if proc.returncode == 3:
        lines = dict(
            ln.split(" ", 1) for ln in proc.stdout.splitlines() if " " in ln
        )
        return (
            f"JAX_PLATFORMS names unavailable platform(s)"
            f" [{lines.get('MISSING', '?')}] — registered factories:"
            f" [{lines.get('KNOWN', '?')}]. A platform with no PJRT"
            " factory can never come up; fix the pin or the plugin"
            " install instead of retrying the probe."
        )
    return None  # factory exists (or check itself broke) — full probe decides


def probe_device_health(
    timeout_s: float = 60.0,
    env: Optional[dict] = None,
    require_accelerator: bool = False,
    precheck: bool = True,
) -> bool:
    """Run a trivial jit in a detached subprocess; on timeout the child is
    killed and ABANDONED (a child wedged in uninterruptible device sleep
    ignores SIGKILL — blocking on its exit would hang the caller, the exact
    condition the probe exists to detect).

    `env`: environment for the child. Callers probing "is the ACCELERATOR
    back?" after force_cpu_platform() MUST pass the pre-scrub environment —
    the child inherits os.environ by default, and a scrubbed parent would
    make the probe vacuously test CPU (the bug behind round 3's phantom
    'chip wake windows'). `require_accelerator` additionally rejects a
    successful probe whose default backend is cpu.

    Every call records its verdict + failure reason + the child's output
    tail (its traceback) in :func:`last_probe_detail`; the record carries
    ``retryable`` — False for deterministic config errors (an unregistered
    platform, an unknown backend) where re-probing can never help, so
    callers with retry loops (ensure_healthy_backend, the bench ProbeLog)
    fast-fail instead of burning their remaining timeout rounds."""
    import pathlib
    import subprocess
    import sys
    import tempfile
    import time

    global _last_probe

    def _record(
        ok: bool, reason: str, output: str = "", retryable: bool = True
    ) -> bool:
        global _last_probe
        tail = output.strip()
        if len(tail) > 2000:
            tail = "...(truncated)...\n" + tail[-2000:]
        _last_probe = {
            "ok": ok,
            "reason": reason,
            "output_tail": tail,
            "require_accelerator": require_accelerator,
            "retryable": retryable,
        }
        return ok

    if precheck:
        unavailable = check_platform_available(env)
        if unavailable is not None:
            return _record(False, unavailable, retryable=False)

    out = tempfile.NamedTemporaryFile(mode="w+", delete=False)
    out_path = out.name
    proc = subprocess.Popen(
        [
            sys.executable,
            "-c",
            "import jax, jax.numpy as jnp;"
            "x = jax.jit(lambda a: (a @ a).sum())(jnp.ones((128, 128)));"
            "jax.block_until_ready(x); print('OK', jax.default_backend())",
        ],
        stdout=out,
        stderr=subprocess.STDOUT,
        cwd=pathlib.Path(__file__).resolve().parents[2],
        start_new_session=True,
        env=env,
    )
    try:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if proc.poll() is not None:
                break
            time.sleep(0.5)
        else:
            proc.kill()
            # abandoned child may still hold the temp file; read what it
            # managed to write — a wedged init usually logged WHERE first
            partial = ""
            try:
                out.seek(0)
                partial = out.read()
            except OSError:
                pass
            return _record(
                False,
                f"probe child hung past {timeout_s:.0f}s (killed and"
                " abandoned — accelerator wedged in device init?)",
                partial,
            )
        out.seek(0)
        text = out.read()
        if proc.returncode != 0 or "OK" not in text:
            return _record(
                False,
                f"probe child exited rc={proc.returncode} without OK"
                " (backend crashed during import/jit — see output_tail"
                " for the traceback)",
                text,
                # "Unknown backend" is jax rejecting the JAX_PLATFORMS pin
                # itself — deterministic, retries can never succeed
                retryable="Unknown backend" not in text,
            )
        if require_accelerator and "OK cpu" in text:
            return _record(
                False,
                "probe succeeded but on the CPU backend while an"
                " accelerator was configured (plugin failed to register"
                " its devices — see output_tail)",
                text,
            )
        return _record(True, "", text)
    finally:
        out.close()
        if proc.poll() is not None:  # only unlink when the child is gone
            try:
                os.unlink(out_path)
            except OSError:
                pass


def force_cpu_platform() -> None:
    """Re-pin this process onto host CPU. The env var alone is NOT enough on
    images whose sitecustomize registers an accelerator plugin at interpreter
    start — the platform must be re-pinned via jax.config after import."""
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


_backend_note: Optional[str] = None


def ensure_healthy_backend(
    timeout_s: float = 60.0, retries: int = 1, retry_wait_s: float = 0.0
) -> str:
    """Probe the default accelerator; fall back to CPU when wedged.
    Memoized per process (one subprocess probe). Returns a backend note.

    `retries`/`retry_wait_s`: a remote chip behind a tunnel can be
    transiently unavailable — probe up to `retries` times, sleeping between
    attempts, before giving up on it (bench uses this so a short outage
    doesn't condemn the whole artifact to the CPU-fallback path)."""
    global _backend_note
    if _backend_note is None:
        import sys
        import time as _time

        # already initialized on CPU in this process (e.g. the test
        # harness pinned it): nothing to probe
        if "jax" in sys.modules:
            import jax

            if jax.config.jax_platforms == "cpu":
                _backend_note = "default"
                return _backend_note
        ok = False
        for attempt in range(max(retries, 1)):
            if attempt and retry_wait_s:
                _time.sleep(retry_wait_s)
            if probe_device_health(timeout_s):
                ok = True
                break
            detail = last_probe_detail() or {}
            if not detail.get("retryable", True):
                # deterministic config error (unavailable platform):
                # further timeout rounds can never succeed — fast-fail
                break
        if ok:
            _backend_note = "default"
        else:
            force_cpu_platform()
            _backend_note = "cpu-fallback (accelerator probe failed)"
            # surface WHY at startup instead of swallowing it: the probe
            # fell back silently in every bench round before this
            detail = last_probe_detail() or {}
            print(
                "WARNING: accelerator probe failed — falling back to CPU."
                f" Reason: {detail.get('reason', 'unknown')}",
                file=sys.stderr,
            )
            if detail.get("output_tail"):
                print(
                    "probe child output tail:\n" + detail["output_tail"],
                    file=sys.stderr,
                )
    return _backend_note


def host_machine_fingerprint() -> str:
    """Stable fingerprint of the host's CPU feature set.

    XLA:CPU bakes the compiling machine's features into the executable; the
    persistent compile cache will happily hand that executable to a host with
    a *different* feature set ("Compile machine features ... vs host machine
    features ... could lead to execution errors such as SIGILL"). Partitioning
    the cache by this fingerprint makes such cross-host reuse impossible.
    """
    import hashlib

    flags = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags") or line.startswith("Features"):
                    # one physical CPU model per host: the first flags line
                    # is the whole feature story
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    if not flags:
        import platform as _platform

        flags = f"{_platform.machine()}|{_platform.processor()}"
    return hashlib.md5(flags.encode()).hexdigest()[:8]


def enable_compile_cache(path: Optional[str] = None) -> str:
    """Point JAX's persistent compilation cache at a writable directory so
    repeat processes skip the multi-minute XLA compile of the full-size wave
    program (the executable is keyed by HLO + compile options + backend, so
    a stale cache can never produce wrong results — only a miss).

    Must run before the first compilation in the process; safe to call any
    time after `import jax` (config updates apply to subsequent compiles).
    """
    import jax

    if path is None:
        # partition by (platform pin, XLA flags, host machine features):
        # executables AOT-compiled under one config can load under another
        # with machine-feature warnings and a SIGILL risk (e.g. the
        # virtual-8-device test config vs a plain CPU process, or two hosts
        # with different AVX/AMX sets sharing a cache volume) — never share
        # cache entries across configs or machine types
        import hashlib

        config_token = hashlib.md5(
            (
                os.environ.get("JAX_PLATFORMS", "auto")
                + "|"
                + os.environ.get("XLA_FLAGS", "")
                + "|"
                + host_machine_fingerprint()
            ).encode()
        ).hexdigest()[:8]
        # GROVE_TPU_COMPILE_CACHE names the cache ROOT; the per-config
        # partition applies underneath it too, so a shared CI cache dir can
        # still never mix configs
        root = os.environ.get(
            "GROVE_TPU_COMPILE_CACHE",
            os.path.join(
                os.environ.get(
                    "XDG_CACHE_HOME", os.path.expanduser("~/.cache")
                ),
                "grove_tpu",
            ),
        )
        path = os.path.join(root, f"jax_cache-{config_token}")
    cache = path
    os.makedirs(cache, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache)
    # default min compile time is 1s; the wave program is minutes, but cache
    # the mid-size test shapes too
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return cache


def cpu_subprocess_env(n_devices: Optional[int] = None) -> Dict[str, str]:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # disables the axon sitecustomize pin
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is None:
        env["XLA_FLAGS"] = ""  # exactly one device
    else:
        env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    return env
