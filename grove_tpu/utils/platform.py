"""Subprocess environment for forced-CPU JAX children.

Single home for the sitecustomize workaround (this image's axon TPU plugin
pins the platform before user code runs — see tests/conftest.py): child
processes that must run on host CPU devices get a sanitized env from here.
"""

from __future__ import annotations

import os
from typing import Dict, Optional


def cpu_subprocess_env(n_devices: Optional[int] = None) -> Dict[str, str]:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # disables the axon sitecustomize pin
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is None:
        env["XLA_FLAGS"] = ""  # exactly one device
    else:
        env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    return env
