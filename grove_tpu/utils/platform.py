"""Subprocess environment for forced-CPU JAX children.

Single home for the sitecustomize workaround (this image's axon TPU plugin
pins the platform before user code runs — see tests/conftest.py): child
processes that must run on host CPU devices get a sanitized env from here.
"""

from __future__ import annotations

import os
from typing import Dict, Optional


def probe_device_health(timeout_s: float = 60.0) -> bool:
    """Run a trivial jit in a detached subprocess; on timeout the child is
    killed and ABANDONED (a child wedged in uninterruptible device sleep
    ignores SIGKILL — blocking on its exit would hang the caller, the exact
    condition the probe exists to detect)."""
    import pathlib
    import subprocess
    import sys
    import tempfile
    import time

    out = tempfile.NamedTemporaryFile(mode="w+", delete=False)
    proc = subprocess.Popen(
        [
            sys.executable,
            "-c",
            "import jax, jax.numpy as jnp;"
            "x = jax.jit(lambda a: (a @ a).sum())(jnp.ones((128, 128)));"
            "jax.block_until_ready(x); print('OK', jax.default_backend())",
        ],
        stdout=out,
        stderr=subprocess.STDOUT,
        cwd=pathlib.Path(__file__).resolve().parents[2],
        start_new_session=True,
    )
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if proc.poll() is not None:
            break
        time.sleep(0.5)
    else:
        proc.kill()
        return False
    out.seek(0)
    return proc.returncode == 0 and "OK" in out.read()


def force_cpu_platform() -> None:
    """Re-pin this process onto host CPU. The env var alone is NOT enough on
    images whose sitecustomize registers an accelerator plugin at interpreter
    start — the platform must be re-pinned via jax.config after import."""
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def ensure_healthy_backend(timeout_s: float = 60.0) -> str:
    """Probe the default accelerator; fall back to CPU when wedged.
    Returns a human-readable backend note."""
    if probe_device_health(timeout_s):
        return "default"
    force_cpu_platform()
    return "cpu-fallback (accelerator probe failed)"


def cpu_subprocess_env(n_devices: Optional[int] = None) -> Dict[str, str]:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # disables the axon sitecustomize pin
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is None:
        env["XLA_FLAGS"] = ""  # exactly one device
    else:
        env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    return env
