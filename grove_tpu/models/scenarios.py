"""Scenario builders for the BASELINE acceptance configs.

The sample manifests are the user-facing form of the first four scenarios
(samples/*.yaml — reference-format CRs); `load_sample` parses them into
domain objects for tests/sims. `stress_gang_specs`/`build_stress_problem`
produce the synthetic 10k-gang x 5k-node solver input that bench.py times
(BASELINE.json north star); bench and tests share this single generator so
a shape change can't silently fork the benchmark from the test suite.
"""

from __future__ import annotations

import pathlib
from typing import List

import numpy as np

# canonical sample manifests ship INSIDE the package (pip-installed copies
# must work without a repo checkout); the repo-root samples/ directory is
# the user-facing mirror, drift-tested in tests/test_models.py
SAMPLES_DIR = pathlib.Path(__file__).resolve().parent / "samples"

# BASELINE.json acceptance configs (minus the stress sim, which is synthetic)
BASELINE_SAMPLES = {
    "simple": "simple1.yaml",
    "disaggregated": "single-node-disaggregated.yaml",
    "multinode_disaggregated": "multinode-disaggregated.yaml",
    "agentic": "agentic-pipeline.yaml",
}


def load_sample(name: str):
    """Scenario name (or bare filename) → PodCliqueSet domain object."""
    from grove_tpu.api.load import load_podcliqueset_file

    filename = BASELINE_SAMPLES.get(name, name)
    return load_podcliqueset_file(str(SAMPLES_DIR / filename))


def stress_gang_specs(n_gangs: int, seed: int = 0) -> List[dict]:
    """Headline stress mix: mostly small single-group gangs (the cluster can
    hold them all), a tail of multi-group disaggregated-style gangs carrying
    slice-level pack hints."""
    rng = np.random.default_rng(seed)
    gangs = []
    for i in range(n_gangs):
        if i % 8 == 0:
            n_groups = int(rng.integers(2, 4))
            groups = [
                {
                    "name": f"g{i}-{p}",
                    "demand": {
                        "tpu": float(rng.integers(1, 3)),
                        "cpu": float(rng.integers(1, 9)),
                    },
                    "count": int(rng.integers(1, 5)),
                    "min_count": None,
                }
                for p in range(n_groups)
            ]
            required = "cloud.google.com/gke-tpu-slice"
        else:
            groups = [
                {
                    "name": f"g{i}-0",
                    "demand": {"tpu": 1.0, "cpu": 2.0},
                    "count": int(rng.integers(2, 5)),
                    "min_count": None,
                }
            ]
            required = None
        for g in groups:
            g["min_count"] = g["count"]
        gangs.append(
            {
                "name": f"g{i}",
                "groups": groups,
                "required_key": required,
                "preferred_key": None,
                "priority": 0,
            }
        )
    return gangs


def build_stress_problem(
    n_nodes: int,
    n_gangs: int,
    seed: int = 0,
    hosts_per_ici_block: int = 8,
    blocks_per_slice: int = 8,
):
    """The BASELINE.json stress sim input: n_gangs onto an n_nodes cluster
    (5120 nodes x 8 TPU chips = 40k chips at full scale)."""
    from grove_tpu.api.topology import ClusterTopology
    from grove_tpu.sim.cluster import make_nodes
    from grove_tpu.solver.encode import build_problem

    nodes = make_nodes(
        n_nodes,
        capacity={"cpu": 128.0, "tpu": 8.0},
        hosts_per_ici_block=hosts_per_ici_block,
        blocks_per_slice=blocks_per_slice,
    )
    return build_problem(
        nodes, stress_gang_specs(n_gangs, seed), ClusterTopology()
    )
