"""Workload scenario models: the BASELINE.json acceptance shapes as
reusable builders.

Consumed by tests and bench.py (which previously inlined its own stress
generator). Four sample families plus the synthetic stress generator mirror
the acceptance configs in BASELINE.json:

- ``simple``                  the quickstart shape (samples/simple1.yaml)
- ``disaggregated``           single-node prefill/decode split
- ``multinode_disaggregated`` multi-node instance with slice-packing hints
- ``agentic``                 pipeline with explicit startup ordering
- ``stress_problem``          the 10k-gang x 5k-node synthetic solver input
"""

from grove_tpu.models.scenarios import (
    BASELINE_SAMPLES,
    build_stress_problem,
    load_sample,
    stress_gang_specs,
)

__all__ = [
    "BASELINE_SAMPLES",
    "build_stress_problem",
    "load_sample",
    "stress_gang_specs",
]
