"""Defaulting: webhook-equivalent pure functions.

Rule-for-rule re-host of
/root/reference/operator/internal/webhook/admission/pcs/defaulting/podcliqueset.go:35-120
(plus the kubebuilder schema defaults the apiserver applies before the webhook:
startupType=AnyOrder, PCSG replicas=1, PCSG minAvailable=1).
"""

from __future__ import annotations

from grove_tpu.api.types import (
    DEFAULT_TERMINATION_DELAY_SECONDS,
    QUEUE_ROOT,
    SPREAD_DO_NOT_SCHEDULE,
    STARTUP_ANY_ORDER,
    HeadlessServiceConfig,
    PodCliqueSet,
    Queue,
)

DEFAULT_TERMINATION_GRACE_PERIOD = 30


def default_podcliqueset(pcs: PodCliqueSet) -> PodCliqueSet:
    """Mutates `pcs` in place (callers hold the only copy pre-store) and
    returns it."""
    if not pcs.metadata.namespace:
        pcs.metadata.namespace = "default"
    tmpl = pcs.spec.template

    # kubebuilder default — podcliqueset.go:128
    if tmpl.startup_type is None:
        tmpl.startup_type = STARTUP_ANY_ORDER
    # defaulting/podcliqueset.go:52-54 (4h)
    if tmpl.termination_delay is None:
        tmpl.termination_delay = DEFAULT_TERMINATION_DELAY_SECONDS
    # defaulting/podcliqueset.go:59-66
    if tmpl.headless_service_config is None:
        tmpl.headless_service_config = HeadlessServiceConfig(
            publish_not_ready_addresses=True
        )

    for clique in tmpl.cliques:
        spec = clique.spec
        if spec.replicas == 0:
            spec.replicas = 1
        if spec.min_available is None:
            spec.min_available = spec.replicas
        if spec.auto_scaling_config is not None:
            if spec.auto_scaling_config.min_replicas is None:
                spec.auto_scaling_config.min_replicas = spec.replicas
        pod_spec = spec.pod_spec
        if not pod_spec.restart_policy:
            pod_spec.restart_policy = "Always"
        pod_spec.extra.setdefault(
            "terminationGracePeriodSeconds", DEFAULT_TERMINATION_GRACE_PERIOD
        )

    # disruption budget defaults (grove-tpu extension — see
    # api/types.py DisruptionBudget): a budget block without an explicit
    # cap means "one gang at a time", the PDB-ish conservative default
    if tmpl.disruption_budget is not None:
        if tmpl.disruption_budget.max_unavailable_gangs is None:
            tmpl.disruption_budget.max_unavailable_gangs = 1

    # spread constraint defaults (grove-tpu extension — see
    # api/types.py TopologyConstraint)
    tc = tmpl.topology_constraint
    if tc is not None and tc.spread_domain is not None:
        if tc.spread_min_domains is None:
            tc.spread_min_domains = 2
        if tc.spread_when_unsatisfiable is None:
            tc.spread_when_unsatisfiable = SPREAD_DO_NOT_SCHEDULE

    for sg in tmpl.pod_clique_scaling_group_configs:
        # kubebuilder defaults — podcliqueset.go:211, :224
        if sg.replicas is None:
            sg.replicas = 1
        if sg.min_available is None:
            sg.min_available = 1
        if sg.scale_config is not None and sg.scale_config.min_replicas is None:
            sg.scale_config.min_replicas = sg.replicas

    return pcs


def default_queue(q: Queue) -> Queue:
    """Queue defaulting (quota subsystem, docs/quota.md): cluster-scoped,
    parent anchored at the implicit root (two-level tree)."""
    q.metadata.namespace = ""
    if not q.spec.parent:
        q.spec.parent = QUEUE_ROOT
    return q
