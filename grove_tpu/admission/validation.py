"""Validation: webhook-equivalent pure functions.

Rule-for-rule re-host of
/root/reference/operator/internal/webhook/admission/pcs/validation/podcliqueset.go:59-530
(create + update paths) and validation/podcliquedeps.go:24-110 (startup-DAG
cycle detection via Tarjan SCC), plus ClusterTopology validation
(webhook/admission/clustertopology/validation/clustertopology.go).

Validation runs on the *defaulted* object (the reference orders webhooks the
same way: defaulting, then validation).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from grove_tpu.api import names as namegen
from grove_tpu.api.topology import TOPOLOGY_DOMAIN_ORDER, ClusterTopology, broader_than
from grove_tpu.api.types import (
    STARTUP_EXPLICIT,
    STARTUP_IN_ORDER,
    STARTUP_TYPES,
    PodCliqueSet,
)

_DNS1123_RE = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
# Pod hostnames are DNS labels: the worst-case generated pod name must fit.
MAX_HOSTNAME_LEN = 63


@dataclass
class ValidationResult:
    errors: List[str] = field(default_factory=list)
    warnings: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.errors

    def error(self, path: str, msg: str) -> None:
        self.errors.append(f"{path}: {msg}")

    def warn(self, msg: str) -> None:
        self.warnings.append(msg)


class ValidationError(Exception):
    def __init__(self, result: ValidationResult):
        self.result = result
        super().__init__("; ".join(result.errors))


# ---------------------------------------------------------------------------
# Dependency graph + Tarjan SCC (podcliquedeps.go)
# ---------------------------------------------------------------------------


class PodCliqueDependencyGraph:
    """startsAfter DAG; an SCC with >1 node (or a self-loop) is a cycle."""

    def __init__(self) -> None:
        self.adjacency: Dict[str, List[str]] = {}

    def add_dependencies(self, frm: str, to: List[str]) -> None:
        self.adjacency.setdefault(frm, []).extend(to)

    def unknown_cliques(self, discovered: List[str]) -> List[str]:
        known = set(discovered)
        out = []
        for deps in self.adjacency.values():
            out.extend(d for d in deps if d not in known)
        return out

    def strongly_connected_cliques(self) -> List[List[str]]:
        """Tarjan's SCC; single-node components only count with a self-loop
        (reference NOTE at podcliquedeps.go:55-57 excludes trivial SCCs)."""
        index_counter = [0]
        indices: Dict[str, int] = {}
        lowlink: Dict[str, int] = {}
        on_stack: Dict[str, bool] = {}
        stack: List[str] = []
        sccs: List[List[str]] = []

        def strong_connect(v: str) -> None:
            indices[v] = lowlink[v] = index_counter[0]
            index_counter[0] += 1
            stack.append(v)
            on_stack[v] = True
            for w in self.adjacency.get(v, []):
                if w not in indices:
                    strong_connect(w)
                    lowlink[v] = min(lowlink[v], lowlink[w])
                elif on_stack.get(w):
                    lowlink[v] = min(lowlink[v], indices[w])
            if lowlink[v] == indices[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1 or v in self.adjacency.get(v, []):
                    sccs.append(sorted(comp))

        for node in list(self.adjacency):
            if node not in indices:
                strong_connect(node)
        return sccs


# ---------------------------------------------------------------------------
# Create-path validation
# ---------------------------------------------------------------------------


def validate_podcliqueset(
    pcs: PodCliqueSet,
    topology: Optional[ClusterTopology] = None,
    is_update: bool = False,
) -> ValidationResult:
    res = ValidationResult()
    _validate_object_meta(pcs, res)
    _validate_spec(pcs, res, topology, is_update)
    return res


def validate_or_raise(
    pcs: PodCliqueSet, topology: Optional[ClusterTopology] = None
) -> ValidationResult:
    res = validate_podcliqueset(pcs, topology)
    if not res.ok:
        raise ValidationError(res)
    return res


def _validate_object_meta(pcs: PodCliqueSet, res: ValidationResult) -> None:
    name = pcs.metadata.name
    if not name:
        res.error("metadata.name", "name is required")
        return
    if not _DNS1123_RE.match(name):
        res.error("metadata.name", f"{name!r} must be a valid DNS-1123 label")


def _worst_case_pod_name_len(pcs: PodCliqueSet) -> Tuple[int, str]:
    """Longest generated pod hostname across cliques/groups at max replicas
    (the reference enforces generated-name budgets in
    validatePodCliqueNameConstraints / validateScalingGroupPodCliqueNames)."""
    worst, worst_name = 0, ""
    tmpl = pcs.spec.template
    max_pcs_rep = max(pcs.spec.replicas, 1)
    for clique in tmpl.standalone_clique_templates():
        max_pod = max(
            clique.spec.replicas,
            clique.spec.auto_scaling_config.max_replicas
            if clique.spec.auto_scaling_config
            else 0,
        )
        pclq = namegen.podclique_name(pcs.metadata.name, max_pcs_rep - 1, clique.name)
        pod = namegen.pod_name(pclq, max(max_pod - 1, 0))
        if len(pod) > worst:
            worst, worst_name = len(pod), pod
    for sg in tmpl.pod_clique_scaling_group_configs:
        max_sg_rep = max(
            sg.replicas or 1,
            sg.scale_config.max_replicas if sg.scale_config else 0,
        )
        for cname in sg.clique_names:
            clique = tmpl.clique_template(cname)
            if clique is None:
                continue
            pcsg_fqn = namegen.pcsg_name(pcs.metadata.name, max_pcs_rep - 1, sg.name)
            pclq = namegen.podclique_name(pcsg_fqn, max_sg_rep - 1, cname)
            pod = namegen.pod_name(pclq, max(clique.spec.replicas - 1, 0))
            if len(pod) > worst:
                worst, worst_name = len(pod), pod
    return worst, worst_name


def _validate_spec(
    pcs: PodCliqueSet,
    res: ValidationResult,
    topology: Optional[ClusterTopology],
    is_update: bool = False,
) -> None:
    spec = pcs.spec
    tmpl = spec.template
    if spec.replicas < 0:
        res.error("spec.replicas", "must be non-negative")

    if tmpl.startup_type not in STARTUP_TYPES:
        res.error(
            "spec.template.cliqueStartupType",
            f"unsupported value {tmpl.startup_type!r}; must be one of {STARTUP_TYPES}",
        )

    if tmpl.termination_delay is None:
        res.error("spec.template.terminationDelay", "field is required")
    elif tmpl.termination_delay <= 0:
        res.error(
            "spec.template.terminationDelay", "terminationDelay must be greater than 0"
        )

    # --- disruption budget (docs/robustness.md voluntary disruption) ----
    db = tmpl.disruption_budget
    if db is not None:
        if db.max_unavailable_gangs is None:
            res.error(
                "spec.template.disruptionBudget.maxUnavailableGangs",
                "field is required",
            )
        elif db.max_unavailable_gangs < 0:
            res.error(
                "spec.template.disruptionBudget.maxUnavailableGangs",
                "must be non-negative (0 blocks all voluntary disruption)",
            )
        elif db.max_unavailable_gangs == 0:
            res.warn(
                "disruptionBudget.maxUnavailableGangs=0 blocks every"
                " voluntary disruption, including rolling updates and"
                " node drains, until the budget is raised"
            )
        if db.quiet_window is not None and db.quiet_window < 0:
            res.error(
                "spec.template.disruptionBudget.quietWindow",
                "must be non-negative",
            )

    # --- cliques --------------------------------------------------------
    if not tmpl.cliques:
        res.error("spec.template.cliques", "at least one PodClique must be defined")
        return

    clique_names = [c.name for c in tmpl.cliques]
    _unique(clique_names, "spec.template.cliques.name", "clique names must be unique", res)
    role_names = [c.spec.role_name for c in tmpl.cliques if c.spec.role_name]
    _unique(
        role_names, "spec.template.cliques.roleName", "clique roleNames must be unique", res
    )

    scheduler_names = {
        c.spec.pod_spec.scheduler_name or "default-scheduler" for c in tmpl.cliques
    }
    if len(scheduler_names) > 1:
        res.error(
            "spec.template.cliques.spec.podSpec.schedulerName",
            "the schedulerName for all pods have to be the same",
        )

    sg_member_names = {
        n for sg in tmpl.pod_clique_scaling_group_configs for n in sg.clique_names
    }
    # A member clique's effective parent constraint is its scaling group's
    # (falling back to the PCS template's when the group has none).
    parent_tc_by_clique = {}
    for sg in tmpl.pod_clique_scaling_group_configs:
        for n in sg.clique_names:
            parent_tc_by_clique[n] = sg.topology_constraint or tmpl.topology_constraint

    explicit = tmpl.startup_type == STARTUP_EXPLICIT
    for i, clique in enumerate(tmpl.cliques):
        path = f"spec.template.cliques[{i}]"
        if not clique.name:
            res.error(f"{path}.name", "name is required")
        elif not _DNS1123_RE.match(clique.name):
            res.error(f"{path}.name", f"{clique.name!r} must be a valid DNS-1123 label")
        cs = clique.spec
        if cs.replicas <= 0:
            res.error(f"{path}.spec.replicas", "must be greater than 0")
        if cs.min_available is None:
            res.error(f"{path}.spec.minAvailable", "field is required")
        else:
            if cs.min_available <= 0:
                res.error(f"{path}.spec.minAvailable", "must be greater than 0")
            if cs.min_available > cs.replicas:
                res.error(
                    f"{path}.spec.minAvailable",
                    "minAvailable must not be greater than replicas",
                )
        if explicit and cs.starts_after:
            for dep in cs.starts_after:
                if not dep:
                    res.error(
                        f"{path}.spec.startsAfter", "clique dependency must not be empty"
                    )
                if dep == clique.name:
                    res.error(
                        f"{path}.spec.startsAfter",
                        "clique dependency cannot refer to itself",
                    )
            _unique(
                cs.starts_after,
                f"{path}.spec.startsAfter",
                "clique dependencies must be unique",
                res,
            )
        if cs.auto_scaling_config is not None:
            if clique.name in sg_member_names:
                res.error(
                    f"{path}.spec.autoScalingConfig",
                    "AutoScalingConfig is not allowed for a PodClique that is part of"
                    " a scaling group",
                )
            _validate_scale_config(
                cs.auto_scaling_config,
                cs.min_available or 0,
                f"{path}.spec.autoScalingConfig",
                res,
            )
            if cs.auto_scaling_config.max_replicas < cs.replicas:
                res.error(
                    f"{path}.spec.autoScalingConfig.maxReplicas",
                    "must be greater than or equal to replicas",
                )
        _validate_pod_spec(cs.pod_spec, f"{path}.spec.podSpec", res, is_update)
        if clique.topology_constraint is not None:
            _validate_topology_constraint(
                clique.topology_constraint,
                parent_tc_by_clique.get(clique.name, tmpl.topology_constraint),
                f"{path}.topologyConstraint",
                topology,
                res,
            )

    # --- scaling groups -------------------------------------------------
    sg_names = [sg.name for sg in tmpl.pod_clique_scaling_group_configs]
    _unique(
        sg_names,
        "spec.template.podCliqueScalingGroups.name",
        "PodCliqueScalingGroupConfig names must be unique",
        res,
    )
    all_sg_cliques: List[str] = []
    for j, sg in enumerate(tmpl.pod_clique_scaling_group_configs):
        path = f"spec.template.podCliqueScalingGroups[{j}]"
        if not sg.name:
            res.error(f"{path}.name", "name is required")
        elif not _DNS1123_RE.match(sg.name):
            res.error(f"{path}.name", f"{sg.name!r} must be a valid DNS-1123 label")
        unknown = [n for n in sg.clique_names if n not in clique_names]
        if unknown:
            res.error(
                f"{path}.cliqueNames", f"unidentified PodClique names found: {unknown}"
            )
        all_sg_cliques.extend(sg.clique_names)
        if sg.replicas is not None and sg.replicas <= 0:
            res.error(f"{path}.replicas", "must be greater than 0")
        if sg.min_available is not None:
            if sg.min_available <= 0:
                res.error(f"{path}.minAvailable", "must be greater than 0")
            if sg.replicas is not None and sg.min_available > sg.replicas:
                res.error(
                    f"{path}.minAvailable", "minAvailable must not be greater than replicas"
                )
        if sg.scale_config is not None:
            _validate_scale_config(
                sg.scale_config, sg.min_available or 0, f"{path}.scaleConfig", res
            )
        if sg.topology_constraint is not None:
            _validate_topology_constraint(
                sg.topology_constraint,
                tmpl.topology_constraint,
                f"{path}.topologyConstraint",
                topology,
                res,
            )
    _unique(
        all_sg_cliques,
        "spec.template.podCliqueScalingGroups.cliqueNames",
        "clique names must not overlap across scaling groups",
        res,
    )

    # --- startup DAG (Explicit only — podcliqueset.go:143-145; InOrder
    # derives the chain from declaration order and ignores startsAfter) -----
    if tmpl.startup_type == STARTUP_EXPLICIT:
        graph = PodCliqueDependencyGraph()
        for clique in tmpl.cliques:
            graph.add_dependencies(clique.name, list(clique.spec.starts_after))
        unknown = graph.unknown_cliques(clique_names)
        if unknown:
            res.error(
                "spec.template.cliques.startsAfter",
                f"dependencies refer to unknown cliques: {sorted(set(unknown))}",
            )
        cycles = graph.strongly_connected_cliques()
        if cycles:
            res.error(
                "spec.template.cliques",
                f"clique must not have circular dependencies: {cycles}",
            )

    # --- PCS-level topology constraint ---------------------------------
    if tmpl.topology_constraint is not None:
        _validate_topology_constraint(
            tmpl.topology_constraint,
            None,
            "spec.template.topologyConstraint",
            topology,
            res,
            allow_spread=True,
        )
        # gang-level spread and per-group (clique/PCSG) packs are mutually
        # exclusive: the balanced spread fill places the whole gang, so a
        # narrower per-group pack could not be honored at the same time
        if tmpl.topology_constraint.spread_domain is not None:
            offenders = [
                f"clique {c.name!r}"
                for c in tmpl.cliques
                if c.topology_constraint is not None
                and c.topology_constraint.pack_domain is not None
            ] + [
                f"scalingGroup {sg.name!r}"
                for sg in tmpl.pod_clique_scaling_group_configs
                if sg.topology_constraint is not None
                and sg.topology_constraint.pack_domain is not None
            ]
            if offenders:
                res.error(
                    "spec.template.topologyConstraint.spreadDomain",
                    "cannot be combined with per-clique or per-scaling-group"
                    f" packDomain constraints ({', '.join(offenders)})",
                )

    # --- generated-name budget ------------------------------------------
    worst, worst_name = _worst_case_pod_name_len(pcs)
    if worst > MAX_HOSTNAME_LEN:
        res.error(
            "metadata.name",
            f"generated pod hostname {worst_name!r} ({worst} chars) exceeds"
            f" {MAX_HOSTNAME_LEN}; shorten the PodCliqueSet/clique/group names",
        )


def _validate_scale_config(sc, min_available: int, path: str, res: ValidationResult) -> None:
    if sc.min_replicas is None:
        res.error(f"{path}.minReplicas", "field is required")
        return
    if sc.min_replicas < min_available:
        res.error(
            f"{path}.minReplicas",
            "must be greater than or equal to minAvailable",
        )
    if sc.max_replicas < sc.min_replicas:
        res.error(
            f"{path}.maxReplicas", "must be greater than or equal to minReplicas"
        )


def _validate_pod_spec(
    pod_spec, path: str, res: ValidationResult, is_update: bool = False
) -> None:
    if not pod_spec.containers:
        res.error(f"{path}.containers", "at least one container is required")
    if pod_spec.restart_policy and pod_spec.restart_policy != "Always":
        res.warn(f"{path}.restartPolicy will be ignored, it will be set to Always")
    # forbidden fields the operator owns (validatePodSpec — create path only,
    # matching the reference's operation==Create gate)
    if not is_update:
        if pod_spec.extra.get("topologySpreadConstraints"):
            res.error(f"{path}.topologySpreadConstraints", "must not be set")
        if pod_spec.extra.get("nodeName"):
            res.error(f"{path}.nodeName", "must not be set")


def _validate_topology_constraint(
    tc,
    parent_tc,
    path: str,
    topology: Optional[ClusterTopology],
    res: ValidationResult,
    allow_spread: bool = False,
) -> None:
    _validate_spread_constraint(tc, path, topology, res, allow_spread)
    if tc.pack_domain is None:
        return
    if tc.pack_domain not in TOPOLOGY_DOMAIN_ORDER:
        res.error(
            f"{path}.packDomain",
            f"unknown topology domain {tc.pack_domain!r}; must be one of"
            f" {sorted(TOPOLOGY_DOMAIN_ORDER)}",
        )
        return
    if topology is not None and topology.level_index(tc.pack_domain) is None:
        res.error(
            f"{path}.packDomain",
            f"domain {tc.pack_domain!r} is not a level of the cluster topology",
        )
    # Child constraints must be equal to or stricter than the parent's
    # (podcliqueset.go:232-234 docs on PCSG TopologyConstraint). A parent with
    # an unknown domain is reported at its own path; skip the comparison.
    if (
        parent_tc is not None
        and parent_tc.pack_domain is not None
        and parent_tc.pack_domain in TOPOLOGY_DOMAIN_ORDER
    ):
        if broader_than(tc.pack_domain, parent_tc.pack_domain):
            res.error(
                f"{path}.packDomain",
                f"must be equal to or stricter than the parent constraint"
                f" {parent_tc.pack_domain!r}",
            )


def _validate_spread_constraint(
    tc, path: str, topology, res: ValidationResult, allow_spread: bool
) -> None:
    """Topology SPREAD rules (grove-tpu extension; no reference analogue):
    gang-level only, known domain, strictly narrower than a packDomain it
    composes with, minDomains >= 2, whenUnsatisfiable enum."""
    from grove_tpu.api.types import SPREAD_UNSATISFIABLE_MODES

    has_spread_fields = (
        tc.spread_domain is not None
        or tc.spread_min_domains is not None
        or tc.spread_when_unsatisfiable is not None
    )
    if not has_spread_fields:
        return
    if not allow_spread:
        res.error(
            f"{path}.spreadDomain",
            "spread constraints are only supported on the template-level"
            " topologyConstraint (the whole gang), not per clique or"
            " scaling group",
        )
        return
    if tc.spread_domain is None:
        res.error(
            f"{path}.spreadDomain",
            "spreadMinDomains/spreadWhenUnsatisfiable require spreadDomain",
        )
        return
    if tc.spread_domain not in TOPOLOGY_DOMAIN_ORDER:
        res.error(
            f"{path}.spreadDomain",
            f"unknown topology domain {tc.spread_domain!r}; must be one of"
            f" {sorted(TOPOLOGY_DOMAIN_ORDER)}",
        )
        return
    if topology is not None and topology.level_index(tc.spread_domain) is None:
        res.error(
            f"{path}.spreadDomain",
            f"domain {tc.spread_domain!r} is not a level of the cluster"
            " topology",
        )
    if (
        tc.pack_domain is not None
        and tc.pack_domain in TOPOLOGY_DOMAIN_ORDER
        and not broader_than(tc.pack_domain, tc.spread_domain)
    ):
        res.error(
            f"{path}.spreadDomain",
            f"must be strictly narrower than packDomain {tc.pack_domain!r}"
            " (pack into one broad domain, spread across the narrower"
            " domains inside it)",
        )
    if tc.spread_min_domains is not None and tc.spread_min_domains < 2:
        res.error(
            f"{path}.spreadMinDomains", "must be at least 2 when set"
        )
    if (
        tc.spread_when_unsatisfiable is not None
        and tc.spread_when_unsatisfiable not in SPREAD_UNSATISFIABLE_MODES
    ):
        res.error(
            f"{path}.spreadWhenUnsatisfiable",
            f"must be one of {list(SPREAD_UNSATISFIABLE_MODES)}",
        )


def _unique(items: List[str], path: str, msg: str, res: ValidationResult) -> None:
    seen = set()
    for it in items:
        if it in seen:
            res.error(path, f"{msg} (duplicate: {it!r})")
            return
        seen.add(it)


# ---------------------------------------------------------------------------
# Update-path validation (immutability)
# ---------------------------------------------------------------------------


def validate_podcliqueset_update(
    new: PodCliqueSet,
    old: PodCliqueSet,
    topology: Optional[ClusterTopology] = None,
) -> ValidationResult:
    """Full update validation: the create-path rules on the new object plus
    immutability checks — matching the reference webhook handler, which runs
    validate() then validateUpdate() on every update (admission handler.go).
    """
    res = validate_podcliqueset(new, topology, is_update=True)
    nt, ot = new.spec.template, old.spec.template

    if nt.startup_type != ot.startup_type:
        res.error("spec.template.cliqueStartupType", "field is immutable")

    if len(nt.cliques) != len(ot.cliques):
        res.error("spec.template.cliques", "not allowed to change clique composition")
    old_by_name = {c.name: (i, c) for i, c in enumerate(ot.cliques)}
    order_enforced = nt.startup_type in (STARTUP_IN_ORDER, STARTUP_EXPLICIT)
    for i, nc in enumerate(nt.cliques):
        if nc.name not in old_by_name:
            res.error(
                "spec.template.cliques.name",
                f"not allowed to change clique composition, new clique name"
                f" {nc.name!r} is not allowed",
            )
            continue
        oi, oc = old_by_name[nc.name]
        if order_enforced and i != oi:
            res.error(
                "spec.template.cliques",
                f"clique order cannot be changed when StartupType is InOrder or"
                f" Explicit (expected {oc.name!r} at position {oi})",
            )
        if nc.spec.role_name != oc.spec.role_name:
            res.error(f"spec.template.cliques[{i}].spec.roleName", "field is immutable")
        if nc.spec.min_available != oc.spec.min_available:
            res.error(
                f"spec.template.cliques[{i}].spec.minAvailable", "field is immutable"
            )
        if list(nc.spec.starts_after) != list(oc.spec.starts_after):
            res.error(
                f"spec.template.cliques[{i}].spec.startsAfter", "field is immutable"
            )

    if len(nt.pod_clique_scaling_group_configs) != len(
        ot.pod_clique_scaling_group_configs
    ):
        res.error(
            "spec.template.podCliqueScalingGroups",
            "not allowed to add or remove PodCliqueScalingGroupConfigs",
        )
        return res
    old_sgs = {sg.name: sg for sg in ot.pod_clique_scaling_group_configs}
    for sg in nt.pod_clique_scaling_group_configs:
        if sg.name not in old_sgs:
            res.error(
                "spec.template.podCliqueScalingGroups.name",
                f"not allowed to change scaling group composition, new scaling"
                f" group name {sg.name!r} is not allowed",
            )
            continue
        osg = old_sgs[sg.name]
        if list(sg.clique_names) != list(osg.clique_names):
            res.error(
                "spec.template.podCliqueScalingGroups.cliqueNames", "field is immutable"
            )
        if sg.min_available != osg.min_available:
            res.error(
                "spec.template.podCliqueScalingGroups.minAvailable",
                "field is immutable",
            )
    return res


# ---------------------------------------------------------------------------
# ClusterTopology validation
# ---------------------------------------------------------------------------


def validate_cluster_topology(topo: ClusterTopology) -> ValidationResult:
    """webhook/admission/clustertopology/validation: level enum membership,
    uniqueness, and broad→narrow ordering."""
    res = ValidationResult()
    levels = topo.spec.levels
    if not levels:
        res.error("spec.levels", "at least one level is required")
        return res
    if len(levels) > 7:
        res.error("spec.levels", "at most 7 levels are allowed")
    seen_domains, seen_keys = set(), set()
    prev_order = -1
    for i, lvl in enumerate(levels):
        if lvl.domain not in TOPOLOGY_DOMAIN_ORDER:
            res.error(f"spec.levels[{i}].domain", f"unknown domain {lvl.domain!r}")
            continue
        if lvl.domain in seen_domains:
            res.error(f"spec.levels[{i}].domain", f"duplicate domain {lvl.domain!r}")
        seen_domains.add(lvl.domain)
        if not lvl.key:
            res.error(f"spec.levels[{i}].key", "key is required")
        if lvl.key in seen_keys:
            res.error(f"spec.levels[{i}].key", f"duplicate key {lvl.key!r}")
        seen_keys.add(lvl.key)
        order = TOPOLOGY_DOMAIN_ORDER[lvl.domain]
        if order <= prev_order:
            res.error(
                f"spec.levels[{i}].domain",
                "levels must be ordered from broadest to narrowest",
            )
        prev_order = order
    return res


# ---------------------------------------------------------------------------
# Queue validation (quota subsystem — docs/quota.md)
# ---------------------------------------------------------------------------


def validate_queue(queue) -> ValidationResult:
    """Webhook-equivalent Queue validation: DNS-label name, two-level tree
    (parent must be the implicit root), non-negative shares, and per-resource
    ceiling >= deserved (a ceiling below the deserved share is unsatisfiable:
    the queue could never reach what fair-share ordering entitles it to)."""
    from grove_tpu.api.types import QUEUE_ROOT

    res = ValidationResult()
    name = queue.metadata.name
    if not name or not _DNS1123_RE.match(name) or len(name) > 63:
        res.error("metadata.name", f"{name!r} is not a DNS-1123 label")
    if name == QUEUE_ROOT:
        res.error(
            "metadata.name",
            f"{QUEUE_ROOT!r} is the implicit tree root and cannot be a Queue",
        )
    if queue.spec.parent not in ("", QUEUE_ROOT):
        res.error(
            "spec.parent",
            f"must be {QUEUE_ROOT!r} (the queue tree is two-level: "
            "root -> tenant queues)",
        )
    for fname, shares in (
        ("deserved", queue.spec.deserved),
        ("ceiling", queue.spec.ceiling),
    ):
        for r, v in shares.items():
            if v < 0:
                res.error(f"spec.{fname}[{r}]", f"must be >= 0, got {v}")
    for r, cap in queue.spec.ceiling.items():
        deserved = queue.spec.deserved.get(r)
        if deserved is not None and cap < deserved:
            res.error(
                f"spec.ceiling[{r}]",
                f"ceiling {cap} is below deserved {deserved}",
            )
    if not queue.spec.deserved:
        res.warn(
            f"queue {name!r} has no deserved shares: it orders last whenever "
            "it holds any usage and can never justify a reclaim"
        )
    return res
