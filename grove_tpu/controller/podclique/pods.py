"""PodClique pod component: create/delete/ungate pods.

Re-host of /root/reference/operator/internal/controller/podclique/components/pod/
(pod.go, syncflow.go, initcontainer.go):
- pods are created WITH the `grove.io/podgang-pending-creation` scheduling gate
- identity env vars + stable hostname `<pclq>-<idx>` via the index allocator
- replica diff folds the expectations store over the (possibly stale) cache
- the gate is removed only when (1) the pod is referenced by its PodGang and
  (2) for scaled gangs, the base PodGang is scheduled (syncflow.go:242-387)
- excess pods are deleted worst-first (DeletionSorter equivalent)
"""

from __future__ import annotations

import copy
import json
from typing import List, Optional

from grove_tpu.api import names as namegen
from grove_tpu.api.meta import ObjectMeta
from grove_tpu.api.pod import (
    Pod,
    is_ready,
    is_schedule_gated,
    is_scheduled,
    is_terminating,
)
from grove_tpu.api.types import (
    PODGANG_SCHEDULING_GATE,
    PodClique,
    PodGang,
)
from grove_tpu.controller.common import OperatorContext
from grove_tpu.runtime import indexer
from grove_tpu.runtime.store import commit_spec

STARTUP_DEPS_ANNOTATION = "grove.io/startup-dependencies"  # JSON on the PCLQ


def owner_pcs_name(pclq: PodClique) -> str:
    return pclq.metadata.labels.get(namegen.LABEL_PART_OF, "")


def sync_pods(
    ctx: OperatorContext, pclq: PodClique, pods, base_sched_memo=None
) -> int:
    """Create/delete pods to match spec.replicas; returns pods still gated.

    ``pods``: the reconciler's pre-scanned pod list (read-only views),
    shared between this flow and the gate pass — both always decided
    against the pre-sync snapshot (the replica diff covers in-flight
    creates via expectations), so sharing one scan is behavior-identical
    and halves the per-reconcile scan cost (one LIST instead of two in
    HttpStore cluster mode).

    ``base_sched_memo``: optional per-drain-batch memo for the base-gang-
    scheduled check — scaled PCLQs of one set share a base gang, and under
    cache lag the cached view is frozen for the whole round, so one check
    serves every sibling in the batch."""
    ns = pclq.metadata.namespace
    cached_pods = [p for p in pods if not is_terminating(p)]
    observed_uids = [p.metadata.uid for p in cached_pods]
    key = f"{ns}/{pclq.metadata.name}"
    pending_creates, pending_deletes = ctx.pod_expectations.pending(key, observed_uids)

    # diff = existing + expectedCreates − desired − expectedDeletes
    # (syncflow.go:171-186)
    diff = (
        len(cached_pods)
        + len(pending_creates)
        - pclq.spec.replicas
        - len(pending_deletes)
    )
    created_pods: List[Pod] = []
    if diff < 0:
        created_pods = _create_pods(ctx, pclq, -diff, cached_pods)
    elif diff > 0:
        _delete_excess_pods(ctx, pclq, diff, cached_pods, pending_deletes)

    _process_pending_updates(ctx, pclq, cached_pods, pending_deletes)

    # Pods created THIS reconcile are born schedule-gated and may not be
    # visible to the cached gate scan yet (informer lag) — feed their fresh
    # store copies straight into the gate pass: a pod recreated while its
    # gang is already scheduled ungates IN THIS reconcile instead of waiting
    # out the GATE_RETRY_SECONDS requeue (recreate-latency regression noted
    # in ADVICE r5). Pods the gang does not reference yet still count as
    # gated, so the reconciler schedules the gate-retry requeue — without
    # that, a creating reconcile could return "all clear" and, with
    # pod-ADDED events predicate-filtered (reference podPredicate
    # CreateFunc=false, podclique/register.go:102), nothing would ever
    # revisit the gate.
    return _remove_scheduling_gates(
        ctx, pclq, cached_pods + created_pods, base_sched_memo
    )


def _process_pending_updates(
    ctx: OperatorContext, pclq: PodClique, pods, pending_deletes
) -> None:
    """Pod-by-pod rolling replacement (components/pod/rollingupdate.go:55-244):
    pods whose template hash doesn't match the PCLQ's are replaced — all
    not-ready stale pods at once, then ready pods ONE at a time, each only
    after the previous replacement is Ready again."""
    current_hash = pclq.metadata.labels.get(namegen.LABEL_POD_TEMPLATE_HASH)
    if not current_hash:
        return
    ns = pclq.metadata.namespace
    key = f"{ns}/{pclq.metadata.name}"
    # refresh delete expectations: scale-in may have recorded deletions in
    # this same sync pass (stale snapshot would allow a double replacement)
    _, pending_deletes = ctx.pod_expectations.pending(
        key, [p.metadata.uid for p in pods]
    )
    live = [p for p in pods if p.metadata.uid not in pending_deletes]
    stale = [
        p
        for p in live
        if p.metadata.labels.get(namegen.LABEL_POD_TEMPLATE_HASH) != current_hash
    ]
    if not stale:
        return

    not_ready_stale = [p for p in stale if not is_ready(p)]
    if not_ready_stale:
        # pending/unhealthy stale pods carry no availability — replace at once
        for pod in not_ready_stale:
            ctx.pod_expectations.expect_deletions(key, [pod.metadata.uid])
            ctx.store.delete("Pod", ns, pod.metadata.name)
            ctx.record_event(
                "Pod",
                "PodUpdateDeleteSuccessful",
                pod.metadata.name,
                namespace=ns,
                name=pod.metadata.name,
            )
        return

    # every pod is ready; only proceed when no replacement is still missing
    # (one in-flight replacement at a time)
    if len(live) < pclq.spec.replicas or not all(is_ready(p) for p in live):
        return
    victim = sorted(stale, key=deletion_order)[0]
    ctx.pod_expectations.expect_deletions(key, [victim.metadata.uid])
    ctx.store.delete("Pod", ns, victim.metadata.name)
    ctx.record_event(
        "Pod",
        "PodUpdateDeleteSuccessful",
        victim.metadata.name,
        namespace=ns,
        name=victim.metadata.name,
    )


def _create_pods(
    ctx: OperatorContext, pclq: PodClique, count: int, existing: List[Pod]
) -> List[Pod]:
    """Create `count` pods; returns the created store copies so the caller's
    gate pass can consider them in the same reconcile."""
    from grove_tpu.runtime.errors import GroveError
    from grove_tpu.utils.concurrent import Task, run_concurrently_with_slow_start

    ns = pclq.metadata.namespace
    active_names = [p.metadata.name for p in existing]
    indices = indexer.allocate_indices(pclq.metadata.name, active_names, count)
    key = f"{ns}/{pclq.metadata.name}"
    created_pods: List[Pod] = []  # list.append is atomic across task threads

    def make_create(idx: int):
        def create() -> None:
            pod = build_pod(ctx, pclq, idx)
            # ownership-transfer create: the freshly built pod becomes the
            # committed object directly (no private pickled copy); the gate
            # pass below only READS it
            created = ctx.store.create(pod, consume=True)
            ctx.pod_expectations.expect_creations(key, [created.metadata.uid])
            ctx.record_event(
                "Pod",
                "PodCreateSuccessful",
                created.metadata.name,
                namespace=ns,
                name=created.metadata.name,
            )
            created_pods.append(created)

        return create

    # slow-start batches (1,2,4,…) — a failing apiserver is detected after a
    # handful of creates, not a burst (reference utils/concurrent.go:69-90)
    result = run_concurrently_with_slow_start(
        [
            Task(name=namegen.pod_name(pclq.metadata.name, idx), fn=make_create(idx))
            for idx in indices
        ]
    )
    if result.has_errors:
        raise GroveError(
            "ERR_SYNC_PODS", result.summary(), f"create-pods {pclq.metadata.name}"
        )
    created_pods.sort(key=lambda p: p.metadata.name)  # deterministic order
    return created_pods


def build_pod(ctx: OperatorContext, pclq: PodClique, pod_index: int) -> Pod:
    """pod.go:135-264: labels, gate, identity env, hostname, init waiter."""
    pcs_name = owner_pcs_name(pclq)
    pcs_replica = pclq.metadata.labels.get(namegen.LABEL_PCS_REPLICA_INDEX, "0")
    name = namegen.pod_name(pclq.metadata.name, pod_index)
    pod_spec = _clone_pod_spec(pclq)
    pod_spec.scheduling_gates = [PODGANG_SCHEDULING_GATE]
    pod_spec.hostname = name
    pod_spec.subdomain = namegen.headless_service_name(pcs_name, int(pcs_replica))
    pod_spec.service_account_name = namegen.pod_service_account_name(pcs_name)

    headless_addr = namegen.headless_service_address(
        pcs_name, int(pcs_replica), pclq.metadata.namespace
    )
    env = {
        "GROVE_PCS_NAME": pcs_name,
        "GROVE_PCS_INDEX": pcs_replica,
        "GROVE_PCLQ_NAME": pclq.metadata.name,
        "GROVE_HEADLESS_SERVICE": headless_addr,
        "GROVE_PCLQ_POD_INDEX": str(pod_index),
    }
    for container in pod_spec.containers + pod_spec.init_containers:
        for k, v in env.items():
            container.set_env(k, v)

    # init waiter (startup ordering) — initcontainer.go:50-158
    deps_json = pclq.metadata.annotations.get(STARTUP_DEPS_ANNOTATION)
    if deps_json:
        pod_spec.extra["groveInitWaiter"] = {
            "podcliques": json.loads(deps_json),
            "podgang": pclq.metadata.labels.get(namegen.LABEL_PODGANG, ""),
        }

    labels = dict(pclq.metadata.labels)
    labels[namegen.LABEL_PODCLIQUE] = pclq.metadata.name
    labels[namegen.LABEL_COMPONENT] = namegen.COMPONENT_POD
    labels[namegen.LABEL_APP_NAME] = name
    labels[namegen.LABEL_POD_INDEX] = str(pod_index)

    return Pod(
        metadata=ObjectMeta(
            name=name,
            namespace=pclq.metadata.namespace,
            labels=labels,
            owner_references=[_owner_ref(pclq)],
        ),
        spec=pod_spec,
    )


def _clone_container(c):
    # env dicts are the only container field set_env mutates in place
    c2 = copy.copy(c)
    c2.env = [dict(e) for e in c.env]
    return c2


def _clone_pod_spec(pclq: PodClique):
    """Copy-on-write pod-spec clone. build_pod customizes exactly: the gate
    list, identity fields (assigned), per-container env (set_env), and the
    extra dict — those get private copies; everything else (resources,
    commands, tolerations, unmodeled passthrough) stays shared with the
    PCLQ's immutable committed template. Replaces a pickled deep copy of
    the whole template per pod (the dominant pod-create cost at scale)."""
    src = pclq.spec.pod_spec
    spec = copy.copy(src)
    spec.containers = [_clone_container(c) for c in src.containers]
    spec.init_containers = [_clone_container(c) for c in src.init_containers]
    spec.scheduling_gates = list(src.scheduling_gates)
    spec.extra = dict(src.extra)
    return spec


def _owner_ref(pclq: PodClique):
    from grove_tpu.api.meta import OwnerReference

    return OwnerReference(kind="PodClique", name=pclq.metadata.name, uid=pclq.metadata.uid)


def deletion_order(pod: Pod) -> tuple:
    """Worst-first ordering for scale-in (DeletionSorter equivalent):
    gated < unscheduled < scheduled-not-ready < ready; ties by higher index."""
    if is_schedule_gated(pod):
        rank = 0
    elif not is_scheduled(pod):
        rank = 1
    elif not is_ready(pod):
        rank = 2
    else:
        rank = 3
    idx = pod.metadata.labels.get(namegen.LABEL_POD_INDEX, "0")
    return (rank, -int(idx))


def _delete_excess_pods(
    ctx: OperatorContext,
    pclq: PodClique,
    count: int,
    existing: List[Pod],
    pending_deletes,
) -> None:
    ns = pclq.metadata.namespace
    key = f"{ns}/{pclq.metadata.name}"
    candidates = [p for p in existing if p.metadata.uid not in pending_deletes]
    candidates.sort(key=deletion_order)
    for pod in candidates[:count]:
        ctx.pod_expectations.expect_deletions(key, [pod.metadata.uid])
        ctx.store.delete("Pod", ns, pod.metadata.name)
        ctx.record_event(
            "Pod",
            "PodDeleteSuccessful",
            pod.metadata.name,
            namespace=ns,
            name=pod.metadata.name,
        )


# ---------------------------------------------------------------------------
# Scheduling-gate removal (the gang-admission handshake)
# ---------------------------------------------------------------------------


def _remove_scheduling_gates(
    ctx: OperatorContext, pclq: PodClique, pods, base_sched_memo=None
) -> int:
    ns = pclq.metadata.namespace
    podgang_name = pclq.metadata.labels.get(namegen.LABEL_PODGANG, "")
    gated = [p for p in pods if PODGANG_SCHEDULING_GATE in p.spec.scheduling_gates]
    if not gated:
        return 0

    podgang: Optional[PodGang] = (
        ctx.store.get("PodGang", ns, podgang_name, cached=True, readonly=True)
        if podgang_name
        else None
    )
    names_in_gang = set()
    if podgang is not None:
        for group in podgang.spec.pod_groups:
            for ref in group.pod_references:
                names_in_gang.add(ref.name)

    if base_sched_memo is None:
        base_scheduled = _base_podgang_scheduled(ctx, pclq)
    else:
        mkey = (ns, pclq.metadata.labels.get(namegen.LABEL_BASE_PODGANG))
        base_scheduled = base_sched_memo.get(mkey)
        if base_scheduled is None:
            base_scheduled = base_sched_memo[mkey] = _base_podgang_scheduled(
                ctx, pclq
            )

    skipped = 0
    for pod in gated:
        # (1) pod must be referenced by its PodGang (syncflow.go:261)
        if pod.metadata.name not in names_in_gang:
            skipped += 1
            continue
        # (2) scaled pods additionally wait for the base gang (syncflow.go:303-387)
        if not base_scheduled:
            skipped += 1
            continue
        view = ctx.store.get("Pod", ns, pod.metadata.name, readonly=True)
        if view is None or not view.spec.scheduling_gates:
            continue
        # copy-on-write ungate: clone only the spec spine with a private
        # gate list; containers/env stay shared with the committed object
        new_spec = copy.copy(view.spec)
        new_spec.scheduling_gates = [
            g for g in view.spec.scheduling_gates if g != PODGANG_SCHEDULING_GATE
        ]
        commit_spec(ctx.store, view, new_spec)
    return skipped


def _base_podgang_scheduled(ctx: OperatorContext, pclq: PodClique) -> bool:
    """syncflow.go:305-345: true when the PCLQ has no base-podgang label
    (it IS part of the base gang), else when every PodGroup of the base gang
    has PCLQ.status.scheduledReplicas >= group.minReplicas."""
    base_name = pclq.metadata.labels.get(namegen.LABEL_BASE_PODGANG)
    if not base_name:
        return True
    ns = pclq.metadata.namespace
    base = ctx.store.get("PodGang", ns, base_name, cached=True, readonly=True)
    if base is None:
        return False
    for group in base.spec.pod_groups:
        member = ctx.store.get(
            "PodClique", ns, group.name, cached=True, readonly=True
        )
        if member is None:
            return False
        if member.status.scheduled_replicas < group.min_replicas:
            return False
    return True
