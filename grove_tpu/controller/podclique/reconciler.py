"""PodClique reconciler: get → delete-flow → spec-flow (pods) → status-flow.

Re-host of /root/reference/operator/internal/controller/podclique/reconciler.go
with the pod component as its single ordered component
(podclique/reconcilespec.go:213-217).
"""

from __future__ import annotations

from grove_tpu.api import names as namegen
from grove_tpu.controller.common import (
    FINALIZER,
    OperatorContext,
    record_last_error,
    write_status_if_changed,
)
from grove_tpu.controller.podclique import pods as pod_component
from grove_tpu.controller.podclique.status import compute_status
from grove_tpu.runtime.errors import GroveError
from grove_tpu.runtime.flow import (
    ReconcileStepResult,
    continue_reconcile,
    do_not_requeue,
    reconcile_after,
    reconcile_with_errors,
)
from grove_tpu.runtime.workqueue import Key

GATE_RETRY_SECONDS = 2.0


class PodCliqueReconciler:
    def __init__(self, ctx: OperatorContext) -> None:
        self.ctx = ctx
        self._base_sched_memo = None

    def begin_batch(self, keys) -> None:
        """Engine batch hook (deterministic drain only): scaled PCLQs of a
        set share one base gang, and under cache lag the cached view is
        FROZEN for the whole round — so the base-gang-scheduled check is
        computed once per (ns, base gang) per batch instead of per PCLQ.
        Without cache lag reads are live and the memo stays off."""
        self._base_sched_memo = {} if self.ctx.store.cache_lag else None

    def reconcile(self, key: Key) -> ReconcileStepResult:
        _, ns, name = key
        # readonly view: sync_pods only reads the PCLQ; the one-time
        # finalizer write re-gets a mutable copy
        pclq = self.ctx.store.get("PodClique", ns, name, readonly=True)
        if pclq is None:
            return do_not_requeue()
        if pclq.metadata.deletion_timestamp is not None:
            return self._reconcile_delete(pclq)
        try:
            if FINALIZER not in pclq.metadata.finalizers:
                from grove_tpu.runtime.store import commit_finalizer_add

                pclq = commit_finalizer_add(self.ctx.store, pclq, FINALIZER)
                if pclq is None:  # deleted between view and write
                    return do_not_requeue()
            # ONE pod scan shared by the sync flow and the gate pass (both
            # always decided against the pre-sync view — the diff math uses
            # expectations for in-flight creates). The STATUS compute below
            # keeps its own scan: it must reflect this reconcile's own
            # mutations where the store view can show them (cluster mode),
            # and the predicate rationale for filtering pod-ADDED events
            # relies on the creating reconcile re-counting.
            pods = list(
                self.ctx.store.scan(
                    "Pod", ns, {namegen.LABEL_PODCLIQUE: name}, cached=True
                )
            )
            skipped_gated = pod_component.sync_pods(
                self.ctx, pclq, pods, self._base_sched_memo
            )
            view = self.ctx.store.get("PodClique", ns, name, readonly=True)
            if view is not None and view.metadata.deletion_timestamp is None:
                # compute on the zero-copy view; write only on difference
                # (steady-state reconciles then cost no serialization)
                proposed = compute_status(self.ctx, view)
                proposed.observed_generation = view.metadata.generation
                proposed.last_errors = []  # cleared on a clean reconcile
                write_status_if_changed(
                    self.ctx, "PodClique", ns, name, proposed
                )
        except GroveError as err:
            record_last_error(self.ctx, "PodClique", ns, name, err)
            return reconcile_with_errors(f"podclique {ns}/{name}", err)
        if skipped_gated:
            # pods still gated (not in PodGang yet / base gang unscheduled):
            # retry gate removal (reference pod.go:125-130 ErrCodeRequeueAfter)
            return reconcile_after(GATE_RETRY_SECONDS, "pods still schedule-gated")
        return continue_reconcile()

    def _reconcile_delete(self, pclq) -> ReconcileStepResult:
        ns = pclq.metadata.namespace
        try:
            self.ctx.store.delete_collection(
                "Pod", ns, {namegen.LABEL_PODCLIQUE: pclq.metadata.name}
            )
            self.ctx.pod_expectations.delete_expectations(f"{ns}/{pclq.metadata.name}")
            self.ctx.store.remove_finalizer("PodClique", ns, pclq.metadata.name, FINALIZER)
        except GroveError as err:
            return reconcile_with_errors(f"delete podclique {pclq.metadata.name}", err)
        return do_not_requeue()
