"""PodClique status flow.

Re-host of /root/reference/operator/internal/controller/podclique/reconcilestatus.go:
pod categorization → replica counters → PodCliqueScheduled and
MinAvailableBreached conditions. The two subtle rules preserved exactly:
- NOT breached while scheduledReplicas < minAvailable (never gang-terminate a
  gang that was never scheduled — reconcilestatus.go:192-201)
- "starting" pods (scheduled, no container started-and-failed signal yet)
  count as available; pods with a non-zero container exit, or started-but-
  not-ready pods, count against availability (reconcilestatus.go:205-215)
"""

from __future__ import annotations

from grove_tpu.api import names as namegen
from grove_tpu.api.meta import Condition, set_condition
from grove_tpu.api.pod import (
    has_erroneous_exit,
    is_ready,
    is_schedule_gated,
    is_scheduled,
    is_terminating,
)
from grove_tpu.api.types import (
    COND_MIN_AVAILABLE_BREACHED,
    COND_POD_CLIQUE_SCHEDULED,
    PodClique,
)
from grove_tpu.controller.common import OperatorContext

UPDATE_IN_PROGRESS_ANNOTATION = "grove.io/update-in-progress"


def compute_status(ctx: OperatorContext, pclq: PodClique, pods=None):
    """The status `pclq` SHOULD have, computed WITHOUT mutating it — safe on
    zero-copy readonly store views. The reconciler compares the result
    against the live status and writes only on difference, so steady-state
    reconciles cost no serialization at all (the write-free analogue of the
    reference's status-patch-if-changed). ``pods``: optional pre-scanned
    pod views shared with the pod-sync flow (one scan per reconcile)."""
    from grove_tpu.controller.common import status_shadow

    shadow = status_shadow(pclq)
    reconcile_status(ctx, shadow, pods)
    return shadow.status


def reconcile_status(ctx: OperatorContext, pclq: PodClique, pods=None) -> PodClique:
    ns = pclq.metadata.namespace
    st = pclq.status
    current_hash = pclq.metadata.labels.get(namegen.LABEL_POD_TEMPLATE_HASH)
    counters = None
    if pods is None:
        # event-driven aggregation: the store maintains these counters
        # incrementally from watch deltas (runtime/aggregate.py), exactly
        # equal to a full rescan of the same cached view — so the per-event
        # O(pods) rescan drops to O(1). HttpStore has no aggregate (reads
        # are live lists); it keeps the scan below.
        pod_counters = getattr(ctx.store, "pod_counters", None)
        if pod_counters is not None:
            counters = pod_counters(ns, pclq.metadata.name, cached=True)
    if counters is not None:
        st.replicas = counters.total
        st.ready_replicas = counters.ready
        st.scheduled_replicas = counters.scheduled
        st.schedule_gated_replicas = counters.gated
        st.updated_replicas = counters.updated(current_hash)
        num_error_exits = counters.error_exits
        num_started_not_ready = counters.started_not_ready
    else:
        if pods is None:
            pods = ctx.store.scan(
                "Pod", ns, {namegen.LABEL_PODCLIQUE: pclq.metadata.name}, cached=True
            )
        pods = [p for p in pods if not is_terminating(p)]
        st.replicas = len(pods)
        st.ready_replicas = sum(1 for p in pods if is_ready(p))
        st.scheduled_replicas = sum(1 for p in pods if is_scheduled(p))
        st.schedule_gated_replicas = sum(1 for p in pods if is_schedule_gated(p))
        st.updated_replicas = sum(
            1
            for p in pods
            if current_hash
            and p.metadata.labels.get(namegen.LABEL_POD_TEMPLATE_HASH) == current_hash
        )
        num_error_exits = sum(
            1 for p in pods if not is_ready(p) and has_erroneous_exit(p)
        )
        num_started_not_ready = sum(
            1
            for p in pods
            if is_scheduled(p)
            and not is_ready(p)
            and not has_erroneous_exit(p)
            and any(cs.started for cs in p.status.container_statuses)
        )
    st.selector = f"{namegen.LABEL_PODCLIQUE}={pclq.metadata.name}"
    now = ctx.clock.now()
    set_condition(
        st.conditions, _scheduled_condition(pclq), now
    )
    set_condition(
        st.conditions,
        _min_available_breached_condition(pclq, num_error_exits, num_started_not_ready),
        now,
    )
    return pclq


def _scheduled_condition(pclq: PodClique) -> Condition:
    """reconcilestatus.go:238-254."""
    min_available = pclq.spec.min_available or 0
    if pclq.status.scheduled_replicas < min_available:
        return Condition(
            type=COND_POD_CLIQUE_SCHEDULED,
            status="False",
            reason="InsufficientScheduledPods",
            message=(
                f"Insufficient scheduled pods. expected at least: {min_available},"
                f" found: {pclq.status.scheduled_replicas}"
            ),
        )
    return Condition(
        type=COND_POD_CLIQUE_SCHEDULED,
        status="True",
        reason="SufficientScheduledPods",
        message="Sufficient scheduled pods found",
    )


def _min_available_breached_condition(
    pclq: PodClique, num_error_exits: int, num_started_not_ready: int
) -> Condition:
    """reconcilestatus.go:177-225."""
    if pclq.metadata.annotations.get(UPDATE_IN_PROGRESS_ANNOTATION):
        return Condition(
            type=COND_MIN_AVAILABLE_BREACHED,
            status="Unknown",
            reason="UpdateInProgress",
            message="Update is in progress",
        )
    min_available = pclq.spec.min_available or 0
    scheduled = pclq.status.scheduled_replicas
    if scheduled < min_available:
        return Condition(
            type=COND_MIN_AVAILABLE_BREACHED,
            status="False",
            reason="InsufficientScheduledPods",
            message=(
                f"Insufficient scheduled pods. expected at least: {min_available},"
                f" found: {scheduled}"
            ),
        )
    ready_or_starting = scheduled - num_error_exits - num_started_not_ready
    if ready_or_starting < min_available:
        return Condition(
            type=COND_MIN_AVAILABLE_BREACHED,
            status="True",
            reason="InsufficientReadyPods",
            message=(
                f"Insufficient ready or starting pods. expected at least:"
                f" {min_available}, found: {ready_or_starting}"
            ),
        )
    return Condition(
        type=COND_MIN_AVAILABLE_BREACHED,
        status="False",
        reason="SufficientReadyPods",
        message="Sufficient ready or starting pods found",
    )
