"""Forecast-driven remediation: the policy controller that closes the loop.

PRs 12-14 built the glass box — burn-rate alerts detect, explain verdicts
diagnose, what-if trial solves simulate. This controller is the missing
verb: ticked by the harness like the HPA and the node monitor, it turns
those signals into ACTIONS, under three hard rules:

1. **Prove before acting.** A structural remediation (drain of a node,
   defrag migration of a gang) executes only when the what-if engine's
   commit-nothing trial solve says the action FLIPS the cited gang's
   verdict to ``fits_now``. No speculation: the same solver kernel that
   would place the gang afterwards judges the hypothesis first.
2. **Mechanism stays put.** Every action goes through the existing
   machinery — node drains through ``NodeDrainController`` (which runs
   each eviction through the ``DisruptionBroker``'s per-PCS budget
   grants), scale-ups through the autoscaler's decision log. The storm
   breaker is respected: an open breaker pauses all remediation.
3. **Account for everything.** Every considered action — executed or
   skipped — writes one causal chain into ``LEDGER``
   (trigger→diagnosis→simulation→action→effect); grovelint GL019
   ``act-must-log`` enforces the write sits in the same function as the
   act call. Effects are measured: the SLO error-budget delta over the
   effect window lands on the entry once the window elapses.

Triggers: ``SloBurnRateHigh`` (walk pending gangs' explain verdicts,
defrag-migrate the one provably unblocked), forecast-peak (preemptive
scale-up ahead of the diurnal peak the forecaster predicts), and a
fragmentation threshold (defrag without waiting for the burn).

Off by default with the PR-1 one-boolean-check discipline
(``GROVE_TPU_REMEDIATE=1`` / ``enable()``); a disabled remediator is
provably inert — byte-identical A/B pinned in tests and the smoke.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from grove_tpu.api import names as namegen
from grove_tpu.api.meta import get_condition
from grove_tpu.api.types import COND_PODGANG_SCHEDULED
from grove_tpu.observability.forecast import FORECASTER
from grove_tpu.observability.ledger import (
    ACTION_DRAIN_NODE,
    ACTION_MIGRATE_GANG,
    ACTION_SCALE_UP,
    LEDGER,
    OUTCOME_EXECUTED,
    OUTCOME_SKIPPED,
    TRIGGER_FAILSLOW,
    TRIGGER_FORECAST_PEAK,
    TRIGGER_FRAG_THRESHOLD,
    TRIGGER_SLO_BURN,
)
from grove_tpu.observability.slo import SLO
from grove_tpu.sim.cluster import NODE_DEGRADED

DEFAULT_EFFECT_WINDOW = 120.0  # seconds from action to effect measurement
DEFAULT_COOLDOWN = 60.0  # per (action kind, target) re-trigger damping
MAX_PENDING_WALK = 4  # explain verdicts consulted per burn tick
MAX_DRAIN_CANDIDATES = 3  # filler nodes trial-solved per defrag attempt


class RemediationController:
    """One instance per harness, wired over the existing mechanism layer
    (store/cluster/scheduler/drainer/broker/autoscaler/explain). Keeps
    only policy state (cooldowns, scale policies, pending effect
    measurements) — every cluster fact is re-read per tick."""

    def __init__(
        self,
        store,
        cluster,
        scheduler,
        drainer,
        broker,
        autoscaler,
        explain,
    ) -> None:
        self.store = store
        self.cluster = cluster
        self.scheduler = scheduler
        self.drainer = drainer
        self.broker = broker
        self.autoscaler = autoscaler
        self.explain = explain
        self.enabled = os.environ.get("GROVE_TPU_REMEDIATE", "") not in (
            "",
            "0",
            "false",
        )
        self.effect_slo: Optional[str] = None
        self.effect_window = DEFAULT_EFFECT_WINDOW
        self.cooldown = DEFAULT_COOLDOWN
        self.frag_threshold: Optional[float] = None
        # forecast scale-up policies: series → HPA-shaped target
        self._scale_policies: List[dict] = []
        # (action_kind, target) -> vt before which we will not re-consider
        self._cooldowns: Dict[Tuple[str, str], float] = {}
        # (due_vt, ledger entry id, slo name, budget_before)
        self._pending_effects: List[Tuple[float, int, Optional[str], Optional[float]]] = []

    # -- lifecycle -------------------------------------------------------

    def enable(
        self,
        effect_slo: Optional[str] = None,
        effect_window: Optional[float] = None,
        cooldown: Optional[float] = None,
        frag_threshold: Optional[float] = None,
    ) -> "RemediationController":
        if effect_slo is not None:
            self.effect_slo = effect_slo
        if effect_window is not None:
            self.effect_window = float(effect_window)
        if cooldown is not None:
            self.cooldown = float(cooldown)
        if frag_threshold is not None:
            self.frag_threshold = float(frag_threshold)
        self.enabled = True
        return self

    def disable(self) -> None:
        self.enabled = False

    def add_scale_policy(
        self,
        series: str,
        threshold: float,
        kind: str,
        namespace: str,
        name: str,
        max_replicas: int,
        step: int = 1,
    ) -> None:
        """Preemptive scale-up policy: when the forecast's peak mean over
        the horizon crosses ``threshold``, raise the target by ``step``
        replicas (never past ``max_replicas``) BEFORE the peak arrives."""
        self._scale_policies.append(
            {
                "series": series,
                "threshold": float(threshold),
                "kind": kind,
                "namespace": namespace,
                "name": name,
                "max_replicas": int(max_replicas),
                "step": int(step),
            }
        )

    def next_deadline(self) -> Optional[float]:
        """Earliest pending effect-measurement instant — lets the harness
        jump virtual time to it instead of idling short ticks."""
        if not self.enabled or not self._pending_effects:
            return None
        return min(due for due, _, _, _ in self._pending_effects)

    # -- tick ------------------------------------------------------------

    def tick(self) -> int:
        """One policy round: measure due effects, then at most one
        structural action plus any forecast scale-ups. Returns work units
        so harness quiescence sees remediation as progress."""
        if not self.enabled:
            return 0
        now = self.store.clock.now()
        work = self._measure_effects(now)
        burning = SLO.burning()
        if burning:
            structural = self._on_burn(burning[0], now)
        elif self.frag_threshold is not None:
            structural = self._on_frag(now)
        else:
            structural = 0
        if not structural:
            # fail-slow drains ride the same one-structural-action-per-
            # tick discipline as burn/frag defrags
            structural = self._on_failslow(now)
        work += structural
        work += self._on_forecast(now)
        return work

    # -- triggers --------------------------------------------------------

    def _on_burn(self, burn: dict, now: float) -> int:
        """Burn alert: walk pending gangs' explain verdicts; defrag the
        first one the what-if engine proves a drain would unblock."""
        slo_name = burn["name"]
        fast = burn.get("burn_rate_fast")
        detail = f"slo {slo_name} burn" + (
            f" fast={fast:.1f}x" if isinstance(fast, float) else ""
        )
        for ns, name in self._pending_gangs():
            doc = self.explain.explain(ns, name)
            if doc is None or doc.get("fits_now"):
                continue
            diagnosis = {
                "gang": f"{ns}/{name}",
                "binding_constraint": doc.get("binding_constraint"),
                "detail": doc.get("detail"),
            }
            if self._defraggable(doc):
                acted = self._defrag(
                    TRIGGER_SLO_BURN, detail, ns, name, diagnosis,
                    slo_name, now,
                )
                if acted:
                    return acted
        return 0

    def _on_frag(self, now: float) -> int:
        """Fragmentation threshold: defrag a blocked gang before the frag
        turns into a burn."""
        report = self.explain.capacity()
        score = 0.0
        for level in report.get("levels", []):
            for frac in (level.get("fragmentation") or {}).values():
                score = max(score, float(frac))
        if score < self.frag_threshold:
            return 0
        detail = f"fragmentation {score:.2f} >= {self.frag_threshold:.2f}"
        for ns, name in self._pending_gangs():
            doc = self.explain.explain(ns, name)
            if doc is None or doc.get("fits_now"):
                continue
            if not self._defraggable(doc):
                continue
            diagnosis = {
                "gang": f"{ns}/{name}",
                "binding_constraint": doc.get("binding_constraint"),
                "detail": doc.get("detail"),
            }
            acted = self._defrag(
                TRIGGER_FRAG_THRESHOLD, detail, ns, name, diagnosis,
                self.effect_slo, now,
            )
            if acted:
                return acted
        return 0

    def _on_failslow(self, now: float) -> int:
        """Fail-slow trigger (docs/robustness.md "Gray failures"): a node
        the suspicion EWMA flipped to Degraded is already masked from new
        placements; this decides whether to also DRAIN it — only when the
        what-if engine proves every victim gang re-places on the remaining
        healthy capacity (the scheduled-gang analogue of a verdict flip:
        Scheduled → fits-elsewhere), and every victim clears the
        disruption broker's budget. A gray failure never justifies
        breaking a gang the failure itself did not break."""
        degraded = sorted(
            n.name
            for n in self.cluster.nodes
            if n.state == NODE_DEGRADED
        )
        work = 0
        for node in degraded:
            if self._cooling("failslow", node, now):
                continue
            victims = self._bound_gangs(node)
            if not victims:
                # nothing bound: the schedulable mask alone contains the
                # gray failure; draining an empty node is pure churn
                self._cool("failslow", node, now)
                continue
            trigger_detail = (
                f"node {node} Degraded (fail-slow suspicion over threshold)"
            )
            diagnosis = {
                "node": node,
                "victims": [f"{vns}/{vname}" for vns, vname in victims],
            }
            if self.broker.active() and self.broker.breaker_open:
                self._cool("failslow", node, now)
                LEDGER.record(
                    TRIGGER_FAILSLOW, ACTION_DRAIN_NODE, OUTCOME_SKIPPED,
                    trigger_detail=trigger_detail, diagnosis=diagnosis,
                    reason="breaker-open", now=now,
                )
                work += 1
                continue
            proven = True
            afters = []
            for vns, vname in victims:
                report = self.explain.whatif(
                    {
                        "gang": {"namespace": vns, "name": vname},
                        "actions": [
                            {"action": "drain-node", "node": node}
                        ],
                    }
                )
                afters.append(
                    {
                        "gang": f"{vns}/{vname}",
                        "fits_after": bool(
                            report["after"].get("fits_now")
                        ),
                        "after": report["after"].get(
                            "binding_constraint"
                        ),
                    }
                )
                if not report["after"].get("fits_now"):
                    proven = False
                    break
            self._cool("failslow", node, now)
            simulation = {"flipped": proven, "victims": afters}
            if not proven:
                LEDGER.record(
                    TRIGGER_FAILSLOW, ACTION_DRAIN_NODE, OUTCOME_SKIPPED,
                    trigger_detail=trigger_detail, diagnosis=diagnosis,
                    simulation=simulation,
                    reason="not-flipped", now=now,
                )
                work += 1
                continue
            denied = False
            for vns, vname in victims:
                gang = self.store.get("PodGang", vns, vname, readonly=True)
                if gang is not None and not self.broker.would_allow(
                    gang, now
                ):
                    LEDGER.record(
                        TRIGGER_FAILSLOW, ACTION_DRAIN_NODE,
                        OUTCOME_SKIPPED,
                        trigger_detail=trigger_detail, diagnosis=diagnosis,
                        simulation=simulation,
                        action={"target": node},
                        reason=f"budget-denied for {vns}/{vname}", now=now,
                    )
                    denied = True
                    break
            if denied:
                work += 1
                continue
            self.drainer.request_drain(node)
            entry = LEDGER.record(
                TRIGGER_FAILSLOW, ACTION_DRAIN_NODE, OUTCOME_EXECUTED,
                trigger_detail=trigger_detail, diagnosis=diagnosis,
                simulation=simulation,
                action={
                    "target": node,
                    "mechanism": "drain",
                    "victims": [
                        f"{vns}/{vname}" for vns, vname in victims
                    ],
                },
                now=now,
            )
            self._schedule_effect(entry, self.effect_slo, now)
            return work + 1
        return work

    def _on_forecast(self, now: float) -> int:
        """Forecast peaks: preemptive scale-up ahead of the predicted
        diurnal peak (scoring feeds forecast_skill/<series> per round)."""
        work = 0
        for policy in self._scale_policies:
            fc = FORECASTER.forecast(policy["series"], feed=True, now=now)
            peak = fc.get("peak")
            if peak is None or peak["mean"] < policy["threshold"]:
                continue
            work += self._scale_up(policy, fc, now)
        return work

    # -- actions (GL019: every act call logs its ledger entry here) ------

    def _defrag(
        self,
        trigger: str,
        trigger_detail: str,
        ns: str,
        name: str,
        diagnosis: dict,
        slo_name: Optional[str],
        now: float,
    ) -> int:
        """Budget-gated defrag: trial filler-node drains through what-if;
        execute the first PROVEN flip via the drain controller (whose own
        eviction path runs every gang through a broker grant)."""
        node = None  # the chosen candidate (set on flip)
        action_kind = ACTION_MIGRATE_GANG
        # cooldown keyed on the diagnosed gang, not the action kind the
        # attempt ends up with (drain-node vs migrate-gang is decided by
        # the winning candidate's health, below)
        if self._cooling("defrag", f"{ns}/{name}", now):
            return 0
        if self.broker.active() and self.broker.breaker_open:
            self._cool("defrag", f"{ns}/{name}", now)
            LEDGER.record(
                trigger, action_kind, OUTCOME_SKIPPED,
                trigger_detail=trigger_detail, diagnosis=diagnosis,
                reason="breaker-open", now=now,
            )
            return 1
        tried = []
        simulation = None
        for candidate, health in self._drain_candidates():
            report = self.explain.whatif(
                {
                    "gang": {"namespace": ns, "name": name},
                    "actions": [
                        {"action": "drain-node", "node": candidate}
                    ],
                }
            )
            tried.append(candidate)
            if not report["flipped"]:
                continue
            node = candidate
            simulation = {
                "flipped": True,
                "actions": report["actions"],
                "after": report["after"].get("binding_constraint"),
            }
            # a flapping/unhealthy filler is a drain-node remediation;
            # a healthy one is a pure defrag migration
            if not health:
                action_kind = ACTION_DRAIN_NODE
            break
        self._cool("defrag", f"{ns}/{name}", now)
        if node is None:
            LEDGER.record(
                trigger, action_kind, OUTCOME_SKIPPED,
                trigger_detail=trigger_detail, diagnosis=diagnosis,
                simulation={"flipped": False, "tried": tried},
                reason="no-flipping-candidate", now=now,
            )
            return 1
        # budget gate BEFORE the cordon: every gang the drain would evict
        # must clear the broker's pure check (the drain's own grant() still
        # decides for real, per gang, at eviction time)
        victims = self._bound_gangs(node)
        for vns, vname in victims:
            gang = self.store.get("PodGang", vns, vname, readonly=True)
            if gang is not None and not self.broker.would_allow(gang, now):
                LEDGER.record(
                    trigger, action_kind, OUTCOME_SKIPPED,
                    trigger_detail=trigger_detail, diagnosis=diagnosis,
                    simulation=simulation,
                    action={"target": node},
                    reason=f"budget-denied for {vns}/{vname}", now=now,
                )
                return 1
        self.drainer.request_drain(node)
        entry = LEDGER.record(
            trigger, action_kind, OUTCOME_EXECUTED,
            trigger_detail=trigger_detail, diagnosis=diagnosis,
            simulation=simulation,
            action={
                "target": node,
                "mechanism": "drain",
                "victims": [f"{vns}/{vname}" for vns, vname in victims],
            },
            now=now,
        )
        self._schedule_effect(entry, slo_name, now)
        return 1

    def _scale_up(self, policy: dict, fc: dict, now: float) -> int:
        """Forecast-gated preemptive scale-up through the autoscaler's
        decision log (ONE unified hpa_* stream)."""
        kind, ns, name = policy["kind"], policy["namespace"], policy["name"]
        key = f"{kind}/{ns}/{name}"
        if self._cooling(ACTION_SCALE_UP, key, now):
            return 0
        self._cool(ACTION_SCALE_UP, key, now)
        peak = fc["peak"]
        trigger_detail = (
            f"{policy['series']} forecast peak {peak['mean']:.3f} >="
            f" {policy['threshold']:.3f} at t={peak['at_s']:.0f}s"
        )
        simulation = {
            "flipped": None,
            "forecast": {
                "peak": peak,
                "model": fc.get("model"),
                "skill": fc.get("skill"),
            },
        }
        target = self.store.get(kind, ns, name, readonly=True)
        if target is None:
            LEDGER.record(
                TRIGGER_FORECAST_PEAK, ACTION_SCALE_UP, OUTCOME_SKIPPED,
                trigger_detail=trigger_detail, simulation=simulation,
                action={"target": key}, reason="target-absent", now=now,
            )
            return 1
        current = int(target.spec.replicas)
        desired = min(policy["max_replicas"], current + policy["step"])
        if desired <= current:
            LEDGER.record(
                TRIGGER_FORECAST_PEAK, ACTION_SCALE_UP, OUTCOME_SKIPPED,
                trigger_detail=trigger_detail, simulation=simulation,
                action={"target": key, "from": current},
                reason="at-max-replicas", now=now,
            )
            return 1
        scaled = self.autoscaler.scale_target(kind, ns, name, desired)
        entry = LEDGER.record(
            TRIGGER_FORECAST_PEAK, ACTION_SCALE_UP,
            OUTCOME_EXECUTED if scaled else OUTCOME_SKIPPED,
            trigger_detail=trigger_detail, simulation=simulation,
            action={"target": key, "from": current, "to": desired},
            reason="" if scaled else "scale-rejected", now=now,
        )
        if scaled:
            self._schedule_effect(entry, self.effect_slo, now)
        return 1

    # -- effects ---------------------------------------------------------

    def _schedule_effect(
        self, entry_id: Optional[int], slo_name: Optional[str], now: float
    ) -> None:
        if entry_id is None:
            return
        budget = (
            SLO.budget_remaining(slo_name) if slo_name is not None else None
        )
        self._pending_effects.append(
            (now + self.effect_window, entry_id, slo_name, budget)
        )

    def _measure_effects(self, now: float) -> int:
        due = [e for e in self._pending_effects if e[0] <= now]
        if not due:
            return 0
        self._pending_effects = [
            e for e in self._pending_effects if e[0] > now
        ]
        for _, entry_id, slo_name, before in due:
            after = (
                SLO.budget_remaining(slo_name)
                if slo_name is not None
                else None
            )
            LEDGER.effect(
                entry_id, self.effect_window, before, after, now=now
            )
        return len(due)

    # -- cluster reads ---------------------------------------------------

    @staticmethod
    def _defraggable(doc: dict) -> bool:
        """A verdict a drain/migration could plausibly flip: blocked on
        topology or raw capacity (fragmentation family), not on quota /
        disruption holds / solve ordering."""
        constraint = doc.get("binding_constraint") or ""
        detail = doc.get("detail") or ""
        return constraint in ("topology", "capacity") or "fragmentation" in detail

    def _pending_gangs(self) -> List[Tuple[str, str]]:
        """Unscheduled PodGangs in deterministic order, bounded — explain
        verdicts are cheap but not free."""
        out = []
        for gang in self.store.scan("PodGang"):
            cond = get_condition(
                gang.status.conditions, COND_PODGANG_SCHEDULED
            )
            if cond is not None and cond.is_true():
                continue
            out.append((gang.metadata.namespace, gang.metadata.name))
        out.sort()
        return out[:MAX_PENDING_WALK]

    def _drain_candidates(self) -> List[Tuple[str, bool]]:
        """Filler-node candidates for a defrag drain: schedulable nodes
        carrying the FEWEST bound pods first (least relocation for the
        most contiguity), as ``(name, healthy)`` pairs."""
        load: Dict[str, int] = {}
        for (_ns, _pod), bound in self.cluster.bindings.items():
            load[bound] = load.get(bound, 0) + 1
        candidates = [
            (load.get(n.name, 0), n.name, not n.crashed)
            for n in self.cluster.nodes
            if n.schedulable and load.get(n.name, 0) > 0
        ]
        candidates.sort()
        return [
            (name, healthy)
            for _count, name, healthy in candidates[:MAX_DRAIN_CANDIDATES]
        ]

    def _bound_gangs(self, node_name: str) -> List[Tuple[str, str]]:
        """Gangs with >= 1 pod bound to the node (the drain's victim set),
        deterministic order."""
        out = set()
        for (ns, pod_name), bound in list(self.cluster.bindings.items()):
            if bound != node_name:
                continue
            pod = self.store.get("Pod", ns, pod_name, readonly=True)
            if pod is None:
                continue
            gang_name = pod.metadata.labels.get(namegen.LABEL_PODGANG)
            if gang_name:
                out.add((ns, gang_name))
        return sorted(out)

    # -- cooldowns -------------------------------------------------------

    def _cooling(self, kind: str, target: str, now: float) -> bool:
        until = self._cooldowns.get((kind, target))
        return until is not None and now < until

    def _cool(self, kind: str, target: str, now: float) -> None:
        self._cooldowns[(kind, target)] = now + self.cooldown
