"""Controller registration: wire the three reconcilers + watch mappings.

Re-host of /root/reference/operator/internal/controller/register.go:29-43 and
the per-controller watch wiring (podclique/register.go:49-278 etc.), in the
same PCS → PCLQ → PCSG order.
"""

from __future__ import annotations

from grove_tpu.api import names as namegen
from grove_tpu.api.types import COND_MIN_AVAILABLE_BREACHED
from grove_tpu.controller.common import OperatorContext
from grove_tpu.controller.podclique.reconciler import PodCliqueReconciler
from grove_tpu.controller.podcliquescalinggroup.reconciler import (
    PodCliqueScalingGroupReconciler,
)
from grove_tpu.controller.podcliqueset.reconciler import PodCliqueSetReconciler
from grove_tpu.runtime.engine import Controller, Engine
from grove_tpu.runtime.store import ADDED, DELETED, MODIFIED


# ---------------------------------------------------------------------------
# Watch predicates (controller-runtime predicate.Funcs re-hosts).
#
# Every predicate fails OPEN on a MODIFIED event with no `old` payload
# (e.g. an HttpStore informer fresh off a reconnect): an extra reconcile is
# idempotent, a skipped one can stall convergence. The store's no-op write
# suppression already removed events with NO change; these predicates
# remove events whose change is IRRELEVANT to the subscribing controller —
# at stress scale (10k sets / 47k pods) unfiltered pod status churn fanning
# into the PodCliqueSet controller was the single largest reconcile source.
# ---------------------------------------------------------------------------


def _cond_status(conditions, cond_type):
    for c in conditions:
        if c.type == cond_type:
            return c.status
    return None


def _breach_changed(old_status, new_status) -> bool:
    """hasMinAvailableBreachedConditionChanged (podcliqueset/register.go
    :146-158): only the condition's STATUS flip matters."""
    return _cond_status(
        old_status.conditions, COND_MIN_AVAILABLE_BREACHED
    ) != _cond_status(new_status.conditions, COND_MIN_AVAILABLE_BREACHED)


def generation_changed(ev) -> bool:
    """predicate.GenerationChangedPredicate (podcliqueset/register.go:53):
    pass creates/deletes; pass updates only on a spec (generation) change,
    so a controller's own status writes never re-enqueue it.

    Deletion-mark and finalizer transitions also pass: a real apiserver
    bumps metadata.generation when deletionTimestamp is set, but the
    repo's store models that as a version-only write — without this the
    finalizer-gated delete flow would never run. Label/annotation
    transitions pass for the same reason: metadata-only writes use
    bump_generation=False here (e.g. the rolling-update flow popping
    UPDATE_IN_PROGRESS_ANNOTATION, rollingupdate.py:204) where a real
    apiserver WOULD bump generation, and that pop is the only signal that
    un-suspends the MinAvailableBreached condition."""
    if ev.type != MODIFIED or ev.old is None:
        return True
    om, nm = ev.old.metadata, ev.obj.metadata
    return (
        nm.generation != om.generation
        or nm.deletion_timestamp != om.deletion_timestamp
        or nm.finalizers != om.finalizers
        or nm.annotations != om.annotations
        or nm.labels != om.labels
    )


def pclq_changed_for_owner(ev) -> bool:
    """podCliquePredicate (podcliqueset/register.go:90-103): creates are
    the owner's own doing; deletes always matter; updates matter when the
    spec, any status replica counter, or the breach condition moved."""
    if ev.type == ADDED:
        return False
    if ev.type == DELETED:
        return True
    if ev.old is None:
        return True
    old, new = ev.old, ev.obj
    if old.metadata.generation != new.metadata.generation:
        return True
    os, ns = old.status, new.status
    return (
        os.replicas != ns.replicas
        or os.ready_replicas != ns.ready_replicas
        or os.schedule_gated_replicas != ns.schedule_gated_replicas
        # the repo's PCS status/rolling-update flows also aggregate these
        # two (reconciler.py), so their transitions must requeue the owner
        # — the reference's narrower triple suffices for ITS status flow
        or os.scheduled_replicas != ns.scheduled_replicas
        or os.updated_replicas != ns.updated_replicas
        or _breach_changed(os, ns)
    )


def pcsg_changed_for_owner(ev) -> bool:
    """podCliqueScalingGroupPredicate (podcliqueset/register.go:105-120)
    plus the replica counters the repo's PCS status flow aggregates."""
    if ev.type != MODIFIED:
        return ev.type != ADDED
    if ev.old is None:
        return True
    os, ns = ev.old.status, ev.obj.status
    return (
        os.replicas != ns.replicas
        or os.scheduled_replicas != ns.scheduled_replicas
        or os.available_replicas != ns.available_replicas
        or os.updated_replicas != ns.updated_replicas
        or os.rolling_update_progress != ns.rolling_update_progress
        or _breach_changed(os, ns)
    )


def pcs_hash_changed(ev) -> bool:
    """podCliqueSetPredicate (podclique/register.go:191-205): children
    re-reconcile on a PCS event only when the rolled-out generation hash
    moves (the signal that a rolling update started/advanced). Everything
    else a child needs arrives via its own kinds' events."""
    if ev.type != MODIFIED:
        return ev.type == DELETED
    if ev.old is None:
        return True
    return (
        ev.old.status.current_generation_hash
        != ev.obj.status.current_generation_hash
    )


def pod_status_transition(ev) -> bool:
    """podPredicate (podclique/register.go:99-116): creates are the
    PCLQ's own doing (its creating reconcile re-counts in the same flow);
    deletes always matter; updates matter only when the pod's lifecycle
    actually moved (phase, binding, conditions incl. Ready/PodScheduled,
    gates, init-waiter completion, labels, or deletion mark)."""
    if ev.type == ADDED:
        return False
    if ev.type == DELETED:
        return True
    if ev.old is None:
        return True
    old, new = ev.old, ev.obj
    os, ns = old.status, new.status
    return (
        os.phase != ns.phase
        or os.node_name != ns.node_name
        or os.init_waiter_done != ns.init_waiter_done
        or os.conditions != ns.conditions
        or old.spec.scheduling_gates != new.spec.scheduling_gates
        or old.metadata.deletion_timestamp != new.metadata.deletion_timestamp
        or old.metadata.labels != new.metadata.labels
    )


def pcs_rolling_pointer_changed(ev) -> bool:
    """shouldEnqueueOnPCSUpdate (podcliquescalinggroup/register.go:114-145):
    the PCSG controller re-reconciles on a PCS event when the rolled-out
    hash moves (update starts) or the rolling update's currently-updating
    replica POINTER moves (its replica's turn arrives) — both are status
    writes a generation/hash-only gate would swallow."""
    if ev.type != MODIFIED:
        return ev.type == DELETED
    if ev.old is None:
        return True

    def pointer(pcs):
        prog = pcs.status.rolling_update_progress
        if prog is None or prog.currently_updating is None:
            return None
        return prog.currently_updating.replica_index

    return (
        pointer(ev.old) != pointer(ev.obj)
        or ev.old.status.current_generation_hash
        != ev.obj.status.current_generation_hash
    )


def pcsg_rolling_progress_changed(ev) -> bool:
    """podCliqueScalingGroupPredicate on the PCLQ controller
    (podclique/register.go:225-240): constituent PCLQs re-reconcile on a
    PCSG event only when its rolling-update progress moved (the replica
    selection that tells a PCLQ its pods are next)."""
    if ev.type != MODIFIED:
        return False
    if ev.old is None:
        return True
    return (
        ev.old.status.rolling_update_progress
        != ev.obj.status.rolling_update_progress
    )


def podgang_phase_or_spec_changed(ev) -> bool:
    """PodGang events fan out on creation, deletion, SPEC changes (pod
    membership / reservation hints — written with bump_generation=False,
    podgang.py:327, so compared structurally, not via generation), PHASE
    transitions (the base-gang-scheduled signal that unblocks deferred
    scaled-gang creation and pod ungating), and CONDITION transitions (the
    PCS status flow mirrors gang conditions into pod_gang_statuses,
    reconciler.py — a condition-only flip like Unhealthy must refresh the
    mirror; condition flips are rare because the store suppresses no-op
    writes) — NOT on placement-score touches, which move on every
    re-admission. Conditions are compared by (type, status, reason) only:
    _mark_scheduled embeds the score in the Scheduled condition's MESSAGE
    (scheduler.py), so a message-sensitive compare would re-admit the very
    score churn this predicate exists to filter. Reference analogue:
    podGangPredicate
    (podclique/register.go:271-278) passes all updates. The contract test
    (tests/test_podgang_status_contract.py) asserts controller flows read
    ONLY the fields this predicate passes — a new consumer of
    placement_score breaks the build instead of stalling behind the
    filter. DELETED passes so an out-of-band gang deletion re-runs the
    owner's podgang sync (recreate)."""
    if ev.type != MODIFIED:
        return True  # creates AND deletes both matter
    if ev.old is None:
        return True

    def cond_key(conditions):
        return [(c.type, c.status, c.reason) for c in conditions]

    return (
        ev.old.status.phase != ev.obj.status.phase
        or cond_key(ev.old.status.conditions)
        != cond_key(ev.obj.status.conditions)
        or ev.old.spec != ev.obj.spec
    )


def _map_to_part_of(ev):
    """Child event → owning PodCliqueSet (via app.kubernetes.io/part-of)."""
    owner = ev.obj.metadata.labels.get(namegen.LABEL_PART_OF)
    return [(ev.obj.metadata.namespace, owner)] if owner else []


def _map_pod_to_pclq(ev):
    pclq = ev.obj.metadata.labels.get(namegen.LABEL_PODCLIQUE)
    return [(ev.obj.metadata.namespace, pclq)] if pclq else []


def _map_podgang_to_pclqs(ev):
    """podclique/register.go:242-278: PodGang events map back to the PCLQs
    named by its PodGroups (drives the ungating handshake)."""
    ns = ev.obj.metadata.namespace
    return [(ns, group.name) for group in ev.obj.spec.pod_groups]


def _map_pclq_to_pcsg(ev):
    pcsg = ev.obj.metadata.labels.get(namegen.LABEL_PCSG)
    return [(ev.obj.metadata.namespace, pcsg)] if pcsg else []


def _map_pcsg_to_pclqs(ctx: OperatorContext):
    """PCSG event → its constituent PodCliques
    (podclique/register.go:207-222 mapPodCliqueScalingGroupToPCLQs)."""

    def map_fn(ev):
        ns = ev.obj.metadata.namespace
        return [
            (ns, o.metadata.name)
            for o in ctx.store.scan(
                "PodClique", ns, {namegen.LABEL_PCSG: ev.obj.metadata.name}
            )
        ]

    return map_fn


def _map_pcs_to_children_of_kind(ctx: OperatorContext, kind: str):
    def map_fn(ev):
        sel = namegen.default_labels(ev.obj.metadata.name)
        return [
            (o.metadata.namespace, o.metadata.name)
            for o in ctx.store.scan(kind, ev.obj.metadata.namespace, sel)
        ]

    return map_fn


def register_controllers(engine: Engine, ctx: OperatorContext, config=None) -> None:
    pcs = PodCliqueSetReconciler(ctx)
    pclq = PodCliqueReconciler(ctx)
    pcsg = PodCliqueScalingGroupReconciler(ctx)
    syncs = (
        (
            config.controllers.pod_clique_set.concurrent_syncs,
            config.controllers.pod_clique.concurrent_syncs,
            config.controllers.pod_clique_scaling_group.concurrent_syncs,
        )
        if config is not None
        else (1, 1, 1)
    )

    engine.register(
        Controller(
            name="podcliqueset",
            kind="PodCliqueSet",
            reconcile=pcs.reconcile,
            concurrent_syncs=syncs[0],
            primary_predicate=generation_changed,
            watches=[
                ("PodClique", _map_to_part_of, pclq_changed_for_owner),
                (
                    "PodCliqueScalingGroup",
                    _map_to_part_of,
                    pcsg_changed_for_owner,
                ),
                # NOT in the reference's PCS watch set (it watches only
                # PCLQ + PCSG — register.go:53-60; pod churn reaches the
                # owner as coalesced PCLQ status transitions). Kept here
                # because the repo's podgang component defers scaled-gang
                # creation on the base gang's phase and mirrors gang
                # phases + conditions into PCS status — gated to
                # phase/spec/condition transitions, a handful of events
                # per gang lifetime.
                ("PodGang", _map_to_part_of, podgang_phase_or_spec_changed),
            ],
        )
    )
    engine.register(
        Controller(
            name="podclique",
            kind="PodClique",
            reconcile=pclq.reconcile,
            batch_hook=pclq.begin_batch,
            concurrent_syncs=syncs[1],
            primary_predicate=generation_changed,
            watches=[
                ("Pod", _map_pod_to_pclq, pod_status_transition),
                ("PodGang", _map_podgang_to_pclqs, podgang_phase_or_spec_changed),
                (
                    "PodCliqueSet",
                    _map_pcs_to_children_of_kind(ctx, "PodClique"),
                    pcs_hash_changed,
                ),
                (
                    "PodCliqueScalingGroup",
                    _map_pcsg_to_pclqs(ctx),
                    pcsg_rolling_progress_changed,
                ),
            ],
        )
    )
    engine.register(
        Controller(
            name="podcliquescalinggroup",
            kind="PodCliqueScalingGroup",
            reconcile=pcsg.reconcile,
            concurrent_syncs=syncs[2],
            primary_predicate=generation_changed,
            watches=[
                ("PodClique", _map_pclq_to_pcsg, pclq_changed_for_owner),
                (
                    "PodCliqueSet",
                    _map_pcs_to_children_of_kind(ctx, "PodCliqueScalingGroup"),
                    pcs_rolling_pointer_changed,
                ),
            ],
        )
    )
