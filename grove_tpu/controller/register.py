"""Controller registration: wire the three reconcilers + watch mappings.

Re-host of /root/reference/operator/internal/controller/register.go:29-43 and
the per-controller watch wiring (podclique/register.go:49-278 etc.), in the
same PCS → PCLQ → PCSG order.
"""

from __future__ import annotations

from grove_tpu.api import names as namegen
from grove_tpu.controller.common import OperatorContext
from grove_tpu.controller.podclique.reconciler import PodCliqueReconciler
from grove_tpu.controller.podcliquescalinggroup.reconciler import (
    PodCliqueScalingGroupReconciler,
)
from grove_tpu.controller.podcliqueset.reconciler import PodCliqueSetReconciler
from grove_tpu.runtime.engine import Controller, Engine


def _map_to_part_of(ev):
    """Child event → owning PodCliqueSet (via app.kubernetes.io/part-of)."""
    owner = ev.obj.metadata.labels.get(namegen.LABEL_PART_OF)
    return [(ev.obj.metadata.namespace, owner)] if owner else []


def _map_pod_to_pclq(ev):
    pclq = ev.obj.metadata.labels.get(namegen.LABEL_PODCLIQUE)
    return [(ev.obj.metadata.namespace, pclq)] if pclq else []


def _map_podgang_to_pclqs(ev):
    """podclique/register.go:242-278: PodGang events map back to the PCLQs
    named by its PodGroups (drives the ungating handshake)."""
    ns = ev.obj.metadata.namespace
    return [(ns, group.name) for group in ev.obj.spec.pod_groups]


def _map_pclq_to_pcsg(ev):
    pcsg = ev.obj.metadata.labels.get(namegen.LABEL_PCSG)
    return [(ev.obj.metadata.namespace, pcsg)] if pcsg else []


def _map_pcs_to_children_of_kind(ctx: OperatorContext, kind: str):
    def map_fn(ev):
        sel = namegen.default_labels(ev.obj.metadata.name)
        return [
            (o.metadata.namespace, o.metadata.name)
            for o in ctx.store.scan(kind, ev.obj.metadata.namespace, sel)
        ]

    return map_fn


def register_controllers(engine: Engine, ctx: OperatorContext, config=None) -> None:
    pcs = PodCliqueSetReconciler(ctx)
    pclq = PodCliqueReconciler(ctx)
    pcsg = PodCliqueScalingGroupReconciler(ctx)
    syncs = (
        (
            config.controllers.pod_clique_set.concurrent_syncs,
            config.controllers.pod_clique.concurrent_syncs,
            config.controllers.pod_clique_scaling_group.concurrent_syncs,
        )
        if config is not None
        else (1, 1, 1)
    )

    engine.register(
        Controller(
            name="podcliqueset",
            kind="PodCliqueSet",
            reconcile=pcs.reconcile,
            concurrent_syncs=syncs[0],
            watches=[
                ("PodClique", _map_to_part_of),
                ("PodCliqueScalingGroup", _map_to_part_of),
                ("PodGang", _map_to_part_of),
                ("Pod", _map_to_part_of),
            ],
        )
    )
    engine.register(
        Controller(
            name="podclique",
            kind="PodClique",
            reconcile=pclq.reconcile,
            concurrent_syncs=syncs[1],
            watches=[
                ("Pod", _map_pod_to_pclq),
                ("PodGang", _map_podgang_to_pclqs),
                ("PodCliqueSet", _map_pcs_to_children_of_kind(ctx, "PodClique")),
            ],
        )
    )
    engine.register(
        Controller(
            name="podcliquescalinggroup",
            kind="PodCliqueScalingGroup",
            reconcile=pcsg.reconcile,
            concurrent_syncs=syncs[2],
            watches=[
                ("PodClique", _map_pclq_to_pcsg),
                (
                    "PodCliqueSet",
                    _map_pcs_to_children_of_kind(ctx, "PodCliqueScalingGroup"),
                ),
            ],
        )
    )
