"""Node-health monitor: heartbeat lifecycle, pod failure, gang rescue.

The node-controller role the reference delegates to Kubernetes itself
(kube-controller-manager's node lifecycle controller) plus the gang-aware
recovery policy its scheduler contract implies (SURVEY §5 failure handling):

- **Lifecycle** — every node heartbeats on the virtual clock
  (``SimCluster.heartbeat_tick``); a crashed kubelet stops, and this monitor
  walks the node through Ready → NotReady (grace window, pods stay bound)
  → Lost (grace exceeded). A restart inside the window is a harmless flap.
- **Pod failure** — pods bound to a Lost node are failed and deleted
  (node-eviction semantics): their bindings and capacity release
  immediately, the PodClique controllers recreate them gated, and the quota
  accountant folds the deltas from the same watch events every other
  consumer sees — usage stays exact through the failure.
- **Gang rescue vs. requeue** (docs/robustness.md decision table) — for
  each gang that lost pods:
  - survivors still satisfy every group's MinReplicas floor → **rescue**:
    survivors keep running, and the scheduler's recovery delta-solve places
    only the missing pods, anchored to the survivors' topology domain by
    the packing kernel's recovery pins (ops/packing.py group_pin/gang_pin).
    ``GangRescued`` is emitted once the gang is whole again.
  - survivors breach a floor → **gang-terminate**: the remaining pods are
    torn down, the gang's Scheduled condition flips False
    (reason NodeFailure), and the whole gang re-enters the all-or-nothing
    solver under rate-limited exponential backoff (``GangRequeued``).

Driven as a tick from the harness loop (like the autoscaler) rather than a
store-keyed reconciler: its primary resource — the node — is cluster
infrastructure, not a stored CR.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from grove_tpu.api import names as namegen
from grove_tpu.api.meta import Condition, get_condition, set_condition
from grove_tpu.api.pod import is_terminating
from grove_tpu.api.types import (
    COND_PODGANG_DISRUPTION_TARGET,
    COND_PODGANG_SCHEDULED,
    PHASE_PENDING,
)
from grove_tpu.observability.events import (
    EVENTS,
    REASON_GANG_RELEASED,
    REASON_GANG_REQUEUED,
    REASON_GANG_RESCUED,
    REASON_NODE_DEGRADED,
    REASON_NODE_LOST,
    REASON_NODE_NOT_READY,
    REASON_NODE_READY,
    REASON_NODE_RECOVERED,
    TYPE_NORMAL,
    TYPE_WARNING,
)
from grove_tpu.observability.metrics import METRICS
from grove_tpu.runtime.errors import ERR_CONFLICT, ERR_NOT_FOUND, GroveError
from grove_tpu.runtime.workqueue import WorkQueue
from grove_tpu.sim.cluster import (
    NODE_DEGRADED,
    NODE_LOST,
    NODE_NOT_READY,
    NODE_READY,
    SimCluster,
)

GangKey = Tuple[str, str]  # (namespace, gang name)


class _EpochSet(set):
    """Set that counts its effective mutations. The scheduler's overlap
    pump keys speculative spec reuse on hold-state staleness: any
    hold/release between speculation and the real encode must invalidate
    the speculated spec (``gang_held`` gates encoding), and the epoch is
    the O(1) way to observe that."""

    def __init__(self, *args) -> None:
        super().__init__(*args)
        self.epoch = 0

    def add(self, item) -> None:
        if item not in self:
            self.epoch += 1
        super().add(item)

    def discard(self, item) -> None:
        if item in self:
            self.epoch += 1
        super().discard(item)

    def clear(self) -> None:
        if self:
            self.epoch += 1
        super().clear()


class NodeHealthMonitor:
    """Grace-period node lifecycle + gang-aware failure recovery over a
    SimCluster. One instance per scheduler/cluster pair."""

    def __init__(
        self,
        store,
        cluster: SimCluster,
        not_ready_after: float = 10.0,
        lost_after: float = 30.0,
        failslow_threshold: Optional[float] = None,
        failslow_recover: Optional[float] = None,
        failslow_alpha: float = 0.3,
    ) -> None:
        assert lost_after >= not_ready_after
        self.store = store
        self.cluster = cluster
        self.not_ready_after = not_ready_after
        self.lost_after = lost_after
        # gray-failure (fail-slow) detection, docs/robustness.md "Gray
        # failures". OFF by default (threshold None): the suspicion lane is
        # one boolean check and the monitor is byte-identical to before.
        # When armed, each tick folds the node's heartbeat LATENESS (age at
        # observation — late-but-inside-grace heartbeats that the binary
        # lifecycle ignores) into an EWMA suspicion score; score above
        # `failslow_threshold` seconds flips Ready → Degraded (masked from
        # new placements via `Node.schedulable`, nothing evicted); decay
        # below `failslow_recover` (hysteresis, default threshold/2) flips
        # back. Eviction is NOT this monitor's call — only the remediation
        # controller may drain a Degraded node, behind a what-if-proven
        # flip and the disruption budget (TRIGGER_FAILSLOW).
        self.failslow_threshold = failslow_threshold
        self.failslow_recover = (
            failslow_recover
            if failslow_recover is not None
            else (failslow_threshold / 2.0 if failslow_threshold else None)
        )
        self.failslow_alpha = failslow_alpha
        # node name -> EWMA suspicion score (seconds of smoothed lateness).
        # Private state: only this monitor writes it (grovelint GL022).
        self._suspicion: Dict[str, float] = {}
        # requeued gangs in rate-limited backoff: the workqueue's delayed
        # heap paces re-admission; _held is what the scheduler consults
        # (gang_held) to keep a backing-off gang out of the solve. Gang
        # re-admission is paced in SECONDS (one solve attempt per release),
        # not the reconcile queues' 5ms curve — a gang retrying every drain
        # while capacity is gone would just burn solver rounds
        self.requeue = WorkQueue(base_backoff=1.0, max_backoff=60.0)
        self._held: Set[GangKey] = _EpochSet()
        # gangs whose triage (status flip / pod teardown) hit a transient
        # store error: retried level-triggered on the next tick
        self._triage_retry: Dict[GangKey, str] = {}
        # released-from-backoff gangs get exactly ONE scheduler round: still
        # unscheduled at the next tick → re-held with the next backoff step
        # (client-go retry pacing); scheduled → forgotten
        self._probation: Set[GangKey] = set()
        # in-flight rescues: gang key -> {domain_key, domain, survivors,...};
        # completion (gang whole again) emits GangRescued and archives into
        # `rescues` for the chaos harness's placement verification
        self._rescue_pending: Dict[GangKey, dict] = {}
        self.rescues: List[dict] = []
        # GET /nodes drain column: () -> {node name: Draining|Drained},
        # wired to NodeDrainController.states by the harness/manager (the
        # drain workflow is a separate controller; the monitor only
        # surfaces its state in the node table)
        self.drain_states = None

    # -- scheduler contract ----------------------------------------------

    def gang_held(self, namespace: str, name: str) -> bool:
        """True while the gang sits in requeue backoff — the scheduler
        skips encoding it (its pods stay pending, untouched)."""
        return (namespace, name) in self._held

    @property
    def holds_epoch(self) -> int:
        """Mutation counter of the requeue-hold set: any hold or release
        bumps it, so the scheduler's overlap pump can fold hold-state
        into its staleness token without copying the set."""
        return self._held.epoch

    def hold_gang(self, key: GangKey) -> None:
        """Put a gang into rate-limited requeue backoff from OUTSIDE the
        node-failure triage — the drain controller's terminate-and-requeue
        fallback uses the same pacing a NodeFailure termination gets.
        Every hold is paired with a scheduled release (the workqueue's
        delayed entry) — a hold without one would strand the gang, since
        nothing else ever releases it."""
        self._held.add(key)
        self._probation.discard(key)
        self.requeue.add_rate_limited(
            ("PodGang",) + key, self.store.clock.now()
        )

    def resync(self) -> int:
        """Fresh-leader re-prime (manager run-loop failover, chaos
        ``leader_crash``): monitor holds and backoff counters live in
        leader memory, so a standby that takes over mid-outage starts with
        none — every gang the OLD leader had terminated-and-requeued would
        re-enter the solve unpaced (churn), and a NAIVE re-prime that adds
        holds without scheduled releases would strand them forever.

        Re-derive from persisted state: a gang whose Scheduled condition
        is False with a terminate-and-requeue reason (NodeFailure/Drained)
        is re-held WITH a fresh rate-limited release while unhealthy
        capacity is still missing; once every node is back there is
        nothing to wait for — it goes to probation for an immediate solve
        attempt instead. Also drops stale holds for gangs that vanished or
        re-scheduled. Returns entries touched."""
        now = self.store.clock.now()
        # LIVE health, not the state label: `state` is maintained by monitor
        # ticks (this monitor has run none), so a node restarted just
        # before the failover still reads Lost — but its kubelet is up
        # (crashed=False) and the first tick will flip it Ready. Only a
        # dead kubelet means capacity is actually missing.
        unhealthy = any(n.crashed for n in self.cluster.nodes)
        touched = 0
        for gang in self.store.scan("PodGang"):
            key = (gang.metadata.namespace, gang.metadata.name)
            cond = get_condition(
                gang.status.conditions, COND_PODGANG_SCHEDULED
            )
            if cond is None or cond.is_true():
                continue
            if cond.reason not in ("NodeFailure", "Drained"):
                continue
            if key in self._held or key in self._probation:
                continue
            if unhealthy:
                self._held.add(key)
                self.requeue.add_rate_limited(("PodGang",) + key, now)
            else:
                # capacity is all back: pacing a placeable gang would only
                # idle it — one immediate solve attempt, then normal
                # probation re-arming if it still does not fit
                self._probation.add(key)
            touched += 1
        for key in sorted(self._held):
            gang = self.store.get("PodGang", key[0], key[1], readonly=True)
            cond = (
                get_condition(gang.status.conditions, COND_PODGANG_SCHEDULED)
                if gang is not None
                else None
            )
            if gang is None or (cond is not None and cond.is_true()):
                self._held.discard(key)
                wq_key = ("PodGang",) + key
                self.requeue.forget(wq_key)
                self.requeue.discard_delayed(wq_key)
                touched += 1
        touched += self._resync_rescues(now)
        return touched

    def _resync_rescues(self, now: float) -> int:
        """Rescue tracking is leader memory too: a gang mid-rescue at
        failover (Scheduled=True, replacement pods not yet bound) would
        complete silently — no GangRescued, no domain verification. Re-prime
        a pending-rescue record for every scheduled gang with unbound pod
        references; the survivors' domain is recomputed from live bindings
        (the lost node's name is gone with the old leader)."""
        primed = 0
        for gang in self.store.scan("PodGang"):
            key = (gang.metadata.namespace, gang.metadata.name)
            if key in self._rescue_pending or key in self._held:
                continue
            cond = get_condition(
                gang.status.conditions, COND_PODGANG_SCHEDULED
            )
            if cond is None or not cond.is_true():
                continue
            whole = all(
                (ref.namespace, ref.name) in self.cluster.bindings
                for group in gang.spec.pod_groups
                for ref in group.pod_references
            )
            if whole:
                continue
            domain_key, domain = self._survivor_domain(gang)
            self._rescue_pending[key] = {
                "namespace": key[0],
                "gang": key[1],
                "lost_node": "(pre-failover)",
                "survivors": dict(self._group_survivors(gang)),
                "domain_key": domain_key,
                "domain": domain,
                "since": now,
            }
            primed += 1
        return primed

    def next_deadline(self) -> Optional[float]:
        """Earliest future moment this monitor will act: a crashed node
        crossing NotReady/Lost, or a backoff release. The harness jumps
        virtual time here when otherwise idle."""
        deadlines = []
        for node in self.cluster.nodes:
            if not node.crashed or node.state == NODE_LOST:
                continue
            threshold = (
                self.not_ready_after
                if node.state == NODE_READY
                else self.lost_after
            )
            deadlines.append(node.last_heartbeat + threshold)
        wake = self.requeue.next_delayed_at()
        if wake is not None:
            deadlines.append(wake)
        if self.failslow_threshold is not None and (
            self.cluster.failslow_names()
            or any(s > 0.0 for s in self._suspicion.values())
        ):
            # suspicion only moves when a tick observes it: while a
            # fail-slow fault is armed (or a score is still decaying) the
            # harness must keep ticking through idle periods, or Degraded
            # entry/exit would stall with virtual time
            deadlines.append(self.store.clock.now() + 1.0)
        return min(deadlines) if deadlines else None

    # -- tick -------------------------------------------------------------

    def tick(self) -> int:
        """One monitor round. Returns the number of actions taken (state
        transitions + pod evictions + gang decisions + backoff moves) so
        the harness's quiescence check sees monitor work as progress."""
        now = self.store.clock.now()
        actions = 0
        actions += self._check_probation()
        newly_lost, recovered, gray_moves = self._refresh_node_states(now)
        actions += len(newly_lost) + gray_moves
        if recovered and self._held:
            # capacity just returned (a lost node rejoined): waiting out
            # the rest of the backoff would idle a placeable gang — release
            # every held gang for an immediate solve round, with failure
            # counts reset (the world changed; stale backoff is meaningless)
            for gang_key in sorted(self._held):
                wq_key = ("PodGang",) + gang_key
                self.requeue.forget(wq_key)
                # drop the scheduled entry too: it would otherwise pop
                # later and grant an extra release outside the pacing
                self.requeue.discard_delayed(wq_key)
                self._probation.add(gang_key)
                actions += 1
            self._held.clear()
        # evict from EVERY lost node each tick, not just newly-lost ones:
        # a binding can appear on an already-Lost node through commit races
        # (and rebuild_bindings on failover), and the no-binding-to-Lost
        # invariant must be level-triggered, not edge-triggered
        lost = [n for n in self.cluster.nodes if n.state == NODE_LOST]
        affected: Dict[GangKey, str] = dict(self._triage_retry)
        self._triage_retry.clear()
        for node in lost:
            actions += self._evict_lost_node(node, affected)
        for key, lost_node in sorted(affected.items()):
            try:
                actions += self._triage_gang(key, lost_node, now)
            except GroveError:
                # transient store outage mid-triage: every step is
                # idempotent — re-run the whole decision next tick
                self._triage_retry[key] = lost_node
        actions += self._release_due(now)
        actions += self._check_rescues(now)
        self._export_gauges(now)
        return actions

    # -- node lifecycle ---------------------------------------------------

    def _refresh_node_states(self, now: float) -> Tuple[List, bool, int]:
        newly_lost = []
        recovered = False
        gray_moves = 0
        hb_floor = 0.0
        if self.failslow_threshold is not None:
            # peer-relative baseline: the healthiest live kubelet's
            # heartbeat age. Observation cadence and idle-time jumps
            # inflate every node's age equally — subtracting the floor
            # cancels them, so a healthy cohort scores 0 and a fail-slow
            # node's lateness is exactly its extra lag over its peers
            live_ages = [
                now - n.last_heartbeat
                for n in self.cluster.nodes
                if not n.crashed
            ]
            hb_floor = min(live_ages) if live_ages else 0.0
        for node in self.cluster.nodes:
            if not node.crashed:
                if self.failslow_threshold is not None:
                    # suspicion lane (gray failures): Ready ⇄ Degraded is
                    # decided by the EWMA, entirely outside the binary
                    # want-compare below — a Degraded node must not emit a
                    # spurious NodeReady while its heartbeats are merely
                    # late-but-inside-grace
                    gray_moves += self._suspect(node, now, hb_floor)
                    if node.state == NODE_DEGRADED:
                        continue
                # a live kubelet heartbeats by definition (heartbeat_tick
                # refreshes the timestamp); large virtual-time jumps must
                # never read as cluster-wide heartbeat loss
                want = NODE_READY
            else:
                age = now - node.last_heartbeat
                # strict comparisons: next_deadline() wakes the harness at
                # exactly last_heartbeat + threshold, and that tick must
                # already observe the transition (<= would wake to a no-op
                # and stall virtual time)
                if age < self.not_ready_after:
                    want = NODE_READY
                elif age < self.lost_after:
                    want = NODE_NOT_READY
                else:
                    want = NODE_LOST
            if want == NODE_READY and node.state == NODE_DEGRADED:
                # crashed fail-slow node still inside the grace window:
                # keep the Degraded mask (recovery goes through the
                # suspicion hysteresis once the kubelet is back, not
                # through the binary lane)
                continue
            if want == node.state:
                continue
            ref = ("Node", "", node.name)
            if want == NODE_NOT_READY:
                EVENTS.record(
                    ref,
                    TYPE_WARNING,
                    REASON_NODE_NOT_READY,
                    f"no heartbeat for {now - node.last_heartbeat:.1f}s "
                    f"(grace {self.lost_after:g}s)",
                )
            elif want == NODE_LOST:
                EVENTS.record(
                    ref,
                    TYPE_WARNING,
                    REASON_NODE_LOST,
                    f"heartbeat grace period ({self.lost_after:g}s) "
                    "exceeded; failing its pods",
                )
                METRICS.inc("node_lost_total")
                newly_lost.append(node)
            elif want == NODE_READY:
                EVENTS.record(
                    ref,
                    TYPE_NORMAL,
                    REASON_NODE_READY,
                    f"heartbeat restored (was {node.state})",
                )
                if node.state == NODE_NOT_READY:
                    # recovered inside the grace window: a flap, no pod
                    # was failed
                    METRICS.inc("node_flaps_total")
                elif node.state == NODE_LOST:
                    recovered = True  # capacity returned to the pool
            node.state = want
        return newly_lost, recovered, gray_moves

    def _suspect(self, node, now: float, hb_floor: float) -> int:
        """Fold one heartbeat-lateness observation into the node's EWMA
        suspicion score and apply the Ready ⇄ Degraded hysteresis. Returns
        the number of state transitions (0 or 1).

        Lateness is PEER-RELATIVE: this node's heartbeat age minus the
        healthiest live node's (`hb_floor`) — fail-slow means "slow
        compared to the cohort", and the subtraction makes the score
        independent of tick cadence and virtual-time jumps. The score is
        a PURE function of the observed lateness trace:
        s ← α·lateness + (1−α)·s, s₀ = 0 — the storm test replays the
        seeded trace through a NumPy oracle and pins equality."""
        lateness = max(0.0, (now - node.last_heartbeat) - hb_floor)
        s = self.failslow_alpha * lateness + (
            1.0 - self.failslow_alpha
        ) * self._suspicion.get(node.name, 0.0)
        if s < 1e-3:
            # clamp the asymptotic decay tail to a true zero so an idle
            # cluster quiesces (next_deadline stops scheduling wake-ups)
            s = 0.0
        self._suspicion[node.name] = s
        ref = ("Node", "", node.name)
        if node.state == NODE_READY and s > self.failslow_threshold:
            node.state = NODE_DEGRADED
            EVENTS.record(
                ref,
                TYPE_WARNING,
                REASON_NODE_DEGRADED,
                f"fail-slow suspicion {s:.2f}s exceeds"
                f" {self.failslow_threshold:g}s (EWMA of heartbeat"
                " lateness); masking from new placements, running pods"
                " stay bound",
            )
            METRICS.inc("node_degraded_total")
            return 1
        if node.state == NODE_DEGRADED and s < self.failslow_recover:
            node.state = NODE_READY
            EVENTS.record(
                ref,
                TYPE_NORMAL,
                REASON_NODE_RECOVERED,
                f"fail-slow suspicion decayed to {s:.2f}s (below"
                f" {self.failslow_recover:g}s); schedulable again",
            )
            METRICS.inc("node_recovered_total")
            return 1
        return 0

    def _evict_lost_node(self, node, affected: Dict[GangKey, str]) -> int:
        """Fail every pod bound to the Lost node: delete it (the PCLQ
        controller recreates it gated) and release its binding/capacity at
        once. Records each touched gang in `affected` for triage."""
        victims = [
            key
            for key, bound in self.cluster.bindings.items()
            if bound == node.name
        ]
        evicted = 0
        for ns, pod_name in victims:
            pod = self.store.get("Pod", ns, pod_name, readonly=True)
            if pod is None:
                # grovelint: disable=GL012 -- the pod's store Deleted event already fired (it is gone from the store), so the delta fold released this charge; only the stale cluster-map entry remains
                self.cluster.bindings.pop((ns, pod_name), None)
                continue
            gang_name = pod.metadata.labels.get(namegen.LABEL_PODGANG)
            if gang_name:
                affected.setdefault((ns, gang_name), node.name)
            try:
                self.store.delete("Pod", ns, pod_name)
            except GroveError as e:
                if e.code != ERR_NOT_FOUND:
                    # transient store outage: keep the binding so the
                    # level-triggered sweep retries next tick
                    continue
            # release the binding only once the pod is actually gone —
            # a kept binding for a live pod stays visible to capacity
            # accounting and survivor counts
            # grovelint: disable=GL012 -- store.delete above just fired the watch event (or NOT_FOUND: it fired earlier); the event is the registration, this pop only syncs the cluster map
            self.cluster.bindings.pop((ns, pod_name), None)
            evicted += 1
        if evicted:
            EVENTS.record(
                ("Node", "", node.name),
                TYPE_WARNING,
                REASON_NODE_LOST,
                f"failed {evicted} pod(s) bound to lost node {node.name}",
            )
            METRICS.inc("node_evicted_pods_total", evicted)
        return evicted

    # -- gang triage: rescue vs. requeue ----------------------------------

    def _group_survivors(self, gang) -> Dict[str, int]:
        # a pod only counts as a survivor on a HEALTHY node: a binding that
        # outlived a failed eviction attempt (store outage) must not make a
        # doomed gang look rescuable. Degraded is NOT unhealthy here — a
        # fail-slow node's pods are alive and running (that is the whole
        # point of the state); counting them dead would terminate gangs a
        # gray failure never broke
        unhealthy = {
            n.name
            for n in self.cluster.nodes
            if n.state in (NODE_NOT_READY, NODE_LOST)
        }
        out: Dict[str, int] = {}
        for group in gang.spec.pod_groups:
            n = 0
            for ref in group.pod_references:
                bound = self.cluster.bindings.get((ref.namespace, ref.name))
                if bound is None or bound in unhealthy:
                    continue
                pod = self.store.get(
                    "Pod", ref.namespace, ref.name, readonly=True
                )
                if pod is not None and not is_terminating(pod):
                    n += 1
            out[group.name] = n
        return out

    def _triage_gang(self, key: GangKey, lost_node: str, now: float) -> int:
        ns, name = key
        gang = self.store.get("PodGang", ns, name, readonly=True)
        if gang is None:
            return 0
        cond = get_condition(gang.status.conditions, COND_PODGANG_SCHEDULED)
        if cond is None or not cond.is_true():
            # the gang was not placed (or is already torn down / requeued):
            # its pending pods flow through the normal solve, nothing to do
            return 0
        survivors = self._group_survivors(gang)
        rescuable = all(
            survivors.get(g.name, 0) >= g.min_replicas
            for g in gang.spec.pod_groups
        )
        if rescuable:
            self._begin_rescue(key, gang, survivors, lost_node, now)
        else:
            self._terminate_and_requeue(key, gang, survivors, lost_node, now)
        return 1

    def _survivor_domain(self, gang) -> Tuple[Optional[str], Optional[str]]:
        """(topology key, domain label) of the survivors when the gang has a
        gang-level required pack — the domain its replacements must rejoin
        (verified at rescue completion and by the chaos harness)."""
        tc = gang.spec.topology_constraint
        required = (
            tc.pack_constraint.required
            if tc is not None and tc.pack_constraint is not None
            else None
        )
        if required is None:
            return None, None
        for group in gang.spec.pod_groups:
            for ref in group.pod_references:
                bound = self.cluster.bindings.get((ref.namespace, ref.name))
                node = self.cluster.node(bound) if bound else None
                if node is not None:
                    return required, node.labels.get(required)
        return required, None

    def _begin_rescue(
        self, key: GangKey, gang, survivors: Dict, lost_node: str, now: float
    ) -> None:
        domain_key, domain = self._survivor_domain(gang)
        self._rescue_pending[key] = {
            "namespace": key[0],
            "gang": key[1],
            "lost_node": lost_node,
            "survivors": dict(survivors),
            "domain_key": domain_key,
            "domain": domain,
            "since": now,
        }

    def _terminate_and_requeue(
        self, key: GangKey, gang, survivors: Dict, lost_node: str, now: float
    ) -> None:
        ns, name = key
        self._rescue_pending.pop(key, None)
        # tear down the remaining pods: a gang below its floor is broken as
        # a unit (gang semantics) — survivors' fragmented capacity returns
        # to the pool and the whole gang re-places atomically later
        for group in gang.spec.pod_groups:
            for ref in group.pod_references:
                try:
                    self.store.delete("Pod", ref.namespace, ref.name)
                except GroveError as e:
                    if e.code != ERR_NOT_FOUND:
                        raise  # tick-level retry re-runs the triage
                # grovelint: disable=GL012 -- store.delete above fired the Deleted watch event (NOT_FOUND means it fired earlier); the delta fold already released the charge
                self.cluster.bindings.pop((ref.namespace, ref.name), None)
        breached = {
            g.name: (survivors.get(g.name, 0), g.min_replicas)
            for g in gang.spec.pod_groups
            if survivors.get(g.name, 0) < g.min_replicas
        }
        message = (
            f"node {lost_node} lost; survivors below MinReplicas "
            f"({', '.join(f'{g}={s}/{m}' for g, (s, m) in sorted(breached.items()))})"
            "; gang terminated and requeued"
        )
        # retry-with-fresh-read like the scheduler's evictions: the status
        # flip and the pod deletions must land together
        for _ in range(4):
            fresh = self.store.get("PodGang", ns, name)
            if fresh is None:
                break
            set_condition(
                fresh.status.conditions,
                Condition(
                    type=COND_PODGANG_DISRUPTION_TARGET,
                    status="True",
                    reason="NodeFailure",
                    message=message,
                ),
                now,
            )
            set_condition(
                fresh.status.conditions,
                Condition(
                    type=COND_PODGANG_SCHEDULED,
                    status="False",
                    reason="NodeFailure",
                    message=message,
                ),
                now,
            )
            fresh.status.phase = PHASE_PENDING
            fresh.status.placement_score = None
            try:
                self.store.update_status(fresh)
                break
            except GroveError as e:
                if e.code != ERR_CONFLICT:
                    raise
        EVENTS.record(
            ("PodGang", ns, name), TYPE_WARNING, REASON_GANG_REQUEUED, message
        )
        METRICS.inc("gang_requeues_total")
        self._held.add(key)
        self._probation.discard(key)
        self.requeue.add_rate_limited(("PodGang", ns, name), now)

    # -- backoff pacing ----------------------------------------------------

    def _release_due(self, now: float) -> int:
        released = 0
        while True:
            key = self.requeue.pop(now)
            if key is None:
                return released
            gang_key = (key[1], key[2])
            if gang_key not in self._held:
                continue  # forgotten meanwhile (gang deleted)
            self._held.discard(gang_key)
            self._probation.add(gang_key)
            EVENTS.record(
                key,
                TYPE_NORMAL,
                REASON_GANG_RELEASED,
                f"backoff expired after {self.requeue.failures(key)} "
                "attempt(s); re-entering the all-or-nothing solve",
            )
            released += 1

    def _check_probation(self) -> int:
        """Gangs released last tick had one solve round: re-arm the ones
        still unscheduled, forget the ones that made it (or vanished)."""
        moved = 0
        now = self.store.clock.now()
        for gang_key in sorted(self._probation):
            ns, name = gang_key
            wq_key = ("PodGang", ns, name)
            gang = self.store.get("PodGang", ns, name, readonly=True)
            cond = (
                get_condition(gang.status.conditions, COND_PODGANG_SCHEDULED)
                if gang is not None
                else None
            )
            if gang is None or (cond is not None and cond.is_true()):
                self._probation.discard(gang_key)
                self.requeue.forget(wq_key)
                moved += 1
                continue
            # still pending: next backoff step (capacity has not returned)
            self._probation.discard(gang_key)
            self._held.add(gang_key)
            self.requeue.add_rate_limited(wq_key, now)
            moved += 1
        return moved

    # -- rescue completion -------------------------------------------------

    def _check_rescues(self, now: float) -> int:
        done = 0
        for key in sorted(self._rescue_pending):
            rec = self._rescue_pending[key]
            ns, name = key
            gang = self.store.get("PodGang", ns, name, readonly=True)
            if gang is None or key in self._held:
                del self._rescue_pending[key]
                continue
            cond = get_condition(
                gang.status.conditions, COND_PODGANG_SCHEDULED
            )
            if cond is None or not cond.is_true():
                # preempted/reclaimed/terminated while rescuing: the gang
                # re-places whole through its own path — not a rescue
                del self._rescue_pending[key]
                continue
            nodes = []
            whole = True
            for group in gang.spec.pod_groups:
                for ref in group.pod_references:
                    bound = self.cluster.bindings.get(
                        (ref.namespace, ref.name)
                    )
                    if bound is None:
                        whole = False
                        break
                    nodes.append(bound)
                if not whole:
                    break
            if not whole:
                continue  # replacements still pending; check next tick
            rec["completed_at"] = now
            rec["placement_nodes"] = nodes
            if rec["domain_key"] is not None and rec["domain"] is not None:
                rec["rejoined_domain"] = all(
                    (n := self.cluster.node(nn)) is not None
                    and n.labels.get(rec["domain_key"]) == rec["domain"]
                    for nn in nodes
                )
            EVENTS.record(
                ("PodGang", ns, name),
                TYPE_NORMAL,
                REASON_GANG_RESCUED,
                f"gang whole again after losing {rec['lost_node']}"
                + (
                    f"; replacements rejoined {rec['domain_key']}="
                    f"{rec['domain']}"
                    if rec.get("domain") is not None
                    else ""
                ),
            )
            METRICS.inc("gang_rescues_total")
            self.rescues.append(rec)
            del self._rescue_pending[key]
            done += 1
        return done

    # -- observability -----------------------------------------------------

    def _export_gauges(self, now: float) -> None:
        counts = {
            NODE_READY: 0,
            NODE_NOT_READY: 0,
            NODE_LOST: 0,
            NODE_DEGRADED: 0,
        }
        max_age = 0.0
        for node in self.cluster.nodes:
            counts[node.state] = counts.get(node.state, 0) + 1
            if node.crashed:
                max_age = max(max_age, now - node.last_heartbeat)
        METRICS.set("nodes_ready", counts[NODE_READY])
        METRICS.set("nodes_not_ready", counts[NODE_NOT_READY])
        METRICS.set("nodes_lost", counts[NODE_LOST])
        METRICS.set("nodes_degraded", counts[NODE_DEGRADED])
        METRICS.set(
            "node_suspicion_max_seconds",
            max(self._suspicion.values()) if self._suspicion else 0.0,
        )
        METRICS.set("node_heartbeat_age_max_seconds", max_age)
        METRICS.set("gangs_in_requeue_backoff", len(self._held))
        METRICS.set("gang_rescues_pending", len(self._rescue_pending))

    def node_snapshot(self) -> List[dict]:
        """Wire-shape node table for GET /nodes and `cli nodes`
        (docs/observability.md)."""
        now = self.store.clock.now()
        bound_counts: Dict[str, int] = {}
        # list() snapshot: GET /nodes serves from apiserver threads while
        # the sim/scheduler thread binds and evicts concurrently — iterating
        # the live dict would race ("dict changed size during iteration")
        for _key, bound in list(self.cluster.bindings.items()):
            bound_counts[bound] = bound_counts.get(bound, 0) + 1
        drains = self.drain_states() if self.drain_states is not None else {}
        return [
            {
                "name": n.name,
                "state": n.state,
                "cordoned": n.cordoned,
                "schedulable": n.schedulable,
                # "" | Draining | Drained (docs/robustness.md drain flow)
                "drain": drains.get(n.name, ""),
                "heartbeatAgeSeconds": round(max(0.0, now - n.last_heartbeat), 3),
                # EWMA fail-slow suspicion (0.0 while detection is off)
                "suspicion": round(self._suspicion.get(n.name, 0.0), 3),
                "capacity": dict(n.capacity),
                "labels": dict(n.labels),
                "boundPods": bound_counts.get(n.name, 0),
            }
            for n in list(self.cluster.nodes)
        ]
