"""PodCliqueSet reconciler: get → delete-flow → spec-flow → status-flow.

Re-host of /root/reference/operator/internal/controller/podcliqueset/
{reconciler.go,reconcilespec.go,reconcilestatus.go}: ensureFinalizer →
processGenerationHashChange → sync ordered components (SA, Role, RoleBinding,
SATokenSecret, HeadlessService, HPA, PCSReplica, PodClique, PCSG, PodGang —
reconcilespec.go:202-215) → updateObservedGeneration; status aggregates
replica availability and PodGang phases.
"""

from __future__ import annotations

from grove_tpu.api import names as namegen
from grove_tpu.api.hashing import compute_pcs_generation_hash
from grove_tpu.api.meta import get_condition
from grove_tpu.api.types import (
    COND_MIN_AVAILABLE_BREACHED,
    COND_POD_CLIQUE_SCHEDULED,
    COND_PODGANG_SCHEDULED,
    PHASE_PENDING,
    PHASE_RUNNING,
    PHASE_STARTING,
    PCSRollingUpdateProgress,
    PodCliqueSet,
    PodGangStatusSummary,
)
from grove_tpu.controller.common import (
    FINALIZER,
    OperatorContext,
    record_last_error,
    write_status_if_changed,
)
from grove_tpu.controller.podcliqueset.components import (
    infra,
    podclique,
    podgang,
    replica as replica_component,
    rollingupdate,
    scalinggroup,
)
from grove_tpu.runtime.errors import GroveError
from grove_tpu.runtime.flow import (
    ReconcileStepResult,
    continue_reconcile,
    do_not_requeue,
    reconcile_after,
    reconcile_with_errors,
)
from grove_tpu.runtime.workqueue import Key

CHILD_KINDS_CASCADE = [
    "PodGang",
    "PodClique",
    "PodCliqueScalingGroup",
    "Service",
    "HorizontalPodAutoscaler",
    "ServiceAccount",
    "Role",
    "RoleBinding",
    "Secret",
]


class PodCliqueSetReconciler:
    def __init__(self, ctx: OperatorContext) -> None:
        self.ctx = ctx

    def reconcile(self, key: Key) -> ReconcileStepResult:
        _, ns, name = key
        # readonly view: the spec flow READS the PCS (components take it as
        # input); the rare writes (finalizer add, hash change, observed
        # generation) each re-get a mutable copy
        pcs = self.ctx.store.get("PodCliqueSet", ns, name, readonly=True)
        if pcs is None:
            return do_not_requeue()
        if pcs.metadata.deletion_timestamp is not None:
            return self._reconcile_delete(pcs)
        try:
            result = self._reconcile_spec(pcs)
            self._reconcile_status(ns, name)
        except GroveError as err:
            record_last_error(self.ctx, "PodCliqueSet", ns, name, err)
            return reconcile_with_errors(f"pcs {ns}/{name}", err)
        return result

    # -- delete flow -----------------------------------------------------

    def _reconcile_delete(self, pcs: PodCliqueSet) -> ReconcileStepResult:
        ns = pcs.metadata.namespace
        selector = namegen.default_labels(pcs.metadata.name)
        remaining = 0
        for kind in CHILD_KINDS_CASCADE:
            victims = self.ctx.store.list(kind, ns, selector)
            for v in victims:
                if v.metadata.deletion_timestamp is None:
                    self.ctx.store.delete(kind, ns, v.metadata.name)
            remaining += len(self.ctx.store.list(kind, ns, selector))
        if remaining:
            # children drain asynchronously (their finalizers); check back
            return reconcile_after(0.001, "waiting for child deletion")
        self.ctx.store.remove_finalizer(
            "PodCliqueSet", ns, pcs.metadata.name, FINALIZER
        )
        return do_not_requeue()

    # -- spec flow -------------------------------------------------------

    def _reconcile_spec(self, pcs: PodCliqueSet) -> ReconcileStepResult:
        ns, name = pcs.metadata.namespace, pcs.metadata.name
        if FINALIZER not in pcs.metadata.finalizers:
            pcs = self.ctx.store.get("PodCliqueSet", ns, name)
            if pcs is None:  # deleted between view and mutable re-get
                return continue_reconcile()
            pcs.metadata.finalizers.append(FINALIZER)
            pcs = self.ctx.store.update(pcs, bump_generation=False)

        pcs = self._process_generation_hash(pcs)

        infra.sync_rbac(self.ctx, pcs)
        infra.sync_headless_services(self.ctx, pcs)
        infra.sync_hpas(self.ctx, pcs)
        breach_wait = replica_component.sync(self.ctx, pcs)
        update_wait = rollingupdate.sync(self.ctx, pcs)
        podclique.sync(self.ctx, pcs)
        scalinggroup.sync(self.ctx, pcs)
        podgang.sync(self.ctx, pcs)

        view = self.ctx.store.get("PodCliqueSet", ns, name, readonly=True)
        if (
            view is not None
            and view.metadata.deletion_timestamp is None
            and view.status.observed_generation != view.metadata.generation
        ):
            fresh = self.ctx.store.get("PodCliqueSet", ns, name)
            if fresh is not None and fresh.metadata.deletion_timestamp is None:
                fresh.status.observed_generation = fresh.metadata.generation
                self.ctx.store.update_status(fresh)

        waits = [w for w in (breach_wait, update_wait) if w is not None]
        if waits:
            return reconcile_after(min(waits), "breach/rolling-update wait")
        return continue_reconcile()

    def _process_generation_hash(self, pcs: PodCliqueSet) -> PodCliqueSet:
        """reconcilespec.go:72-123: template hash change starts a rolling
        update (progress tracked in status). `pcs` may be a readonly view —
        the steady state (hash unchanged) never touches the store; a change
        re-gets a mutable copy for the write."""
        new_hash = compute_pcs_generation_hash(pcs)
        if pcs.status.current_generation_hash == new_hash:
            return pcs
        fresh = self.ctx.store.get(
            "PodCliqueSet", pcs.metadata.namespace, pcs.metadata.name
        )
        if fresh is None or fresh.metadata.deletion_timestamp is not None:
            return pcs
        if fresh.status.current_generation_hash is None:
            fresh.status.current_generation_hash = new_hash
            return self.ctx.store.update_status(fresh)
        if fresh.status.current_generation_hash != new_hash:
            fresh.status.current_generation_hash = new_hash
            fresh.status.rolling_update_progress = PCSRollingUpdateProgress(
                update_started_at=self.ctx.clock.now()
            )
            self.ctx.record_event(
                "PodCliqueSet",
                "RollingUpdateStarted",
                fresh.metadata.name,
                namespace=fresh.metadata.namespace,
                name=fresh.metadata.name,
            )
            return self.ctx.store.update_status(fresh)
        return fresh

    # -- status flow -----------------------------------------------------

    def _reconcile_status(self, ns: str, name: str) -> None:
        # compute on the zero-copy view; write only on difference (the
        # steady state then costs no serialization at all)
        view = self.ctx.store.get("PodCliqueSet", ns, name, readonly=True)
        if view is None or view.metadata.deletion_timestamp is not None:
            return
        gangs = self.ctx.store.scan(
            "PodGang",
            ns,
            {
                **namegen.default_labels(name),
                namegen.LABEL_COMPONENT: namegen.COMPONENT_PODGANG,
            },
            cached=True,
        )
        from grove_tpu.api.meta import deep_copy

        st = deep_copy(view.status)
        st.replicas = view.spec.replicas
        st.pod_gang_statuses = [
            PodGangStatusSummary(
                name=g.metadata.name,
                phase=g.status.phase,
                conditions=list(g.status.conditions),
            )
            for g in gangs
        ]
        st.available_replicas = self._count_available_replicas(view)
        st.updated_replicas = self._count_updated_replicas(view)
        st.selector = f"{namegen.LABEL_PART_OF}={name}"
        st.last_errors = []  # cleared on a clean reconcile
        write_status_if_changed(self.ctx, "PodCliqueSet", ns, name, st)

    def _count_updated_replicas(self, pcs: PodCliqueSet) -> int:
        """Replicas whose every PCLQ carries the current template hash with
        all pods updated (podcliqueset.go:68-70 UpdatedReplicas)."""
        from grove_tpu.api.hashing import pod_template_hash_for
        from grove_tpu.controller.podcliqueset.components.rollingupdate import (
            _clique_template_name,
        )

        ns = pcs.metadata.namespace
        tmpl = pcs.spec.template
        # hash depends only on the template — compute once per clique
        want_hash = {
            clique.name: pod_template_hash_for(pcs, clique.name)
            for clique in tmpl.cliques
        }
        count = 0
        for replica in range(pcs.spec.replicas):
            sel = {
                **namegen.default_labels(pcs.metadata.name),
                namegen.LABEL_PCS_REPLICA_INDEX: str(replica),
            }
            pclqs = list(self.ctx.store.scan("PodClique", ns, sel, cached=True))
            if not pclqs:
                continue
            updated = True
            for pclq in pclqs:
                want = want_hash.get(_clique_template_name(pcs, pclq))
                if want is None:
                    continue
                if (
                    pclq.metadata.labels.get(namegen.LABEL_POD_TEMPLATE_HASH)
                    != want
                    or pclq.status.updated_replicas < pclq.spec.replicas
                ):
                    updated = False
                    break
            if updated:
                count += 1
        return count

    def _count_available_replicas(self, pcs: PodCliqueSet) -> int:
        """A PCS replica is available when every standalone PCLQ is actually
        scheduled up to minAvailable (PodCliqueScheduled=True), every PCSG has
        scheduledReplicas >= minAvailable, and none of them currently breach
        MinAvailable (podcliqueset/reconcilestatus.go availability rule —
        never count a never-scheduled replica as available)."""
        ns = pcs.metadata.namespace
        count = 0
        for replica in range(pcs.spec.replicas):
            sel = {
                **namegen.default_labels(pcs.metadata.name),
                namegen.LABEL_PCS_REPLICA_INDEX: str(replica),
            }
            pclqs = [
                p
                for p in self.ctx.store.scan("PodClique", ns, sel, cached=True)
                if p.metadata.labels.get(namegen.LABEL_COMPONENT)
                == namegen.COMPONENT_PCS_PODCLIQUE
            ]
            pcsgs = list(self.ctx.store.scan(
                "PodCliqueScalingGroup", ns, sel, cached=True
            ))
            entities = pclqs + pcsgs
            if not entities:
                continue
            scheduled = all(
                (c := get_condition(p.status.conditions, COND_POD_CLIQUE_SCHEDULED))
                is not None
                and c.is_true()
                for p in pclqs
            ) and all(
                g.status.scheduled_replicas >= g.spec.min_available for g in pcsgs
            )
            breached = any(
                (c := get_condition(e.status.conditions, COND_MIN_AVAILABLE_BREACHED))
                is not None
                and c.is_true()
                for e in entities
            )
            if scheduled and not breached:
                count += 1
        return count
