"""PodCliqueSet reconciler: get → delete-flow → spec-flow → status-flow.

Re-host of /root/reference/operator/internal/controller/podcliqueset/
{reconciler.go,reconcilespec.go,reconcilestatus.go}: ensureFinalizer →
processGenerationHashChange → sync ordered components (SA, Role, RoleBinding,
SATokenSecret, HeadlessService, HPA, PCSReplica, PodClique, PCSG, PodGang —
reconcilespec.go:202-215) → updateObservedGeneration; status aggregates
replica availability and PodGang phases.
"""

from __future__ import annotations

from grove_tpu.api import names as namegen
from grove_tpu.api.hashing import compute_pcs_generation_hash
from grove_tpu.api.meta import get_condition
from grove_tpu.api.types import (
    COND_MIN_AVAILABLE_BREACHED,
    COND_POD_CLIQUE_SCHEDULED,
    COND_PODGANG_SCHEDULED,
    PHASE_PENDING,
    PHASE_RUNNING,
    PHASE_STARTING,
    PCSRollingUpdateProgress,
    PodCliqueSet,
    PodGangStatusSummary,
)
from grove_tpu.controller.common import (
    FINALIZER,
    OperatorContext,
    record_last_error,
    write_status_if_changed,
)
from grove_tpu.controller.podcliqueset.components import (
    infra,
    podclique,
    podgang,
    replica as replica_component,
    rollingupdate,
    scalinggroup,
)
from grove_tpu.runtime.errors import GroveError
from grove_tpu.runtime.flow import (
    ReconcileStepResult,
    continue_reconcile,
    do_not_requeue,
    reconcile_after,
    reconcile_with_errors,
)
from grove_tpu.runtime.workqueue import Key

CHILD_KINDS_CASCADE = [
    "PodGang",
    "PodClique",
    "PodCliqueScalingGroup",
    "Service",
    "HorizontalPodAutoscaler",
    "ServiceAccount",
    "Role",
    "RoleBinding",
    "Secret",
]


class ChildSnapshot:
    """ONE informer-view fetch of a set's children per reconcile.

    Under cache lag the cached view is FROZEN for the whole drain round
    (events apply to it only at round start), so every component and the
    status flow can be served from this single snapshot instead of
    re-scanning per component — the "one component build" of the batched
    drain. Built only for cache-lag stores; live-read stores keep their
    per-component scans (committed state can move mid-reconcile there).
    All held objects are zero-copy readonly views."""

    __slots__ = ("_ctx", "_ns", "_pcs_name", "pclqs", "pcsgs", "_gangs", "_pods")

    def __init__(self, ctx: OperatorContext, ns: str, pcs_name: str) -> None:
        self._ctx = ctx
        self._ns = ns
        self._pcs_name = pcs_name
        sel = namegen.default_labels(pcs_name)
        self.pclqs = list(ctx.store.scan("PodClique", ns, sel, cached=True))
        self.pcsgs = list(
            ctx.store.scan("PodCliqueScalingGroup", ns, sel, cached=True)
        )
        self._gangs = None
        self._pods = None

    def gangs(self):
        """The set's PodGangs (component-labeled), lazily fetched."""
        if self._gangs is None:
            self._gangs = list(
                self._ctx.store.scan(
                    "PodGang",
                    self._ns,
                    {
                        **namegen.default_labels(self._pcs_name),
                        namegen.LABEL_COMPONENT: namegen.COMPONENT_PODGANG,
                    },
                    cached=True,
                )
            )
        return self._gangs

    def pods_by_pclq(self):
        """The set's pods grouped by their PodClique label — one scan
        instead of one per constituent PCLQ."""
        if self._pods is None:
            grouped: dict = {}
            for pod in self._ctx.store.scan(
                "Pod",
                self._ns,
                namegen.default_labels(self._pcs_name),
                cached=True,
            ):
                pclq = pod.metadata.labels.get(namegen.LABEL_PODCLIQUE)
                if pclq is not None:
                    grouped.setdefault(pclq, []).append(pod)
            self._pods = grouped
        return self._pods

    def pclqs_for_replica(self, replica: int, component: str = None):
        idx = str(replica)
        return [
            p
            for p in self.pclqs
            if p.metadata.labels.get(namegen.LABEL_PCS_REPLICA_INDEX) == idx
            and (
                component is None
                or p.metadata.labels.get(namegen.LABEL_COMPONENT) == component
            )
        ]

    def pcsgs_for_replica(self, replica: int):
        idx = str(replica)
        return [
            g
            for g in self.pcsgs
            if g.metadata.labels.get(namegen.LABEL_PCS_REPLICA_INDEX) == idx
        ]


class PodCliqueSetReconciler:
    def __init__(self, ctx: OperatorContext) -> None:
        self.ctx = ctx

    def reconcile(self, key: Key) -> ReconcileStepResult:
        _, ns, name = key
        # readonly view: the spec flow READS the PCS (components take it as
        # input); the rare writes (finalizer add, hash change, observed
        # generation) each re-get a mutable copy
        pcs = self.ctx.store.get("PodCliqueSet", ns, name, readonly=True)
        if pcs is None:
            return do_not_requeue()
        if pcs.metadata.deletion_timestamp is not None:
            return self._reconcile_delete(pcs)
        snap = (
            ChildSnapshot(self.ctx, ns, name)
            if self.ctx.store.cache_lag
            else None
        )
        try:
            result = self._reconcile_spec(pcs, snap)
            self._reconcile_status(ns, name, snap)
        except GroveError as err:
            record_last_error(self.ctx, "PodCliqueSet", ns, name, err)
            return reconcile_with_errors(f"pcs {ns}/{name}", err)
        return result

    # -- delete flow -----------------------------------------------------

    def _reconcile_delete(self, pcs: PodCliqueSet) -> ReconcileStepResult:
        ns = pcs.metadata.namespace
        selector = namegen.default_labels(pcs.metadata.name)
        remaining = 0
        for kind in CHILD_KINDS_CASCADE:
            victims = self.ctx.store.list(kind, ns, selector)
            for v in victims:
                if v.metadata.deletion_timestamp is None:
                    self.ctx.store.delete(kind, ns, v.metadata.name)
            remaining += len(self.ctx.store.list(kind, ns, selector))
        if remaining:
            # children drain asynchronously (their finalizers); check back
            return reconcile_after(0.001, "waiting for child deletion")
        self.ctx.store.remove_finalizer(
            "PodCliqueSet", ns, pcs.metadata.name, FINALIZER
        )
        return do_not_requeue()

    # -- spec flow -------------------------------------------------------

    def _reconcile_spec(
        self, pcs: PodCliqueSet, snap: ChildSnapshot = None
    ) -> ReconcileStepResult:
        ns, name = pcs.metadata.namespace, pcs.metadata.name
        if FINALIZER not in pcs.metadata.finalizers:
            from grove_tpu.runtime.store import commit_finalizer_add

            pcs = commit_finalizer_add(self.ctx.store, pcs, FINALIZER)
            if pcs is None:  # deleted between view and write
                return continue_reconcile()

        pcs = self._process_generation_hash(pcs)

        infra.sync_rbac(self.ctx, pcs)
        infra.sync_headless_services(self.ctx, pcs)
        infra.sync_hpas(self.ctx, pcs)
        breach_wait = replica_component.sync(self.ctx, pcs, snap)
        update_wait = rollingupdate.sync(self.ctx, pcs)
        podclique.sync(self.ctx, pcs)
        scalinggroup.sync(self.ctx, pcs)
        podgang.sync(self.ctx, pcs, snap)

        view = self.ctx.store.get("PodCliqueSet", ns, name, readonly=True)
        if (
            view is not None
            and view.metadata.deletion_timestamp is None
            and view.status.observed_generation != view.metadata.generation
        ):
            fresh = self.ctx.store.get("PodCliqueSet", ns, name)
            if fresh is not None and fresh.metadata.deletion_timestamp is None:
                fresh.status.observed_generation = fresh.metadata.generation
                self.ctx.store.update_status(fresh)

        waits = [w for w in (breach_wait, update_wait) if w is not None]
        if waits:
            return reconcile_after(min(waits), "breach/rolling-update wait")
        return continue_reconcile()

    def _process_generation_hash(self, pcs: PodCliqueSet) -> PodCliqueSet:
        """reconcilespec.go:72-123: template hash change starts a rolling
        update (progress tracked in status). `pcs` may be a readonly view —
        the steady state (hash unchanged) never touches the store; a change
        re-gets a mutable copy for the write."""
        new_hash = compute_pcs_generation_hash(pcs)
        if pcs.status.current_generation_hash == new_hash:
            return pcs
        fresh = self.ctx.store.get(
            "PodCliqueSet", pcs.metadata.namespace, pcs.metadata.name
        )
        if fresh is None or fresh.metadata.deletion_timestamp is not None:
            return pcs
        if fresh.status.current_generation_hash is None:
            fresh.status.current_generation_hash = new_hash
            return self.ctx.store.update_status(fresh)
        if fresh.status.current_generation_hash != new_hash:
            fresh.status.current_generation_hash = new_hash
            fresh.status.rolling_update_progress = PCSRollingUpdateProgress(
                update_started_at=self.ctx.clock.now()
            )
            self.ctx.record_event(
                "PodCliqueSet",
                "RollingUpdateStarted",
                fresh.metadata.name,
                namespace=fresh.metadata.namespace,
                name=fresh.metadata.name,
            )
            return self.ctx.store.update_status(fresh)
        return fresh

    # -- status flow -----------------------------------------------------

    def _reconcile_status(
        self, ns: str, name: str, snap: ChildSnapshot = None
    ) -> None:
        # compute on the zero-copy view; write only on difference (the
        # steady state then costs no serialization at all)
        view = self.ctx.store.get("PodCliqueSet", ns, name, readonly=True)
        if view is None or view.metadata.deletion_timestamp is not None:
            return
        gangs = (
            snap.gangs()
            if snap is not None
            else self.ctx.store.scan(
                "PodGang",
                ns,
                {
                    **namegen.default_labels(name),
                    namegen.LABEL_COMPONENT: namegen.COMPONENT_PODGANG,
                },
                cached=True,
            )
        )
        import copy as _copy

        # shallow status clone: every bulky field is REBUILT fresh below
        # (pod_gang_statuses, last_errors) or left untouched-and-shared
        # (conditions, rolling_update_progress — written only by flows that
        # work on their own mutable PCS copies), so a deep copy of the old
        # status would only pickle data about to be thrown away
        st = _copy.copy(view.status)
        st.replicas = view.spec.replicas
        st.pod_gang_statuses = [
            PodGangStatusSummary(
                name=g.metadata.name,
                phase=g.status.phase,
                conditions=list(g.status.conditions),
            )
            for g in gangs
        ]
        st.available_replicas = self._count_available_replicas(view, snap)
        st.updated_replicas = self._count_updated_replicas(view, snap)
        st.selector = f"{namegen.LABEL_PART_OF}={name}"
        st.last_errors = []  # cleared on a clean reconcile
        write_status_if_changed(self.ctx, "PodCliqueSet", ns, name, st)

    def _count_updated_replicas(
        self, pcs: PodCliqueSet, snap: ChildSnapshot = None
    ) -> int:
        """Replicas whose every PCLQ carries the current template hash with
        all pods updated (podcliqueset.go:68-70 UpdatedReplicas)."""
        from grove_tpu.api.hashing import pod_template_hash_for
        from grove_tpu.controller.podcliqueset.components.rollingupdate import (
            _clique_template_name,
        )

        ns = pcs.metadata.namespace
        tmpl = pcs.spec.template
        # hash depends only on the template — compute once per clique
        want_hash = {
            clique.name: pod_template_hash_for(pcs, clique.name)
            for clique in tmpl.cliques
        }
        count = 0
        for replica in range(pcs.spec.replicas):
            if snap is not None:
                pclqs = snap.pclqs_for_replica(replica)
            else:
                sel = {
                    **namegen.default_labels(pcs.metadata.name),
                    namegen.LABEL_PCS_REPLICA_INDEX: str(replica),
                }
                pclqs = list(
                    self.ctx.store.scan("PodClique", ns, sel, cached=True)
                )
            if not pclqs:
                continue
            updated = True
            for pclq in pclqs:
                want = want_hash.get(_clique_template_name(pcs, pclq))
                if want is None:
                    continue
                if (
                    pclq.metadata.labels.get(namegen.LABEL_POD_TEMPLATE_HASH)
                    != want
                    or pclq.status.updated_replicas < pclq.spec.replicas
                ):
                    updated = False
                    break
            if updated:
                count += 1
        return count

    def _count_available_replicas(
        self, pcs: PodCliqueSet, snap: ChildSnapshot = None
    ) -> int:
        """A PCS replica is available when every standalone PCLQ is actually
        scheduled up to minAvailable (PodCliqueScheduled=True), every PCSG has
        scheduledReplicas >= minAvailable, and none of them currently breach
        MinAvailable (podcliqueset/reconcilestatus.go availability rule —
        never count a never-scheduled replica as available)."""
        ns = pcs.metadata.namespace
        count = 0
        for replica in range(pcs.spec.replicas):
            if snap is not None:
                pclqs = snap.pclqs_for_replica(
                    replica, namegen.COMPONENT_PCS_PODCLIQUE
                )
                pcsgs = snap.pcsgs_for_replica(replica)
            else:
                sel = {
                    **namegen.default_labels(pcs.metadata.name),
                    namegen.LABEL_PCS_REPLICA_INDEX: str(replica),
                }
                pclqs = [
                    p
                    for p in self.ctx.store.scan(
                        "PodClique", ns, sel, cached=True
                    )
                    if p.metadata.labels.get(namegen.LABEL_COMPONENT)
                    == namegen.COMPONENT_PCS_PODCLIQUE
                ]
                pcsgs = list(self.ctx.store.scan(
                    "PodCliqueScalingGroup", ns, sel, cached=True
                ))
            entities = pclqs + pcsgs
            if not entities:
                continue
            scheduled = all(
                (c := get_condition(p.status.conditions, COND_POD_CLIQUE_SCHEDULED))
                is not None
                and c.is_true()
                for p in pclqs
            ) and all(
                g.status.scheduled_replicas >= g.spec.min_available for g in pcsgs
            )
            breached = any(
                (c := get_condition(e.status.conditions, COND_MIN_AVAILABLE_BREACHED))
                is not None
                and c.is_true()
                for e in entities
            )
            if scheduled and not breached:
                count += 1
        return count
