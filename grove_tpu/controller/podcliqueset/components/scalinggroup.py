"""PCS scalinggroup component: PodCliqueScalingGroup CRs from template configs.

Re-host of /root/reference/operator/internal/controller/podcliqueset/components/
podcliquescalinggroup/podcliquescalinggroup.go (250 LoC). Replicas on an
existing PCSG are owned by its HPA (scale subresource) — sync must not clobber
them back to the template value.
"""

from __future__ import annotations

from typing import Dict

from grove_tpu.api import names as namegen
from grove_tpu.api.meta import ObjectMeta
from grove_tpu.api.types import (
    PodCliqueScalingGroup,
    PodCliqueScalingGroupSpec,
    PodCliqueSet,
)
from grove_tpu.controller.common import OperatorContext


def sync(ctx: OperatorContext, pcs: PodCliqueSet) -> None:
    ns = pcs.metadata.namespace
    selector = {
        **namegen.default_labels(pcs.metadata.name),
        namegen.LABEL_COMPONENT: namegen.COMPONENT_PCSG,
    }
    existing_names = {
        g.metadata.name
        for g in ctx.store.scan("PodCliqueScalingGroup", ns, selector)
    }

    def build() -> Dict[str, PodCliqueScalingGroup]:
        out: Dict[str, PodCliqueScalingGroup] = {}
        for replica in range(pcs.spec.replicas):
            for cfg in pcs.spec.template.pod_clique_scaling_group_configs:
                fqn = namegen.pcsg_name(pcs.metadata.name, replica, cfg.name)
                labels = dict(namegen.default_labels(pcs.metadata.name))
                labels[namegen.LABEL_COMPONENT] = namegen.COMPONENT_PCSG
                labels[namegen.LABEL_PCS_REPLICA_INDEX] = str(replica)
                labels[namegen.LABEL_PCSG] = fqn
                out[fqn] = PodCliqueScalingGroup(
                    metadata=ObjectMeta(name=fqn, namespace=ns, labels=labels),
                    spec=PodCliqueScalingGroupSpec(
                        replicas=cfg.replicas or 1,
                        min_available=cfg.min_available or 1,
                        clique_names=list(cfg.clique_names),
                    ),
                )
        return out

    # pure function of (uid, generation) — see podclique.sync
    expected = ctx.desired_cache(
        ("pcsg", pcs.metadata.uid, pcs.metadata.generation), build
    )

    for name, pcsg in expected.items():
        if name not in existing_names:
            # share=True: memoized desired object, reused read-only (see
            # create_or_adopt)
            ctx.store.create(pcsg, share=True)
            ctx.record_event(
                "PodCliqueScalingGroup",
                "PCSGCreateSuccessful",
                name,
                namespace=ns,
                name=name,
            )
        # existing PCSGs keep their (possibly HPA-scaled) replicas

    for name in existing_names - expected.keys():
        ctx.store.delete("PodCliqueScalingGroup", ns, name)
        ctx.record_event(
            "PodCliqueScalingGroup",
            "PCSGDeleteSuccessful",
            name,
            namespace=ns,
            name=name,
        )
