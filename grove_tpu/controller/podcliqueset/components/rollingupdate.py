"""PCS rolling-update orchestration: one replica at a time.

Re-host of /root/reference/operator/internal/controller/podcliqueset/components/
podcliquesetreplica/rollingupdate.go:39-260:
- triggered by a generation-hash change (reconcilespec.go:72-123; the
  reconciler seeds status.rolling_update_progress)
- replica pick order (rollingupdate.go:196-223): no-scheduled-pods first,
  then MinAvailableBreached-but-not-expired, then ascending index
- the selected replica's PodCliques (standalone + scaling-group-owned) get
  the new template spec + pod-template-hash pushed atomically, plus the
  update-in-progress annotation that turns MinAvailableBreached Unknown
  (podclique/reconcilestatus.go UpdateInProgress) so the gang terminator
  never fires mid-update
- a replica completes when every PCLQ reports updatedReplicas >= replicas and
  ready >= minAvailable; then the next replica is picked; when none remain,
  update_ended_at is stamped
"""

from __future__ import annotations

from typing import List, Optional

from grove_tpu.api import names as namegen
from grove_tpu.api.hashing import pod_template_hash_for
from grove_tpu.api.meta import get_condition
from grove_tpu.api.types import (
    COND_MIN_AVAILABLE_BREACHED,
    PCSReplicaRollingUpdateProgress,
    PodCliqueSet,
)
from grove_tpu.controller.common import OperatorContext
from grove_tpu.controller.podclique.status import UPDATE_IN_PROGRESS_ANNOTATION


def sync(ctx: OperatorContext, pcs: PodCliqueSet) -> Optional[float]:
    """Run one step of the rolling update. Returns a requeue delay while the
    update is in flight, None when idle/complete.

    `pcs` may be the reconciler's readonly view: the steady state (no update
    in flight) returns without touching the store; an ACTIVE update switches
    to a private mutable copy for the whole step (this flow tracks its
    progress in pcs.status)."""
    progress = pcs.status.rolling_update_progress
    if progress is None or progress.update_ended_at is not None:
        return None
    pcs = ctx.store.get("PodCliqueSet", pcs.metadata.namespace, pcs.metadata.name)
    if pcs is None or pcs.metadata.deletion_timestamp is not None:
        return None
    progress = pcs.status.rolling_update_progress
    if progress is None or progress.update_ended_at is not None:
        return None

    current = progress.currently_updating
    if current is not None:
        if not _replica_update_done(ctx, pcs, current.replica_index):
            _push_template_to_replica(ctx, pcs, current.replica_index)
            return 2.0
        _complete_replica(ctx, pcs, current.replica_index)
        pcs = ctx.store.get("PodCliqueSet", pcs.metadata.namespace, pcs.metadata.name)
        progress = pcs.status.rolling_update_progress

    next_replica = _pick_next_replica(ctx, pcs)
    if next_replica is not None and not _disruption_granted(
        ctx, pcs, next_replica
    ):
        # the replica's gangs are protected right now (disruptionBudget /
        # quiet window / storm breaker — grove_tpu/disruption): keep the
        # update pending and retry; the broker emitted DisruptionThrottled
        return 2.0
    if next_replica is None:
        progress.update_ended_at = ctx.clock.now()
        progress.currently_updating = None
        ctx.store.update_status(pcs)
        ctx.record_event(
            "PodCliqueSet",
            "RollingUpdateCompleted",
            pcs.metadata.name,
            namespace=pcs.metadata.namespace,
            name=pcs.metadata.name,
        )
        return None
    progress.currently_updating = PCSReplicaRollingUpdateProgress(
        replica_index=next_replica, update_started_at=ctx.clock.now()
    )
    ctx.store.update_status(pcs)
    ctx.record_event(
        "PodCliqueSet",
        "RollingUpdateReplicaStarted",
        f"{pcs.metadata.name} replica {next_replica}",
        namespace=pcs.metadata.namespace,
        name=pcs.metadata.name,
    )
    _push_template_to_replica(ctx, pcs, next_replica)
    return 2.0


# ---------------------------------------------------------------------------
# disruption gate (grove_tpu/disruption, docs/robustness.md)
# ---------------------------------------------------------------------------


def _replica_gangs(ctx: OperatorContext, pcs: PodCliqueSet, replica: int) -> List:
    """Every PodGang the replica owns: the base gang `{pcs}-{replica}` plus
    scaled gangs named under its PCSGs (`{pcs}-{replica}-{sg}-{i}`)."""
    base = namegen.base_podgang_name(pcs.metadata.name, replica)
    prefix = f"{base}-"
    return [
        g
        for g in ctx.store.list(
            "PodGang",
            pcs.metadata.namespace,
            namegen.default_labels(pcs.metadata.name),
        )
        if g.metadata.name == base or g.metadata.name.startswith(prefix)
    ]


def _disruption_granted(
    ctx: OperatorContext, pcs: PodCliqueSet, replica: int
) -> bool:
    """Rolling updates are voluntary disruptions: before the replica's
    cliques get the new template (and their pods die), the whole replica's
    gang set must clear the broker in one grant — and the granted gangs
    are marked DisruptionTarget=RollingUpdate, so the per-PCS budget
    invariant and gauges see a mid-update replica exactly like a drained
    one (a concurrent drain on the same set is then denied)."""
    if ctx.disruption is None or not ctx.disruption.active():
        return True
    gangs = _replica_gangs(ctx, pcs, replica)
    if not gangs:
        return True
    if not ctx.disruption.grant(gangs, "rolling-update"):
        return False
    from grove_tpu.api.meta import Condition, set_condition
    from grove_tpu.api.types import COND_PODGANG_DISRUPTION_TARGET
    from grove_tpu.runtime.errors import ERR_CONFLICT, GroveError

    for gang in gangs:
        # conflict-tolerant: the scheduler flips this back to False
        # (reason Rescheduled) once the updated gang re-places
        for _ in range(4):
            fresh = ctx.store.get(
                "PodGang", gang.metadata.namespace, gang.metadata.name
            )
            if fresh is None:
                break
            set_condition(
                fresh.status.conditions,
                Condition(
                    type=COND_PODGANG_DISRUPTION_TARGET,
                    status="True",
                    reason="RollingUpdate",
                    message=f"replica {replica} selected for rolling update",
                ),
                ctx.clock.now(),
            )
            try:
                ctx.store.update_status(fresh)
                break
            except GroveError as e:
                if e.code != ERR_CONFLICT:
                    raise
    return True


# ---------------------------------------------------------------------------
# replica selection
# ---------------------------------------------------------------------------


def _replica_pclqs(ctx: OperatorContext, pcs: PodCliqueSet, replica: int) -> List:
    return ctx.store.list(
        "PodClique",
        pcs.metadata.namespace,
        {
            **namegen.default_labels(pcs.metadata.name),
            namegen.LABEL_PCS_REPLICA_INDEX: str(replica),
        },
    )


def _replica_needs_update(ctx: OperatorContext, pcs: PodCliqueSet, replica: int) -> bool:
    for pclq in _replica_pclqs(ctx, pcs, replica):
        tmpl_name = _clique_template_name(pcs, pclq)
        want = pod_template_hash_for(pcs, tmpl_name)
        if want is None:
            continue
        if pclq.metadata.labels.get(namegen.LABEL_POD_TEMPLATE_HASH) != want:
            return True
        if pclq.status.updated_replicas < pclq.spec.replicas:
            return True
    return False


def _clique_template_name(pcs: PodCliqueSet, pclq) -> str:
    """PCLQ FQN → clique template name (strip owner + replica prefix)."""
    pcsg = pclq.metadata.labels.get(namegen.LABEL_PCSG)
    owner = pcsg if pcsg else pcs.metadata.name
    owner_replica = (
        pclq.metadata.labels.get(namegen.LABEL_PCSG_REPLICA_INDEX)
        if pcsg
        else pclq.metadata.labels.get(namegen.LABEL_PCS_REPLICA_INDEX, "0")
    )
    prefix = f"{owner}-{owner_replica}-"
    return pclq.metadata.name[len(prefix):]


def _pick_next_replica(ctx: OperatorContext, pcs: PodCliqueSet) -> Optional[int]:
    """rollingupdate.go:196-250 ordering."""
    candidates = []
    for replica in range(pcs.spec.replicas):
        if not _replica_needs_update(ctx, pcs, replica):
            continue
        pclqs = _replica_pclqs(ctx, pcs, replica)
        scheduled = sum(p.status.scheduled_replicas for p in pclqs)
        breached = any(
            (c := get_condition(p.status.conditions, COND_MIN_AVAILABLE_BREACHED))
            is not None
            and c.is_true()
            for p in pclqs
        )
        candidates.append((0 if scheduled == 0 else 1, 0 if breached else 1, replica))
    if not candidates:
        return None
    return sorted(candidates)[0][2]


# ---------------------------------------------------------------------------
# template push + completion
# ---------------------------------------------------------------------------


def _push_template_to_replica(
    ctx: OperatorContext, pcs: PodCliqueSet, replica: int
) -> None:
    """Update spec + hash label (+ update-in-progress marker) on the
    replica's STANDALONE PodCliques. PCSG-owned cliques are updated by the
    PCSG controller's own replica-by-replica rolling update (reference
    granularity — pcsg components/podclique/rollingupdate.go:55-260), gated
    on this PCS replica being the currently-selected one."""
    from grove_tpu.controller.common import apply_template_to_pclq

    for pclq in _replica_pclqs(ctx, pcs, replica):
        if pclq.metadata.labels.get(namegen.LABEL_PCSG):
            continue  # PCSG controller's responsibility
        name = _clique_template_name(pcs, pclq)
        apply_template_to_pclq(ctx, pcs, pclq, name)


def _replica_update_done(ctx: OperatorContext, pcs: PodCliqueSet, replica: int) -> bool:
    pclqs = _replica_pclqs(ctx, pcs, replica)
    if not pclqs:
        return True
    for pclq in pclqs:
        name = _clique_template_name(pcs, pclq)
        want = pod_template_hash_for(pcs, name)
        if want is None:
            continue
        if pclq.metadata.labels.get(namegen.LABEL_POD_TEMPLATE_HASH) != want:
            return False
        if pclq.status.updated_replicas < pclq.spec.replicas:
            return False
        if pclq.status.ready_replicas < (pclq.spec.min_available or 1):
            return False
    return True


def _finish_pcsg_progress(ctx: OperatorContext, pcs: PodCliqueSet, replica: int) -> None:
    sel = {
        **namegen.default_labels(pcs.metadata.name),
        namegen.LABEL_PCS_REPLICA_INDEX: str(replica),
    }
    for pcsg in ctx.store.list("PodCliqueScalingGroup", pcs.metadata.namespace, sel):
        progress = pcsg.status.rolling_update_progress
        if progress is not None and progress.update_ended_at is None:
            progress.update_ended_at = ctx.clock.now()
            progress.updated_replica_indices = list(range(pcsg.spec.replicas))
            progress.ready_replica_indices_selected_to_update = []
            ctx.store.update_status(pcsg)


def _complete_replica(ctx: OperatorContext, pcs: PodCliqueSet, replica: int) -> None:
    _finish_pcsg_progress(ctx, pcs, replica)
    progress = pcs.status.rolling_update_progress
    for pclq in _replica_pclqs(ctx, pcs, replica):
        if UPDATE_IN_PROGRESS_ANNOTATION in pclq.metadata.annotations:
            pclq.metadata.annotations.pop(UPDATE_IN_PROGRESS_ANNOTATION)
            ctx.store.update(pclq, bump_generation=False)
        if pclq.metadata.labels.get(namegen.LABEL_PCSG):
            if pclq.metadata.labels[namegen.LABEL_PCSG] not in (
                progress.updated_pod_clique_scaling_groups
            ):
                progress.updated_pod_clique_scaling_groups.append(
                    pclq.metadata.labels[namegen.LABEL_PCSG]
                )
        elif pclq.metadata.name not in progress.updated_pod_cliques:
            progress.updated_pod_cliques.append(pclq.metadata.name)
    progress.currently_updating = None
    ctx.store.update_status(pcs)
    ctx.record_event(
        "PodCliqueSet",
        "RollingUpdateReplicaCompleted",
        f"{pcs.metadata.name} replica {replica}",
        namespace=pcs.metadata.namespace,
        name=pcs.metadata.name,
    )
