"""PCS replica component: gang termination.

Re-host of /root/reference/operator/internal/controller/podcliqueset/components/
podcliquesetreplica/gangterminate.go:42-213: a PCS replica whose standalone
PCLQ or PCSG has had MinAvailableBreached=True for longer than
TerminationDelay gets ALL its PodCliques deleted (gang-level restart — the
normal sync then recreates them); otherwise requeue with the minimum
remaining wait.
"""

from __future__ import annotations

from typing import List, Optional

from grove_tpu.api import names as namegen
from grove_tpu.api.meta import get_condition
from grove_tpu.api.types import COND_MIN_AVAILABLE_BREACHED, PodCliqueSet
from grove_tpu.controller.common import OperatorContext


def sync(ctx: OperatorContext, pcs: PodCliqueSet, snap=None) -> Optional[float]:
    """Returns the minimum remaining breach wait (requeue hint) or None.
    ``snap``: the reconcile's shared ChildSnapshot (one informer fetch per
    reconcile under cache lag) — None falls back to per-replica scans."""
    delay = pcs.spec.template.termination_delay or 0.0
    now = ctx.clock.now()
    min_wait: Optional[float] = None
    for replica in range(pcs.spec.replicas):
        since = _replica_breach_since(ctx, pcs, replica, snap)
        if since is None:
            continue
        age = now - since
        if age >= delay:
            _terminate_replica(ctx, pcs, replica)
        else:
            remaining = delay - age
            min_wait = remaining if min_wait is None else min(min_wait, remaining)
    return min_wait


def _replica_breach_since(
    ctx: OperatorContext, pcs: PodCliqueSet, replica: int, snap=None
) -> Optional[float]:
    """Earliest still-True breach among the replica's standalone PCLQs and its
    PCSGs (gangterminate.go:67-105; PCSG aggregation covers base replicas)."""
    ns = pcs.metadata.namespace
    breach_times: List[float] = []
    if snap is not None:
        standalone = snap.pclqs_for_replica(
            replica, namegen.COMPONENT_PCS_PODCLIQUE
        )
        pcsgs = snap.pcsgs_for_replica(replica)
    else:
        standalone = ctx.store.scan(
            "PodClique",
            ns,
            {
                **namegen.default_labels(pcs.metadata.name),
                namegen.LABEL_COMPONENT: namegen.COMPONENT_PCS_PODCLIQUE,
                namegen.LABEL_PCS_REPLICA_INDEX: str(replica),
            },
            cached=True,
        )
        pcsgs = ctx.store.scan(
            "PodCliqueScalingGroup",
            ns,
            {
                **namegen.default_labels(pcs.metadata.name),
                namegen.LABEL_PCS_REPLICA_INDEX: str(replica),
            },
            cached=True,
        )
    for pclq in standalone:
        cond = get_condition(pclq.status.conditions, COND_MIN_AVAILABLE_BREACHED)
        if cond is not None and cond.is_true():
            breach_times.append(cond.last_transition_time)
    for pcsg in pcsgs:
        cond = get_condition(pcsg.status.conditions, COND_MIN_AVAILABLE_BREACHED)
        if cond is not None and cond.is_true():
            breach_times.append(cond.last_transition_time)
    return min(breach_times) if breach_times else None


def _terminate_replica(ctx: OperatorContext, pcs: PodCliqueSet, replica: int) -> None:
    """DeleteAllOf PodCliques for the replica (gangterminate.go:190-213)."""
    ns = pcs.metadata.namespace
    n = ctx.store.delete_collection(
        "PodClique",
        ns,
        {
            **namegen.default_labels(pcs.metadata.name),
            namegen.LABEL_PCS_REPLICA_INDEX: str(replica),
        },
    )
    ctx.record_event(
        "PodCliqueSet",
        "GangTerminated",
        f"{pcs.metadata.name} replica {replica}: deleted {n} PodCliques",
        namespace=ns,
        name=pcs.metadata.name,
    )
