"""PCS infra components: RBAC, SA-token secret, headless Services, HPAs.

Re-host of the reference component set ordered ahead of the workload
components (podcliqueset/reconcilespec.go:202-215):
serviceaccount/role/rolebinding/satokensecret (components/{serviceaccount,
role,rolebinding,satokensecret}/), service (components/service/service.go),
hpa (components/hpa/hpa.go:130-168).
"""

from __future__ import annotations

from typing import Dict, List

from grove_tpu.api import names as namegen
from grove_tpu.api.meta import ObjectMeta
from grove_tpu.api.types import GenericObject, PodCliqueSet
from grove_tpu.controller.common import OperatorContext


def _ensure(ctx: OperatorContext, obj: GenericObject) -> None:
    if (
        ctx.store.get(
            obj.kind, obj.metadata.namespace, obj.metadata.name, readonly=True
        )
        is None
    ):
        # freshly built, caller drops it: ownership-transfer create
        ctx.store.create(obj, consume=True)


def _reap(
    ctx: OperatorContext,
    kind: str,
    namespace: str,
    selector: Dict[str, str],
    keep: List[str],
) -> None:
    for obj in ctx.store.scan(kind, namespace, selector):
        if obj.metadata.name not in keep:
            ctx.store.delete(kind, namespace, obj.metadata.name)


def sync_rbac(ctx: OperatorContext, pcs: PodCliqueSet) -> None:
    """Per-PCS ServiceAccount/Role/RoleBinding (pods list/watch for the init
    waiter) + SA token secret mounted into it.

    Existence check FIRST (four readonly dict lookups): these objects are
    immutable once created, and the steady state — every PCS reconcile
    after the first — must not pay four object constructions just to find
    them already present (profiled: sync_rbac was ~2% of the 10k-set
    integrated converge)."""
    ns = pcs.metadata.namespace
    name = pcs.metadata.name
    wanted = (
        ("ServiceAccount", namegen.pod_service_account_name(name)),
        ("Role", namegen.pod_role_name(name)),
        ("RoleBinding", namegen.pod_role_binding_name(name)),
        ("Secret", namegen.initc_sa_token_secret_name(name)),
    )
    if all(
        ctx.store.get(kind, ns, obj_name, readonly=True) is not None
        for kind, obj_name in wanted
    ):
        return
    base = namegen.default_labels(pcs.metadata.name)
    items = [
        GenericObject(
            kind="ServiceAccount",
            metadata=ObjectMeta(
                name=namegen.pod_service_account_name(pcs.metadata.name),
                namespace=ns,
                labels={
                    **base,
                    namegen.LABEL_COMPONENT: namegen.COMPONENT_POD_SERVICE_ACCOUNT,
                },
            ),
        ),
        GenericObject(
            kind="Role",
            metadata=ObjectMeta(
                name=namegen.pod_role_name(pcs.metadata.name),
                namespace=ns,
                labels={**base, namegen.LABEL_COMPONENT: namegen.COMPONENT_POD_ROLE},
            ),
            spec={"rules": [{"resources": ["pods"], "verbs": ["list", "watch", "get"]}]},
        ),
        GenericObject(
            kind="RoleBinding",
            metadata=ObjectMeta(
                name=namegen.pod_role_binding_name(pcs.metadata.name),
                namespace=ns,
                labels={
                    **base,
                    namegen.LABEL_COMPONENT: namegen.COMPONENT_POD_ROLE_BINDING,
                },
            ),
            spec={
                "roleRef": namegen.pod_role_name(pcs.metadata.name),
                "subjects": [namegen.pod_service_account_name(pcs.metadata.name)],
            },
        ),
        GenericObject(
            kind="Secret",
            metadata=ObjectMeta(
                name=namegen.initc_sa_token_secret_name(pcs.metadata.name),
                namespace=ns,
                labels={
                    **base,
                    namegen.LABEL_COMPONENT: namegen.COMPONENT_SA_TOKEN_SECRET,
                },
            ),
        ),
    ]
    for obj in items:
        _ensure(ctx, obj)


def sync_headless_services(ctx: OperatorContext, pcs: PodCliqueSet) -> None:
    """One headless Service per PCS replica for stable pod DNS
    (`<pod>.<svc>.<ns>.svc.cluster.local` — service/service.go)."""
    ns = pcs.metadata.namespace
    base = namegen.default_labels(pcs.metadata.name)
    selector = {**base, namegen.LABEL_COMPONENT: namegen.COMPONENT_HEADLESS_SERVICE}
    hsc = pcs.spec.template.headless_service_config
    keep = []
    for replica in range(pcs.spec.replicas):
        name = namegen.headless_service_name(pcs.metadata.name, replica)
        keep.append(name)
        _ensure(
            ctx,
            GenericObject(
                kind="Service",
                metadata=ObjectMeta(
                    name=name,
                    namespace=ns,
                    labels={
                        **selector,
                        namegen.LABEL_PCS_REPLICA_INDEX: str(replica),
                    },
                ),
                spec={
                    "clusterIP": "None",
                    "publishNotReadyAddresses": (
                        hsc.publish_not_ready_addresses if hsc else True
                    ),
                    "selector": {
                        namegen.LABEL_PART_OF: pcs.metadata.name,
                        namegen.LABEL_PCS_REPLICA_INDEX: str(replica),
                    },
                },
            ),
        )
    _reap(ctx, "Service", ns, selector, keep)


def sync_hpas(ctx: OperatorContext, pcs: PodCliqueSet) -> None:
    """HPA per autoscaled PCLQ and per PCSG with scaleConfig, targeting the
    CR's scale subresource (hpa.go:130-168)."""
    ns = pcs.metadata.namespace
    base = namegen.default_labels(pcs.metadata.name)
    selector = {**base, namegen.LABEL_COMPONENT: namegen.COMPONENT_HPA}
    keep = []
    tmpl = pcs.spec.template
    for replica in range(pcs.spec.replicas):
        for clique in tmpl.standalone_clique_templates():
            sc = clique.spec.auto_scaling_config
            if sc is None:
                continue
            target = namegen.podclique_name(pcs.metadata.name, replica, clique.name)
            keep.append(target)
            _ensure(
                ctx,
                GenericObject(
                    kind="HorizontalPodAutoscaler",
                    metadata=ObjectMeta(name=target, namespace=ns, labels=dict(selector)),
                    spec={
                        "targetKind": "PodClique",
                        "targetName": target,
                        "minReplicas": sc.min_replicas,
                        "maxReplicas": sc.max_replicas,
                        "metrics": sc.metrics,
                    },
                ),
            )
        for sg in tmpl.pod_clique_scaling_group_configs:
            if sg.scale_config is None:
                continue
            target = namegen.pcsg_name(pcs.metadata.name, replica, sg.name)
            keep.append(target)
            _ensure(
                ctx,
                GenericObject(
                    kind="HorizontalPodAutoscaler",
                    metadata=ObjectMeta(name=target, namespace=ns, labels=dict(selector)),
                    spec={
                        "targetKind": "PodCliqueScalingGroup",
                        "targetName": target,
                        "minReplicas": sg.scale_config.min_replicas,
                        "maxReplicas": sg.scale_config.max_replicas,
                        "metrics": sg.scale_config.metrics,
                    },
                ),
            )
    _reap(ctx, "HorizontalPodAutoscaler", ns, selector, keep)
