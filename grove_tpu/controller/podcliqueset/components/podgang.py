"""PCS podgang component — THE semantic hot path.

Re-host of /root/reference/operator/internal/controller/podcliqueset/components/
podgang/syncflow.go (the subtlest pure logic in the reference):

- one BASE PodGang per PCS replica holding every standalone clique plus
  scaling-group replicas 0..minAvailable-1 (syncflow.go:134-152, :230-249)
- one SCALED PodGang per scaling-group replica >= minAvailable, 0-based names
  (syncflow.go:154-197)
- replica counts follow live (HPA-mutated) PCLQ/PCSG resources when they
  exist, else template values (determinePodCliqueReplicas, :271-287)
- a PodGang *pending creation* is deferred while any constituent pod is
  uncreated or not yet labeled with the gang (:394-461); existing gangs keep
  updating
- PodGroups: one per constituent PCLQ — {name: pclq FQN, podReferences:
  sorted pod names, minReplicas: pclq minAvailable} (:488-508)
- excess gangs deleted (:368-386)
- topology constraints translated from level names to node-label keys at the
  PCS / PCSG / PCLQ tiers (scheduler podgang.go:50-126)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from grove_tpu.api import names as namegen
from grove_tpu.api.meta import NamespacedName, ObjectMeta
from grove_tpu.api.types import (
    PodCliqueSet,
    PodGang,
    PodGangSpec,
    PodGroup,
    TopologyConstraintGroupConfig,
)
from grove_tpu.controller.common import (
    OperatorContext,
    find_scaling_group_config_for_clique,
    translate_topology_constraint,
)


@dataclass
class PclqInfo:
    fqn: str
    replicas: int
    min_available: int
    clique_template_name: str


@dataclass
class PodGangInfo:
    fqn: str
    pclqs: List[PclqInfo] = field(default_factory=list)
    base: bool = True
    pcsg_fqn: Optional[str] = None  # set for scaled gangs
    base_fqn: Optional[str] = None  # the base gang a scaled gang hangs off


def compute_expected_podgangs(
    ctx: OperatorContext,
    pcs: PodCliqueSet,
    live_pclqs: Optional[Dict] = None,
    live_pcsgs: Optional[Dict] = None,
) -> List[PodGangInfo]:
    """syncflow.go:113-132. ``live_pclqs``/``live_pcsgs``: pre-fetched
    name→view dicts from the reconcile's shared ChildSnapshot (None →
    fetch here)."""
    ns = pcs.metadata.namespace
    if live_pclqs is None:
        live_pclqs = {
            p.metadata.name: p
            for p in ctx.store.scan(
                "PodClique", ns, namegen.default_labels(pcs.metadata.name), cached=True
            )
        }
    if live_pcsgs is None:
        live_pcsgs = {
            g.metadata.name: g
            for g in ctx.store.scan(
                "PodCliqueScalingGroup",
                ns,
                namegen.default_labels(pcs.metadata.name),
                cached=True,
            )
        }
    out: List[PodGangInfo] = []
    for replica in range(pcs.spec.replicas):
        out.append(_base_podgang_info(pcs, replica, live_pclqs))
    for replica in range(pcs.spec.replicas):
        out.extend(_scaled_podgang_infos(pcs, replica, live_pcsgs))
    return out


def _clique_replicas(pcs, clique, fqn: str, live_pclqs) -> int:
    """determinePodCliqueReplicas (:271-287): live PCLQ replicas when the
    clique is autoscaled and the resource exists; template replicas otherwise."""
    if clique.spec.auto_scaling_config is None:
        return clique.spec.replicas
    live = live_pclqs.get(fqn)
    return live.spec.replicas if live is not None else clique.spec.replicas


def _base_podgang_info(pcs, replica: int, live_pclqs) -> PodGangInfo:
    """:134-152 + :230-249 — worked example (comment at :227-229): with
    minAvailable=3, PCSG replicas 0,1,2 fold into base gang `<pcs>-<r>`;
    replicas 3,4 get scaled gangs `<pcsg-fqn>-0`, `<pcsg-fqn>-1`."""
    info = PodGangInfo(
        fqn=namegen.base_podgang_name(pcs.metadata.name, replica), base=True
    )
    tmpl = pcs.spec.template
    for clique in tmpl.cliques:
        sg_cfg = find_scaling_group_config_for_clique(
            tmpl.pod_clique_scaling_group_configs, clique.name
        )
        if sg_cfg is not None:
            pcsg_fqn = namegen.pcsg_name(pcs.metadata.name, replica, sg_cfg.name)
            for sg_replica in range(sg_cfg.min_available or 1):
                fqn = namegen.podclique_name(pcsg_fqn, sg_replica, clique.name)
                info.pclqs.append(
                    PclqInfo(
                        fqn=fqn,
                        replicas=clique.spec.replicas,
                        min_available=clique.spec.min_available or 1,
                        clique_template_name=clique.name,
                    )
                )
        else:
            fqn = namegen.podclique_name(pcs.metadata.name, replica, clique.name)
            info.pclqs.append(
                PclqInfo(
                    fqn=fqn,
                    replicas=_clique_replicas(pcs, clique, fqn, live_pclqs),
                    min_available=clique.spec.min_available or 1,
                    clique_template_name=clique.name,
                )
            )
    return info


def _scaled_podgang_infos(pcs, replica: int, live_pcsgs) -> List[PodGangInfo]:
    """:154-197 — scaled gangs for PCSG replicas >= minAvailable; replica
    count follows the live PCSG resource (HPA) when present."""
    out: List[PodGangInfo] = []
    tmpl = pcs.spec.template
    for cfg in tmpl.pod_clique_scaling_group_configs:
        pcsg_fqn = namegen.pcsg_name(pcs.metadata.name, replica, cfg.name)
        min_available = cfg.min_available or 1
        replicas = cfg.replicas or 1
        live = live_pcsgs.get(pcsg_fqn)
        if live is not None:
            replicas = live.spec.replicas
        for gang_index, sg_replica in enumerate(range(min_available, replicas)):
            info = PodGangInfo(
                fqn=namegen.scaled_podgang_name(pcsg_fqn, gang_index),
                base=False,
                pcsg_fqn=pcsg_fqn,
                base_fqn=namegen.base_podgang_name(pcs.metadata.name, replica),
            )
            for clique_name in cfg.clique_names:
                clique = tmpl.clique_template(clique_name)
                if clique is None:
                    continue
                fqn = namegen.podclique_name(pcsg_fqn, sg_replica, clique_name)
                # scaled instances always use template replicas (:289-310)
                info.pclqs.append(
                    PclqInfo(
                        fqn=fqn,
                        replicas=clique.spec.replicas,
                        min_available=clique.spec.min_available or 1,
                        clique_template_name=clique_name,
                    )
                )
            out.append(info)
    return out


def sync(ctx: OperatorContext, pcs: PodCliqueSet, snap=None) -> None:
    ns = pcs.metadata.namespace
    # one informer snapshot serves the expected-gang computation, the
    # pending checks, AND the PodGroup builds (previously this flow ran the
    # same PodClique scan twice plus one pod scan per constituent PCLQ)
    if snap is not None:
        live_pclqs = {p.metadata.name: p for p in snap.pclqs}
        live_pcsgs = {g.metadata.name: g for g in snap.pcsgs}
        set_pods = snap.pods_by_pclq()
    else:
        live_pclqs = live_pcsgs = set_pods = None
    expected = compute_expected_podgangs(ctx, pcs, live_pclqs, live_pcsgs)
    expected_names = {g.fqn for g in expected}
    selector = {
        **namegen.default_labels(pcs.metadata.name),
        namegen.LABEL_COMPONENT: namegen.COMPONENT_PODGANG,
    }
    existing = {g.metadata.name for g in ctx.store.scan("PodGang", ns, selector)}

    # delete excess (:368-386)
    for name in existing - expected_names:
        ctx.store.delete("PodGang", ns, name)
        ctx.record_event(
            "PodGang", "PodGangDeleteSuccessful", name, namespace=ns, name=name
        )

    if live_pclqs is None:
        live_pclqs = {
            p.metadata.name: p
            for p in ctx.store.scan(
                "PodClique", ns, namegen.default_labels(pcs.metadata.name), cached=True
            )
        }

    for gang in expected:
        pods_by_pclq, pending = _pods_pending_creation_or_association(
            ctx, ns, gang, live_pclqs, set_pods
        )
        if gang.fqn not in existing and pending > 0:
            # defer creation until every constituent pod exists & is labeled
            # (:432-461)
            continue
        _create_or_update_podgang(ctx, pcs, gang, pods_by_pclq)


def _pods_pending_creation_or_association(
    ctx: OperatorContext, ns: str, gang: PodGangInfo, live_pclqs, set_pods=None
):
    """:394-461: count pods that are (a) from PCLQs not yet created,
    (b) not yet created in existing PCLQs, or (c) missing/mismatching the
    podgang label. Also returns the pod names per PCLQ for PodGroups.
    ``set_pods``: the snapshot's pods-by-PCLQ grouping (one scan for the
    whole set instead of one per constituent PCLQ)."""
    pending = 0
    pods_by_pclq: Dict[str, List[str]] = {}
    for pclq in gang.pclqs:
        live = live_pclqs.get(pclq.fqn)
        if live is None:
            pending += pclq.replicas
            continue
        if set_pods is not None:
            pods = set_pods.get(pclq.fqn, ())
        else:
            pods = ctx.store.scan(
                "Pod", ns, {namegen.LABEL_PODCLIQUE: pclq.fqn}, cached=True
            )
        pods = [p for p in pods if p.metadata.deletion_timestamp is None]
        pending += max(0, live.spec.replicas - len(pods))
        names: List[str] = []
        for pod in pods:
            label = pod.metadata.labels.get(namegen.LABEL_PODGANG)
            if label != gang.fqn:
                pending += 1
                continue
            names.append(pod.metadata.name)
        pods_by_pclq[pclq.fqn] = sorted(names)
    return pods_by_pclq, pending


def _create_or_update_podgang(
    ctx: OperatorContext,
    pcs: PodCliqueSet,
    gang: PodGangInfo,
    pods_by_pclq: Dict[str, List[str]],
) -> None:
    ns = pcs.metadata.namespace
    tmpl = pcs.spec.template
    pod_groups = []
    for pclq in gang.pclqs:
        clique_tmpl = tmpl.clique_template(pclq.clique_template_name)
        pod_groups.append(
            PodGroup(
                name=pclq.fqn,
                pod_references=[
                    NamespacedName(namespace=ns, name=n)
                    for n in pods_by_pclq.get(pclq.fqn, [])
                ],
                min_replicas=pclq.min_available,
                topology_constraint=translate_topology_constraint(
                    clique_tmpl.topology_constraint if clique_tmpl else None,
                    ctx.topology,
                ),
            )
        )

    # PCSG-level pack groups (scheduler podgang.go:117-126)
    group_configs = []
    if gang.base:
        for cfg in tmpl.pod_clique_scaling_group_configs:
            tc = translate_topology_constraint(cfg.topology_constraint, ctx.topology)
            if tc is None:
                continue
            member_names = [
                p.fqn
                for p in gang.pclqs
                if p.clique_template_name in cfg.clique_names
            ]
            if member_names:
                group_configs.append(
                    TopologyConstraintGroupConfig(
                        pod_group_names=member_names, topology_constraint=tc
                    )
                )
    elif gang.pcsg_fqn is not None and gang.base_fqn is not None:
        # exact sg-name extraction: pcsg_fqn = <base_fqn>-<sg-name>
        sg_name = gang.pcsg_fqn[len(gang.base_fqn) + 1 :]
        for cfg in tmpl.pod_clique_scaling_group_configs:
            if cfg.name == sg_name:
                tc = translate_topology_constraint(
                    cfg.topology_constraint, ctx.topology
                )
                if tc is not None:
                    group_configs.append(
                        TopologyConstraintGroupConfig(
                            pod_group_names=[p.fqn for p in gang.pclqs],
                            topology_constraint=tc,
                        )
                    )
                break

    # During a rolling update, hint the scheduler to reuse this gang's prior
    # reservation for replaced pods (scheduler podgang.go:67-73)
    reuse_ref = None
    progress = pcs.status.rolling_update_progress
    if progress is not None and progress.update_ended_at is None:
        reuse_ref = NamespacedName(namespace=ns, name=gang.fqn)

    spec = PodGangSpec(
        pod_groups=pod_groups,
        topology_constraint=translate_topology_constraint(
            tmpl.topology_constraint, ctx.topology
        ),
        topology_constraint_group_configs=group_configs,
        priority_class_name=tmpl.priority_class_name,
        reuse_reservation_ref=reuse_ref,
    )

    current = ctx.store.get("PodGang", ns, gang.fqn, readonly=True)
    if current is None:
        labels = dict(namegen.default_labels(pcs.metadata.name))
        labels[namegen.LABEL_COMPONENT] = namegen.COMPONENT_PODGANG
        # tenant queue (quota subsystem): the scheduler reads the gang's
        # queue assignment from this label at encode time
        queue = pcs.metadata.labels.get(namegen.LABEL_QUEUE)
        if queue:
            labels[namegen.LABEL_QUEUE] = queue
        if not gang.base and gang.base_fqn:
            labels[namegen.LABEL_BASE_PODGANG] = gang.base_fqn
        ctx.store.create(
            PodGang(
                metadata=ObjectMeta(name=gang.fqn, namespace=ns, labels=labels),
                spec=spec,
            ),
            consume=True,  # freshly built and dropped: no pickled copy
        )
        ctx.record_event(
            "PodGang",
            "PodGangCreateSuccessful",
            gang.fqn,
            namespace=ns,
            name=gang.fqn,
        )
    elif current.spec != spec:
        # copy-on-write spec push: `spec` is freshly built (private); the
        # committed clone shares metadata/status with the previous object
        from grove_tpu.runtime.store import commit_spec

        commit_spec(ctx.store, current, spec)


