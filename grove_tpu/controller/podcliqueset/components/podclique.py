"""PCS podclique component: standalone PodCliques per PCS replica.

Re-host of /root/reference/operator/internal/controller/podcliqueset/components/
podclique/podclique.go (395 LoC): one PCLQ per (PCS replica × standalone
clique template), labeled with the base PodGang of its replica; deletes
PCLQs of removed PCS replicas.
"""

from __future__ import annotations

import json
from typing import Dict

from grove_tpu.api import names as namegen
from grove_tpu.api.hashing import pod_template_hash_for
from grove_tpu.api.meta import ObjectMeta
from grove_tpu.api.types import PodClique, PodCliqueSet
from grove_tpu.controller.common import (
    OperatorContext,
    create_or_adopt,
    resolve_starts_after,
    shared_template_spec,
)
from grove_tpu.controller.podclique.pods import STARTUP_DEPS_ANNOTATION


def sync(ctx: OperatorContext, pcs: PodCliqueSet) -> None:
    ns = pcs.metadata.namespace
    selector = {
        **namegen.default_labels(pcs.metadata.name),
        namegen.LABEL_COMPONENT: namegen.COMPONENT_PCS_PODCLIQUE,
    }
    existing_names = {
        p.metadata.name for p in ctx.store.scan("PodClique", ns, selector)
    }

    def build() -> Dict[str, PodClique]:
        out: Dict[str, PodClique] = {}
        for replica in range(pcs.spec.replicas):
            for clique in pcs.spec.template.standalone_clique_templates():
                pclq = build_pclq(pcs, replica, clique)
                out[pclq.metadata.name] = pclq
        return out

    # pure function of (uid, generation): spec/replica changes bump the
    # generation, so the memoized desired set is exact across reconciles
    expected = ctx.desired_cache(
        ("pclq", pcs.metadata.uid, pcs.metadata.generation), build
    )

    for name, pclq in expected.items():
        if name not in existing_names:
            ctx.record_event(
                "PodClique",
                "PodCliqueCreateSuccessful",
                name,
                namespace=ns,
                name=name,
            )
        create_or_adopt(ctx, pclq)

    for name in existing_names - expected.keys():
        ctx.store.delete("PodClique", ns, name)
        ctx.record_event(
            "PodClique",
            "PodCliqueDeleteSuccessful",
            name,
            namespace=ns,
            name=name,
        )


def build_pclq(pcs: PodCliqueSet, replica: int, clique) -> PodClique:
    fqn = namegen.podclique_name(pcs.metadata.name, replica, clique.name)
    labels = dict(namegen.default_labels(pcs.metadata.name))
    labels.update(clique.labels)
    labels[namegen.LABEL_COMPONENT] = namegen.COMPONENT_PCS_PODCLIQUE
    labels[namegen.LABEL_PCS_REPLICA_INDEX] = str(replica)
    labels[namegen.LABEL_PODGANG] = namegen.base_podgang_name(
        pcs.metadata.name, replica
    )
    labels[namegen.LABEL_POD_TEMPLATE_HASH] = pod_template_hash_for(
        pcs, clique.name
    )
    # tenant queue (quota subsystem): PCS label flows to the PCLQ, and from
    # there to every pod (pods copy PCLQ labels wholesale), so the usage
    # accountant can attribute bound capacity without store lookups
    queue = pcs.metadata.labels.get(namegen.LABEL_QUEUE)
    if queue:
        labels[namegen.LABEL_QUEUE] = queue
    annotations = dict(clique.annotations)
    deps = resolve_starts_after(pcs, replica, clique.name)
    if deps:
        annotations[STARTUP_DEPS_ANNOTATION] = json.dumps(deps)
    return PodClique(
        metadata=ObjectMeta(
            name=fqn,
            namespace=pcs.metadata.namespace,
            labels=labels,
            annotations=annotations,
        ),
        spec=shared_template_spec(clique.spec),
    )
