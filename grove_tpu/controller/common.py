"""Shared controller machinery: operator context + component protocol.

Re-host of the component-operator pattern in
/root/reference/operator/internal/controller/common/component/types.go:44-92 —
each reconciler iterates an *ordered* list of components, each owning one child
kind with Sync/Delete; plus cross-component helpers from
controller/common/component/utils/.
"""

from __future__ import annotations

import threading
import weakref
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Protocol

from grove_tpu.api import names as namegen
from grove_tpu.api.topology import ClusterTopology
from grove_tpu.api.types import (
    PodCliqueScalingGroupConfig,
    PodCliqueSet,
    SchedTopologyConstraint,
    TopologyPackConstraint,
)
from grove_tpu.runtime.clock import Clock
from grove_tpu.runtime.expectations import ExpectationsStore
from grove_tpu.runtime.store import Store

FINALIZER = "grove.io/operator"

# live OperatorContext registry: the worker-PROCESS backend
# (runtime/procworkers.py) forks children that inherit every context's
# _event_seq verbatim — without a per-process offset, a child and the
# coordinator would both allocate the same evt-N Event name, the loser's
# best-effort create would conflict away, and the serial-twin
# commit-count equality would break. Weak values: contexts die with
# their harness; the registry must not pin them. Keyed by a monotonic
# registration id so iteration order is deterministic AND identical in a
# forked child (WeakSet iteration order is address-dependent).
_LIVE_CONTEXTS: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()
_CTX_SEQ = 0

# spacing between per-slot Event name ranges; far above any sim's Event
# volume (the ring buffer caps live Events at max_events=1000)
EVENT_SEQ_STRIDE = 10_000_000


def live_contexts() -> List["OperatorContext"]:
    return [ctx for _, ctx in sorted(_LIVE_CONTEXTS.items())]


def contexts_of_store(store) -> List["OperatorContext"]:
    """The live contexts operating a given store, registration order —
    how the process backend finds the expectations/event state belonging
    to the engine it drains (a test process may hold several harnesses)."""
    return [ctx for ctx in live_contexts() if ctx.store is store]


def rebase_event_sequences(slot: int) -> None:
    """Move every live context's Event sequence into the disjoint range
    owned by `slot` (the coordinator's slot 0 keeps the natural range).
    Called once per freshly forked worker process, before it reconciles
    anything — the analogue of api/meta.reset_uid_namespace() for the
    evt-N namespace. `slot` must be unique per (fork generation, worker):
    a previous generation's Events live on in the inherited store, so a
    reused range would re-collide with them."""
    if slot <= 0:
        return
    for ctx in live_contexts():
        with ctx._event_lock:
            ctx._event_seq += slot * EVENT_SEQ_STRIDE


# eq=False: keep identity __eq__/__hash__ (a value-eq dataclass is
# unhashable, and the weak registry below needs to hold instances)
@dataclass(eq=False)
class OperatorContext:
    """Everything a component needs (the reference passes client/scheme/
    eventRecorder; we pass the store + clock + topology + expectations)."""

    store: Store
    clock: Clock
    topology: Optional[ClusterTopology] = None
    # disruption broker (grove_tpu/disruption): the rolling-update flow
    # asks it before taking a replica's gangs down; None (bare tests) or an
    # un-armed broker (no budgets/drains) allows everything untouched
    disruption: Optional[object] = None
    pod_expectations: ExpectationsStore = field(
        default_factory=lambda: ExpectationsStore("pod")
    )
    events: List[str] = field(default_factory=list)
    _event_seq: int = 0
    # sequence + memo guards: reconciles run on parallel worker threads
    # under the concurrent control plane (runtime/workers.py) — a bare
    # `_event_seq += 1` is a read-modify-write race there, and two workers
    # building the same desired-memo key must not interleave the eviction
    # scan. Uncontended lock acquires are the only serial-path cost.
    _event_lock: object = field(default_factory=threading.Lock)
    _memo_lock: object = field(default_factory=threading.Lock)
    max_events: int = 1000  # ring buffer (k8s Events have a TTL; we cap)
    # desired-child memo: the EXPECTED PodCliques/PCSGs of a set are a pure
    # function of (pcs uid, generation) — rebuilding the label dicts /
    # startup-dep JSON / template hashes on every reconcile was a flat
    # per-reconcile component-rebuild cost. Entries are reused READ-ONLY
    # (create_or_adopt only reads; Store.create commits a private copy).
    _desired_memo: Dict[tuple, object] = field(default_factory=dict)
    # sized above the live population at stress scale (10,240 sets × 2
    # entries each) so steady state never evicts a live key
    _desired_memo_max: int = 65536

    def __post_init__(self) -> None:
        global _CTX_SEQ
        _CTX_SEQ += 1
        _LIVE_CONTEXTS[_CTX_SEQ] = self

    def desired_cache(self, key: tuple, build):
        """Memoized desired-children build for `key` (kind, uid, generation).
        A generation bump changes the key; stale generations age out LRU
        (hits move to the end, so insertion order is recency). The lock
        covers the hit-bump and the eviction scan — worker threads from
        the parallel drain share this memo; `build()` runs outside it (a
        racing duplicate build is benign, a torn eviction scan is not)."""
        with self._memo_lock:
            hit = self._desired_memo.pop(key, None)
            if hit is not None:
                self._desired_memo[key] = hit
                return hit
            if len(self._desired_memo) >= self._desired_memo_max:
                # drop the least-recently-used quarter
                for stale in list(self._desired_memo)[
                    : self._desired_memo_max // 4
                ]:
                    self._desired_memo.pop(stale, None)
        value = build()
        with self._memo_lock:
            self._desired_memo[key] = value
        return value

    def record_event(
        self,
        kind: str,
        reason: str,
        message: str,
        namespace: str = "default",
        name: Optional[str] = None,
        type: str = "Normal",
    ) -> None:
        """k8s-Event equivalent: kept as a readable log AND materialized as an
        Event object in the store (the reference emits corev1 Events on every
        important transition — SURVEY §5). Capped as a ring buffer so long
        sims don't accumulate unbounded Event objects.

        Also forwarded to the process-global deduping EventRecorder
        (observability/events.py) — the view `GET /events` serves. Most call
        sites pass the object name as the message; `name` defaults to it so
        dedup identity works without touching every site."""
        from grove_tpu.observability.events import EVENTS

        EVENTS.record((kind, namespace, name or message), type, reason, message)
        self.events.append(f"{kind} {reason}: {message}")
        from grove_tpu.api.meta import ObjectMeta
        from grove_tpu.api.types import GenericObject

        # atomic sequence allocation: parallel reconcile workers
        # (runtime/workers.py) record events concurrently; a torn
        # read-modify-write here would collide two evt-N names and
        # silently drop one best-effort Event (and its rv bump) —
        # breaking the serial-twin commit-count equality
        with self._event_lock:
            self._event_seq += 1
            seq = self._event_seq
        try:
            self.store.create(
                GenericObject(
                    kind="Event",
                    metadata=ObjectMeta(name=f"evt-{seq}"),
                    spec={
                        "involvedKind": kind,
                        "reason": reason,
                        "message": message,
                        "timestamp": self.clock.now(),
                    },
                ),
                consume=True,  # fire-and-forget: no private pickled copy
            )
        except Exception:
            pass  # events are best-effort (conflict on replayed names etc.)
        expired = seq - self.max_events
        if expired > 0:
            try:
                self.store.delete("Event", "default", f"evt-{expired}")
            except Exception:
                pass


class Component(Protocol):
    kind: str

    def sync(self, ctx: OperatorContext, owner) -> None: ...

    def delete(self, ctx: OperatorContext, owner) -> None: ...


def shared_template_spec(spec):
    """Embed a PCS TEMPLATE spec into an EXPECTED child object WITHOUT
    copying. The template usually comes from a zero-copy readonly PCS view,
    so the returned spec ALIASES committed store state: the expected object
    may only flow into [create_or_adopt]/[Store.create] (both copy-on-
    write); never mutate it. One helper so the invariant has one home
    instead of per-call-site comments."""
    return spec


def status_shadow(view):
    """Shadow object over a zero-copy readonly store view: SHARES metadata
    and spec (read-only by the scan/readonly contract) with a PRIVATE
    status clone, so a mutating status flow can run against it without
    touching committed store state. The one sanctioned way to do this —
    pair with [write_status_if_changed] for the write side. The clone is
    condition-aware-shallow (api/meta.clone_status): status flows only
    assign fields or set_condition."""
    from grove_tpu.api.meta import clone_status

    return type(view)(
        metadata=view.metadata,
        spec=view.spec,
        status=clone_status(view.status),
    )


def write_status_if_changed(
    ctx: OperatorContext, kind: str, namespace: str, name: str, status
) -> bool:
    """Write `status` only when it differs from the live object's status.

    The shared tail of every status flow: reconcilers compute the proposed
    status on a zero-copy readonly view (no serialization), and this helper
    owns the compare / mutable re-get / liveness re-check / write — one
    place to fix, three reconcilers using it. Steady-state (unchanged)
    reconciles return without touching the store. Returns True on write.
    """
    from grove_tpu.runtime.store import commit_status

    view = ctx.store.get(kind, namespace, name, readonly=True)
    if view is None or view.metadata.deletion_timestamp is not None:
        return False
    if status == view.status:
        return False
    # copy-on-write commit: the new committed object shares metadata/spec
    # with `view` and takes `status` (the caller's private shadow copy) —
    # no mutable re-get, no pickling (HttpStore falls back internally)
    return commit_status(ctx.store, view, status) is not None


def record_last_error(
    ctx: OperatorContext, kind: str, namespace: str, name: str, err
) -> None:
    """Persist a typed error on the object's status (errors.go:88-103
    LastErrors). Skips the write when the same code+description is already
    recorded — a timestamp-only rewrite would emit a self-watch event and
    defeat the workqueue's backoff with an immediate re-reconcile."""
    view = ctx.store.get(kind, namespace, name, readonly=True)
    if view is None:
        return
    entry = {
        "code": err.code,
        "description": str(err),
        "observedAt": ctx.clock.now(),
    }
    existing = view.status.last_errors
    if existing and all(
        existing[0].get(k) == entry[k] for k in ("code", "description")
    ):
        return
    fresh = ctx.store.get(kind, namespace, name)  # mutable copy for the write
    if fresh is None:
        return
    fresh.status.last_errors = [entry]
    try:
        ctx.store.update_status(fresh)
    except Exception:
        pass  # a failing status write must not mask the original error


def create_or_adopt(ctx: OperatorContext, desired) -> None:
    """Create the child if missing; otherwise adopt label/annotation drift.

    Spec is NOT adopted (it is owned by the child's own controller / HPA),
    and neither is the pod-template-hash label: the hash only moves together
    with a spec push during a rolling update (the replica-by-replica
    orchestrator does both atomically) — otherwise pods would be replaced
    against the old spec.
    """
    ns = desired.metadata.namespace
    # readonly view for the steady-state no-drift comparison; re-get a
    # mutable copy only when adoption actually writes
    current = ctx.store.get(desired.kind, ns, desired.metadata.name, readonly=True)
    if current is None:
        # share=True: `desired` may be a memoized desired-state object
        # (desired_cache) reused read-only by later reconciles — the store
        # commits a private-spined copy and never stamps identity back
        ctx.store.create(desired, share=True)
        return
    if current.metadata.deletion_timestamp is not None:
        return
    from grove_tpu.controller.podclique.status import UPDATE_IN_PROGRESS_ANNOTATION

    want_labels = dict(desired.metadata.labels)
    cur_hash = current.metadata.labels.get(namegen.LABEL_POD_TEMPLATE_HASH)
    if cur_hash is not None:
        want_labels[namegen.LABEL_POD_TEMPLATE_HASH] = cur_hash
    want_annotations = dict(desired.metadata.annotations)
    # the update-in-progress marker is owned by the rolling updater too
    if UPDATE_IN_PROGRESS_ANNOTATION in current.metadata.annotations:
        want_annotations[UPDATE_IN_PROGRESS_ANNOTATION] = (
            current.metadata.annotations[UPDATE_IN_PROGRESS_ANNOTATION]
        )
    if (
        current.metadata.labels != want_labels
        or current.metadata.annotations != want_annotations
    ):
        current = ctx.store.get(desired.kind, ns, desired.metadata.name)
        current.metadata.labels = want_labels
        current.metadata.annotations = want_annotations
        ctx.store.update(current, bump_generation=False)


def find_scaling_group_config_for_clique(
    configs: List[PodCliqueScalingGroupConfig], clique_name: str
) -> Optional[PodCliqueScalingGroupConfig]:
    """component/utils FindScalingGroupConfigForClique."""
    for cfg in configs:
        if clique_name in cfg.clique_names:
            return cfg
    return None


def translate_topology_constraint(
    tc, topology: Optional[ClusterTopology]
) -> Optional[SchedTopologyConstraint]:
    """Operator-side level *name* → scheduler-side topology *key* translation
    (docs/designs/topology.md:541-616): the user's packDomain becomes the
    `required` key; the topology's narrowest level becomes the auto-generated
    `preferred` key; spreadDomain becomes a TopologySpreadConstraint.

    Memoized per topology INSTANCE keyed by the four translated fields: the
    translation is a pure function of (those fields, topology levels), and
    the gang sync re-runs it for every PodGroup of every reconcile — at
    stress scale the same handful of template shapes translate millions of
    times. The shared result is immutable by the committed-object contract."""
    if tc is None or topology is None:
        return None
    memo_key = (
        tc.pack_domain,
        tc.spread_domain,
        tc.spread_min_domains,
        tc.spread_when_unsatisfiable,
    )
    memo = getattr(topology, "_translate_memo", None)
    if memo is None:
        memo = {}
        object.__setattr__(topology, "_translate_memo", memo)
    if memo_key in memo:
        return memo[memo_key]
    pack = spread = None
    if tc.pack_domain is not None:
        pack = TopologyPackConstraint(
            required=topology.translate_pack_domain(tc.pack_domain),
            preferred=topology.narrowest_key(),
        )
    if tc.spread_domain is not None:
        from grove_tpu.api.types import (
            SPREAD_DO_NOT_SCHEDULE,
            TopologySpreadConstraint,
        )

        spread = TopologySpreadConstraint(
            topology_key=topology.translate_pack_domain(tc.spread_domain),
            min_domains=tc.spread_min_domains or 2,
            when_unsatisfiable=(
                tc.spread_when_unsatisfiable or SPREAD_DO_NOT_SCHEDULE
            ),
        )
    result = (
        None
        if pack is None and spread is None
        else SchedTopologyConstraint(pack_constraint=pack, spread_constraint=spread)
    )
    memo[memo_key] = result
    return result


def pcs_child_selector(pcs_name: str) -> Dict[str, str]:
    return dict(namegen.default_labels(pcs_name))


def resolve_starts_after(
    pcs: PodCliqueSet,
    pcs_replica: int,
    clique_name: str,
    owner_pcsg_fqn: Optional[str] = None,
    owner_pcsg_replica: Optional[int] = None,
) -> List[Dict[str, object]]:
    """Resolve startup dependencies to (parent PCLQ FQN, minAvailable) pairs —
    the grove-initc contract (`--podcliques=<fqn>:<minAvailable>`,
    reference initc/cmd/opts/options.go; FQN resolution
    pcsg components/podclique/podclique.go:349-409).

    - InOrder: the dependency chain is the template clique order.
    - Explicit: template startsAfter names.
    - A dependency inside the *same* scaling-group replica resolves to that
      replica's sibling PCLQ; a standalone dependency resolves to the PCS
      replica's PCLQ; a dependency on another scaling group's clique resolves
      to that group's base replicas (0..minAvailable-1).
    """
    from grove_tpu.api.types import STARTUP_EXPLICIT, STARTUP_IN_ORDER

    tmpl = pcs.spec.template
    startup = tmpl.startup_type
    dep_names: List[str] = []
    if startup == STARTUP_IN_ORDER:
        clique_order = [c.name for c in tmpl.cliques]
        idx = clique_order.index(clique_name)
        if idx > 0:
            dep_names = [clique_order[idx - 1]]
    elif startup == STARTUP_EXPLICIT:
        clique = tmpl.clique_template(clique_name)
        dep_names = list(clique.spec.starts_after) if clique else []

    out: List[Dict[str, object]] = []
    for dep in dep_names:
        dep_template = tmpl.clique_template(dep)
        if dep_template is None:
            continue
        dep_min_available = dep_template.spec.min_available or 1
        dep_sg = find_scaling_group_config_for_clique(
            tmpl.pod_clique_scaling_group_configs, dep
        )
        if dep_sg is None:
            fqn = namegen.podclique_name(pcs.metadata.name, pcs_replica, dep)
            out.append({"pclq": fqn, "min_available": dep_min_available})
        elif (
            owner_pcsg_fqn is not None
            and owner_pcsg_replica is not None
            and clique_name in dep_sg.clique_names
        ):
            # same-group sibling within the same PCSG replica
            fqn = namegen.podclique_name(owner_pcsg_fqn, owner_pcsg_replica, dep)
            out.append({"pclq": fqn, "min_available": dep_min_available})
        else:
            # another scaling group: wait on its base replicas
            dep_sg_fqn = namegen.pcsg_name(pcs.metadata.name, pcs_replica, dep_sg.name)
            for r in range(dep_sg.min_available or 1):
                fqn = namegen.podclique_name(dep_sg_fqn, r, dep)
                out.append({"pclq": fqn, "min_available": dep_min_available})
    return out


def apply_template_to_pclq(ctx: OperatorContext, pcs, pclq, clique_name: str) -> bool:
    """Push the PCS template's current spec + pod-template-hash (+ the
    update-in-progress marker that suspends MinAvailableBreached) onto one
    PodClique — the single write both rolling-update orchestrators share
    (PCS replica updater for standalone cliques, PCSG updater for its own
    replicas). Returns True when a write happened."""
    import json as _json

    from grove_tpu.api.hashing import pod_template_hash_for
    from grove_tpu.api.meta import deep_copy
    from grove_tpu.controller.podclique.pods import STARTUP_DEPS_ANNOTATION
    from grove_tpu.controller.podclique.status import (
        UPDATE_IN_PROGRESS_ANNOTATION,
    )

    tmpl_root = pcs.spec.template
    tmpl = tmpl_root.clique_template(clique_name)
    if tmpl is None or pclq.metadata.deletion_timestamp is not None:
        return False
    want_hash = pod_template_hash_for(pcs, clique_name)
    changed = False
    if pclq.metadata.labels.get(namegen.LABEL_POD_TEMPLATE_HASH) != want_hash:
        new_spec = deep_copy(tmpl.spec)
        # preserve HPA-scaled replica counts on standalone cliques
        sg = find_scaling_group_config_for_clique(
            tmpl_root.pod_clique_scaling_group_configs, clique_name
        )
        if sg is None and pclq.spec.auto_scaling_config is not None:
            new_spec.replicas = pclq.spec.replicas
        pclq.spec = new_spec
        pclq.metadata.labels[namegen.LABEL_POD_TEMPLATE_HASH] = want_hash
        pcsg_fqn = pclq.metadata.labels.get(namegen.LABEL_PCSG)
        pcs_replica = int(
            pclq.metadata.labels.get(namegen.LABEL_PCS_REPLICA_INDEX, "0")
        )
        sg_replica = pclq.metadata.labels.get(namegen.LABEL_PCSG_REPLICA_INDEX)
        deps = resolve_starts_after(
            pcs,
            pcs_replica,
            clique_name,
            owner_pcsg_fqn=pcsg_fqn,
            owner_pcsg_replica=int(sg_replica) if sg_replica is not None else None,
        )
        if deps:
            pclq.metadata.annotations[STARTUP_DEPS_ANNOTATION] = _json.dumps(deps)
        else:
            pclq.metadata.annotations.pop(STARTUP_DEPS_ANNOTATION, None)
        changed = True
    if UPDATE_IN_PROGRESS_ANNOTATION not in pclq.metadata.annotations:
        pclq.metadata.annotations[UPDATE_IN_PROGRESS_ANNOTATION] = "true"
        changed = True
    if changed:
        ctx.store.update(pclq)
    return changed
