"""PodCliqueScalingGroup reconciler.

Re-host of /root/reference/operator/internal/controller/podcliquescalinggroup/
(reconcilespec.go, components/podclique/{podclique,sync}.go, reconcilestatus.go):
- materializes one PodClique per (PCSG replica × member clique) with the gang
  labels that encode the base/scaled split (podclique.go:423-449)
- scale-in removes the highest replica indices (sync.go:130-172)
- a *scaled* replica whose MinAvailableBreached persisted past TerminationDelay
  is torn down and recreated (sync.go:206-251); base-replica breaches are
  handled one level up by the PCS replica component (gang termination)
- status aggregates Scheduled/Available/Updated per PCSG replica
  (reconcilestatus.go:40-207)
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from grove_tpu.api import names as namegen
from grove_tpu.api.hashing import pod_template_hash_for
from grove_tpu.api.meta import Condition, ObjectMeta, get_condition, set_condition
from grove_tpu.api.types import (
    COND_MIN_AVAILABLE_BREACHED,
    COND_POD_CLIQUE_SCHEDULED,
    PodClique,
    PodCliqueScalingGroup,
    PodCliqueSet,
)
from grove_tpu.controller.common import (
    FINALIZER,
    OperatorContext,
    create_or_adopt,
    record_last_error,
    resolve_starts_after,
    shared_template_spec,
    write_status_if_changed,
)
from grove_tpu.controller.podclique.pods import STARTUP_DEPS_ANNOTATION
from grove_tpu.runtime.errors import GroveError
from grove_tpu.runtime.flow import (
    ReconcileStepResult,
    continue_reconcile,
    do_not_requeue,
    reconcile_after,
    reconcile_with_errors,
)
from grove_tpu.runtime.workqueue import Key


class PodCliqueScalingGroupReconciler:
    def __init__(self, ctx: OperatorContext) -> None:
        self.ctx = ctx

    # -- entry -----------------------------------------------------------

    def reconcile(self, key: Key) -> ReconcileStepResult:
        _, ns, name = key
        # readonly view: the flows read the PCSG; the one-time finalizer
        # write re-gets a mutable copy
        pcsg = self.ctx.store.get(
            "PodCliqueScalingGroup", ns, name, readonly=True
        )
        if pcsg is None:
            return do_not_requeue()
        if pcsg.metadata.deletion_timestamp is not None:
            return self._reconcile_delete(pcsg)
        pcs = self._owner_pcs(pcsg)
        if pcs is None:
            return do_not_requeue()
        try:
            if FINALIZER not in pcsg.metadata.finalizers:
                from grove_tpu.runtime.store import commit_finalizer_add

                pcsg = commit_finalizer_add(self.ctx.store, pcsg, FINALIZER)
                if pcsg is None:  # deleted between view and write
                    return do_not_requeue()
            update_requeue = self._process_rolling_update(pcsg, pcs)
            requeue_in = self._sync_podcliques(pcsg, pcs)
            self._reconcile_status(pcsg, pcs)
        except GroveError as err:
            record_last_error(self.ctx, "PodCliqueScalingGroup", ns, name, err)
            return reconcile_with_errors(f"pcsg {ns}/{name}", err)
        waits = [w for w in (update_requeue, requeue_in) if w is not None]
        if waits:
            return reconcile_after(min(waits), "pcsg update/breach wait")
        return continue_reconcile()

    def _owner_pcs(self, pcsg) -> Optional[PodCliqueSet]:
        pcs_name = pcsg.metadata.labels.get(namegen.LABEL_PART_OF, "")
        # readonly: PCSG flows only read the owner PCS (template, configs);
        # writes always target PCSG/PodClique objects fetched mutably
        return self.ctx.store.get(
            "PodCliqueSet", pcsg.metadata.namespace, pcs_name, readonly=True
        )

    def _reconcile_delete(self, pcsg) -> ReconcileStepResult:
        ns = pcsg.metadata.namespace
        self.ctx.store.delete_collection(
            "PodClique", ns, {namegen.LABEL_PCSG: pcsg.metadata.name}
        )
        self.ctx.store.remove_finalizer(
            "PodCliqueScalingGroup", ns, pcsg.metadata.name, FINALIZER
        )
        return do_not_requeue()

    # -- spec flow -------------------------------------------------------

    def _sync_podcliques(
        self, pcsg: PodCliqueScalingGroup, pcs: PodCliqueSet
    ) -> Optional[float]:
        ns = pcsg.metadata.namespace
        pcs_replica = int(
            pcsg.metadata.labels.get(namegen.LABEL_PCS_REPLICA_INDEX, "0")
        )
        sg_name = namegen.extract_sg_name_from_pcsg_fqn(
            pcsg.metadata.name, pcs.metadata.name, pcs_replica
        )

        existing_names = {
            p.metadata.name
            for p in self.ctx.store.scan(
                "PodClique",
                ns,
                {namegen.LABEL_PCSG: pcsg.metadata.name},
                cached=True,
            )
        }

        def build() -> Dict[str, PodClique]:
            out: Dict[str, PodClique] = {}
            for replica in range(pcsg.spec.replicas):
                for clique_name in pcsg.spec.clique_names:
                    pclq = self._build_pclq(
                        pcs, pcs_replica, pcsg, sg_name, replica, clique_name
                    )
                    if pclq is not None:
                        out[pclq.metadata.name] = pclq
            return out

        # pure function of the PCSG spec (its generation covers HPA scale
        # writes) and the owning PCS template (its generation covers
        # template pushes) — see ctx.desired_cache
        expected = self.ctx.desired_cache(
            (
                "pcsg-pclq",
                pcsg.metadata.uid,
                pcsg.metadata.generation,
                pcs.metadata.uid,
                pcs.metadata.generation,
            ),
            build,
        )

        # create missing; adopt label/annotation drift on existing
        for pclq in expected.values():
            create_or_adopt(self.ctx, pclq)

        # scale-in: delete excess (highest replica indices first — sync.go:130-172)
        for name in sorted(existing_names - expected.keys(), reverse=True):
            self.ctx.store.delete("PodClique", ns, name)

        return self._terminate_breached_scaled_replicas(pcsg, pcs, pcs_replica)

    def _build_pclq(
        self,
        pcs: PodCliqueSet,
        pcs_replica: int,
        pcsg: PodCliqueScalingGroup,
        sg_name: str,
        replica: int,
        clique_name: str,
    ) -> Optional[PodClique]:
        tmpl = pcs.spec.template.clique_template(clique_name)
        if tmpl is None:
            return None
        sg_cfg = None
        for cfg in pcs.spec.template.pod_clique_scaling_group_configs:
            if cfg.name == sg_name:
                sg_cfg = cfg
        min_available = (
            pcsg.spec.min_available
            if pcsg.spec.min_available
            else (sg_cfg.min_available if sg_cfg else 1)
        )

        fqn = namegen.podclique_name(pcsg.metadata.name, replica, clique_name)
        gang = namegen.podgang_name_for_pcsg_replica(
            pcs.metadata.name, pcs_replica, pcsg.metadata.name, replica, min_available
        )
        labels = dict(namegen.default_labels(pcs.metadata.name))
        labels.update(tmpl.labels)
        labels[namegen.LABEL_COMPONENT] = namegen.COMPONENT_PCSG_PODCLIQUE
        labels[namegen.LABEL_PCS_REPLICA_INDEX] = str(pcs_replica)
        labels[namegen.LABEL_PCSG] = pcsg.metadata.name
        labels[namegen.LABEL_PCSG_REPLICA_INDEX] = str(replica)
        labels[namegen.LABEL_PODGANG] = gang
        labels[namegen.LABEL_POD_TEMPLATE_HASH] = pod_template_hash_for(
            pcs, clique_name
        )
        # tenant queue label flows PCS -> PCLQ -> pods (quota accounting)
        queue = pcs.metadata.labels.get(namegen.LABEL_QUEUE)
        if queue:
            labels[namegen.LABEL_QUEUE] = queue
        if replica >= min_available:
            # scaled replica: points back at its base gang (podclique.go:423-449)
            labels[namegen.LABEL_BASE_PODGANG] = namegen.base_podgang_name(
                pcs.metadata.name, pcs_replica
            )

        annotations = dict(tmpl.annotations)
        deps = resolve_starts_after(
            pcs,
            pcs_replica,
            clique_name,
            owner_pcsg_fqn=pcsg.metadata.name,
            owner_pcsg_replica=replica,
        )
        if deps:
            annotations[STARTUP_DEPS_ANNOTATION] = json.dumps(deps)

        return PodClique(
            metadata=ObjectMeta(
                name=fqn,
                namespace=pcs.metadata.namespace,
                labels=labels,
                annotations=annotations,
            ),
            spec=shared_template_spec(tmpl.spec),
        )

    # -- rolling update (components/podclique/rollingupdate.go:55-260) ----

    def _desired_hash(self, pcs: PodCliqueSet, clique_name: str) -> Optional[str]:
        return pod_template_hash_for(pcs, clique_name)

    def _replica_pclqs(
        self, pcsg, replica: int, readonly: bool = False
    ) -> List[PodClique]:
        ns = pcsg.metadata.namespace
        out = []
        for clique_name in pcsg.spec.clique_names:
            fqn = namegen.podclique_name(pcsg.metadata.name, replica, clique_name)
            pclq = self.ctx.store.get("PodClique", ns, fqn, readonly=readonly)
            if pclq is not None:
                out.append((clique_name, pclq))
        return out

    def _replica_outdated(self, pcsg, pcs, replica: int) -> bool:
        """PCLQ label hash and the PODS' OWN hash labels both checked — the
        PCLQ's status.updatedReplicas is recomputed asynchronously, so right
        after a hash push it still reports the old-hash pod count and the
        replica would momentarily read as done (letting a second replica get
        torn down in the same pass)."""
        from grove_tpu.api.pod import is_terminating

        pairs = self._replica_pclqs(pcsg, replica, readonly=True)
        if len(pairs) < len(pcsg.spec.clique_names):
            return False  # not materialized yet; the sync builds it fresh
        ns = pcsg.metadata.namespace
        for clique_name, pclq in pairs:
            want = self._desired_hash(pcs, clique_name)
            if want is None:
                continue
            if pclq.metadata.labels.get(namegen.LABEL_POD_TEMPLATE_HASH) != want:
                return True
            fresh = [
                p
                for p in self.ctx.store.scan(
                    "Pod", ns, {namegen.LABEL_PODCLIQUE: pclq.metadata.name}
                )
                if not is_terminating(p)
                and p.metadata.labels.get(namegen.LABEL_POD_TEMPLATE_HASH)
                == want
            ]
            if len(fresh) < pclq.spec.replicas:
                return True
        return False

    def _replica_available(self, pcsg, replica: int) -> bool:
        """Every pod of the replica exists and is Ready — a replica the
        updater must take down CAREFULLY (one at a time); anything else is
        force-updated first. Checked against PODS directly: the PCLQ
        conditions lag pod reality and MinAvailableBreached reads Unknown
        while the update-in-progress marker is set, which would let the
        updater tear down the next replica while the previous one is still
        coming back."""
        from grove_tpu.api.pod import is_ready, is_terminating

        pairs = self._replica_pclqs(pcsg, replica, readonly=True)
        if len(pairs) < len(pcsg.spec.clique_names):
            return False
        ns = pcsg.metadata.namespace
        for _, pclq in pairs:
            pods = [
                p
                for p in self.ctx.store.scan(
                    "Pod", ns, {namegen.LABEL_PODCLIQUE: pclq.metadata.name}
                )
                if not is_terminating(p)
            ]
            if len(pods) < pclq.spec.replicas:
                return False
            if not all(is_ready(p) for p in pods):
                return False
        return True

    def _push_template_to_replica(self, pcsg, pcs, replica: int) -> None:
        from grove_tpu.controller.common import apply_template_to_pclq

        for clique_name, pclq in self._replica_pclqs(pcsg, replica):
            apply_template_to_pclq(self.ctx, pcs, pclq, clique_name)

    def _process_rolling_update(
        self, pcsg: PodCliqueScalingGroup, pcs: PodCliqueSet
    ) -> Optional[float]:
        """Replica-by-replica PCSG rolling update, tracked in THIS
        controller's status (reference granularity,
        podcliquescalinggroup/components/podclique/rollingupdate.go:55-260):
        force-update pending/unavailable replicas immediately, then ONE
        ready replica at a time recorded in
        ReadyReplicaIndicesSelectedToUpdate — the rest of the scaling group
        keeps serving while one replica swaps. The PCS-level updater only
        gates WHICH PCS replica updates; it no longer touches PCSG-owned
        cliques."""
        from grove_tpu.api.types import PCSGRollingUpdateProgress

        # `pcsg` may be the readonly reconcile view: the steady state (no
        # outdated replicas, no open progress) reads only; every mutating
        # branch below re-gets a private copy first
        ns = pcsg.metadata.namespace
        progress = pcsg.status.rolling_update_progress
        outdated = [
            r
            for r in range(pcsg.spec.replicas)
            if self._replica_outdated(pcsg, pcs, r)
        ]
        if not outdated:
            if progress is not None and progress.update_ended_at is None:
                fresh = self.ctx.store.get(
                    "PodCliqueScalingGroup", ns, pcsg.metadata.name
                )
                prog = (
                    fresh.status.rolling_update_progress
                    if fresh is not None
                    else None
                )
                if prog is None or prog.update_ended_at is not None:
                    return None
                prog.update_ended_at = self.ctx.clock.now()
                prog.ready_replica_indices_selected_to_update = []
                prog.updated_replica_indices = sorted(
                    set(prog.updated_replica_indices)
                    | set(range(fresh.spec.replicas))
                )
                self.ctx.store.update_status(fresh)
                self.ctx.record_event(
                    "PodCliqueScalingGroup",
                    "RollingUpdateCompleted",
                    fresh.metadata.name,
                    namespace=fresh.metadata.namespace,
                    name=fresh.metadata.name,
                )
            return None
        # active update: switch to a private mutable copy for the rest of
        # the flow (it tracks selection/progress in this CR's status)
        fresh = self.ctx.store.get(
            "PodCliqueScalingGroup", ns, pcsg.metadata.name
        )
        if fresh is None or fresh.metadata.deletion_timestamp is not None:
            return None
        pcsg = fresh
        progress = pcsg.status.rolling_update_progress

        # gate on the PCS-level replica selection: PCSGs of a replica the
        # PCS updater has not reached yet stay on the old template
        pcs_prog = pcs.status.rolling_update_progress
        my_pcs_replica = int(
            pcsg.metadata.labels.get(namegen.LABEL_PCS_REPLICA_INDEX, "0")
        )
        selected = (
            pcs_prog is not None
            and pcs_prog.update_ended_at is None
            and pcs_prog.currently_updating is not None
            and pcs_prog.currently_updating.replica_index == my_pcs_replica
        )
        if not selected and (progress is None or progress.update_ended_at is not None):
            return None

        if progress is None or progress.update_ended_at is not None:
            progress = PCSGRollingUpdateProgress(
                update_started_at=self.ctx.clock.now()
            )
            pcsg.status.rolling_update_progress = progress

        # force-update pending/unavailable replicas first (:96-130)
        ready_outdated = []
        for r in outdated:
            if self._replica_available(pcsg, r):
                ready_outdated.append(r)
            else:
                # grovelint: disable=GL002 -- grant held upstream: the PCS rolling updater cleared the broker for this whole replica before selecting it (components/rollingupdate.py _disruption_granted); an unavailable replica is also excluded from the budget tally by design
                self._push_template_to_replica(pcsg, pcs, r)

        # then one READY replica at a time (:132-260); a freshly-updated
        # replica counts as done the moment its pods carry the new hash,
        # so ALSO wait for it to become available again before tearing the
        # next one down — otherwise two replicas are dark simultaneously
        in_flight = [
            r
            for r in progress.ready_replica_indices_selected_to_update
            if r in outdated
        ]
        settling = [
            r
            for r in range(pcsg.spec.replicas)
            if r not in outdated and not self._replica_available(pcsg, r)
        ]
        if in_flight:
            # grovelint: disable=GL002 -- grant held upstream: in-flight replica was broker-cleared by the PCS rolling updater at selection time
            self._push_template_to_replica(pcsg, pcs, in_flight[0])
        elif ready_outdated and not settling:
            pick = ready_outdated[0]
            progress.ready_replica_indices_selected_to_update.append(pick)
            # grovelint: disable=GL002 -- grant held upstream: this PCSG update only starts while the PCS replica is `selected`, which required _disruption_granted in components/rollingupdate.py
            self._push_template_to_replica(pcsg, pcs, pick)
            self.ctx.record_event(
                "PodCliqueScalingGroup",
                "RollingUpdateReplicaStarted",
                f"{pcsg.metadata.name} replica {pick}",
                namespace=pcsg.metadata.namespace,
                name=pcsg.metadata.name,
            )

        # bookkeeping: replicas no longer outdated are done
        done = [
            r for r in range(pcsg.spec.replicas) if r not in outdated
        ]
        merged = sorted(set(progress.updated_replica_indices) | set(done))
        progress.updated_replica_indices = merged
        self.ctx.store.update_status(pcsg)
        return 2.0

    # -- scaled-replica gang termination ---------------------------------

    def _terminate_breached_scaled_replicas(
        self, pcsg: PodCliqueScalingGroup, pcs: PodCliqueSet, pcs_replica: int
    ) -> Optional[float]:
        """sync.go:206-251: a scaled replica breached longer than
        TerminationDelay is deleted (then recreated by the next sync).
        Returns the minimum remaining wait if any replica is breached."""
        delay = pcs.spec.template.termination_delay or 0.0
        now = self.ctx.clock.now()
        min_available = pcsg.spec.min_available
        ns = pcsg.metadata.namespace
        min_wait: Optional[float] = None
        for replica in range(min_available, pcsg.spec.replicas):
            breach_since = self._replica_breach_since(pcsg, replica)
            if breach_since is None:
                continue
            age = now - breach_since
            if age >= delay:
                for clique_name in pcsg.spec.clique_names:
                    fqn = namegen.podclique_name(
                        pcsg.metadata.name, replica, clique_name
                    )
                    if self.ctx.store.get("PodClique", ns, fqn) is not None:
                        self.ctx.store.delete("PodClique", ns, fqn)
                self.ctx.record_event(
                    "PodCliqueScalingGroup",
                    "ScaledReplicaGangTerminated",
                    f"{pcsg.metadata.name} replica {replica}",
                    namespace=pcsg.metadata.namespace,
                    name=pcsg.metadata.name,
                )
            else:
                remaining = delay - age
                min_wait = remaining if min_wait is None else min(min_wait, remaining)
        return min_wait

    def _replica_breach_since(
        self, pcsg: PodCliqueScalingGroup, replica: int
    ) -> Optional[float]:
        """Earliest still-True MinAvailableBreached transition among the
        replica's constituent PCLQs (None if none breached)."""
        ns = pcsg.metadata.namespace
        since: Optional[float] = None
        for clique_name in pcsg.spec.clique_names:
            fqn = namegen.podclique_name(pcsg.metadata.name, replica, clique_name)
            pclq = self.ctx.store.get("PodClique", ns, fqn, cached=True)
            if pclq is None:
                continue
            cond = get_condition(pclq.status.conditions, COND_MIN_AVAILABLE_BREACHED)
            if cond is not None and cond.is_true():
                t = cond.last_transition_time
                since = t if since is None else min(since, t)
        return since

    # -- status flow -----------------------------------------------------

    def _reconcile_status(
        self, pcsg: PodCliqueScalingGroup, pcs: PodCliqueSet
    ) -> None:
        ns = pcsg.metadata.namespace
        # compute on the zero-copy view; write only on difference (the
        # steady state then costs no serialization at all)
        view = self.ctx.store.get(
            "PodCliqueScalingGroup", ns, pcsg.metadata.name, readonly=True
        )
        if view is None or view.metadata.deletion_timestamp is not None:
            return
        from grove_tpu.controller.common import status_shadow

        fresh = status_shadow(view)
        scheduled = available = updated = 0
        for replica in range(fresh.spec.replicas):
            pclqs: List[PodClique] = []
            for clique_name in fresh.spec.clique_names:
                fqn = namegen.podclique_name(fresh.metadata.name, replica, clique_name)
                pclq = self.ctx.store.get(
                    "PodClique", ns, fqn, cached=True, readonly=True
                )
                if pclq is not None:
                    pclqs.append(pclq)
            if len(pclqs) < len(fresh.spec.clique_names):
                continue
            if all(
                (c := get_condition(p.status.conditions, COND_POD_CLIQUE_SCHEDULED))
                is not None
                and c.is_true()
                for p in pclqs
            ):
                scheduled += 1
            if not any(
                (c := get_condition(p.status.conditions, COND_MIN_AVAILABLE_BREACHED))
                is not None
                and c.is_true()
                for p in pclqs
            ):
                available += 1
            if all(
                p.status.updated_replicas >= p.spec.replicas for p in pclqs
            ):
                updated += 1

        st = fresh.status
        st.observed_generation = fresh.metadata.generation
        st.replicas = fresh.spec.replicas
        st.scheduled_replicas = scheduled
        st.available_replicas = available
        st.updated_replicas = updated
        st.selector = f"{namegen.LABEL_PCSG}={fresh.metadata.name}"
        now = self.ctx.clock.now()
        set_condition(st.conditions, self._breached_condition(fresh), now)
        write_status_if_changed(
            self.ctx, "PodCliqueScalingGroup", ns, pcsg.metadata.name, st
        )

    @staticmethod
    def _breached_condition(pcsg: PodCliqueScalingGroup) -> Condition:
        """reconcilestatus.go:149-207 — with the same never-scheduled guard
        as the PCLQ condition."""
        min_available = pcsg.spec.min_available
        if pcsg.status.scheduled_replicas < min_available:
            return Condition(
                type=COND_MIN_AVAILABLE_BREACHED,
                status="False",
                reason="InsufficientScheduledReplicas",
                message=(
                    f"Insufficient scheduled replicas. expected at least:"
                    f" {min_available}, found: {pcsg.status.scheduled_replicas}"
                ),
            )
        if pcsg.status.available_replicas < min_available:
            return Condition(
                type=COND_MIN_AVAILABLE_BREACHED,
                status="True",
                reason="InsufficientAvailableReplicas",
                message=(
                    f"Insufficient available replicas. expected at least:"
                    f" {min_available}, found: {pcsg.status.available_replicas}"
                ),
            )
        return Condition(
            type=COND_MIN_AVAILABLE_BREACHED,
            status="False",
            reason="SufficientAvailableReplicas",
            message="Sufficient available replicas",
        )
