"""Gang-aware node drain: cordon → budget-checked, trial-solved, gang-whole
eviction (docs/robustness.md "draining a node").

The maintenance path PR 4's involuntary lifecycle had no answer for:
taking a node out of service WITHOUT simulating a crash. The workflow per
draining node, one monitor-style tick at a time:

1. **Cordon** — the node stops being a placement target immediately
   (``Node.cordoned`` feeds ``Node.schedulable``, the single solve mask).
2. For every scheduled gang with a pod on the node, in deterministic
   order:
   a. **Budget check** — the DisruptionBroker must grant the eviction
      (per-PCS ``disruptionBudget``, quiet window, storm breaker). A
      denial leaves the gang bound; the drain retries next tick.
   b. **Trial-solve pre-placement** — the WHOLE gang is trial-solved
      against the remaining schedulable nodes with its own current usage
      credited back (the scheduler's trial machinery, same kernel the
      preemption/reclaim paths use). Admitted ⇒ a placement exists
      BEFORE any pod dies; the planned nodes are recorded and the normal
      solve re-places the gang right after eviction.
   c. **Gang-whole eviction** — all of the gang's pods are deleted
      together (gang semantics: pods of one gang never dribble away
      one by one), ``DisruptionTarget=True``/``Scheduled=False`` reason
      ``Drained``. With a verified pre-placement the gang re-enters the
      very next solve; WITHOUT one (cluster too full) it falls back to
      terminate-and-requeue under the node-health monitor's rate-limited
      backoff — the same pacing a node-failure termination gets — and the
      failure feeds the storm breaker.
3. When no bound pods remain the node reports **Drained**
   (``NodeDrained``); ``uncordon`` returns it to service.

Drain INTENT is persisted as a cluster-scoped ``NodeDrain`` object in the
store, not broker/controller memory: a leader failover mid-drain resumes
the workflow from the store (chaos ``leader_crash`` fault pins this).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from grove_tpu.api import names as namegen
from grove_tpu.api.meta import ObjectMeta, get_condition
from grove_tpu.api.types import (
    COND_PODGANG_SCHEDULED,
    GenericObject,
)
from grove_tpu.observability.events import (
    EVENTS,
    REASON_GANG_DRAINED,
    REASON_NODE_DRAINED,
    REASON_NODE_DRAINING,
    REASON_NODE_UNCORDONED,
    TYPE_NORMAL,
    TYPE_WARNING,
)
from grove_tpu.observability.metrics import METRICS
from grove_tpu.observability.tracing import TRACER
from grove_tpu.runtime.errors import GroveError

DRAIN_DRAINING = "Draining"
DRAIN_DRAINED = "Drained"

GangKey = Tuple[str, str]


class NodeDrainController:
    """Tick-driven drain workflow over one store/cluster/scheduler triple.

    Level-triggered off the persisted ``NodeDrain`` intents — the
    controller itself keeps no drain state, so a fresh instance (leader
    failover) resumes every in-flight drain from the store.
    """

    def __init__(self, store, cluster, scheduler, monitor, broker) -> None:
        self.store = store
        self.cluster = cluster
        self.scheduler = scheduler
        self.monitor = monitor
        self.broker = broker
        # archive of completed gang evictions for smokes/benches:
        # {gang, node, pre_placed, planned_nodes, at}
        self.drained_gangs: List[dict] = []

    # -- operator actions --------------------------------------------------

    def request_drain(self, node_name: str) -> Optional[dict]:
        """Cordon the node and persist the drain intent. Returns the wire
        row (as in GET /nodes) or None when the node does not exist.
        Idempotent: re-requesting an active drain is a no-op."""
        node = self.cluster.node(node_name)
        if node is None:
            return None
        self.broker.arm()
        node.cordoned = True
        if self.store.get("NodeDrain", "", node_name) is None:
            try:
                self.store.create(
                    GenericObject(
                        kind="NodeDrain",
                        metadata=ObjectMeta(name=node_name, namespace=""),
                        spec={
                            "state": DRAIN_DRAINING,
                            "requestedAt": self.store.clock.now(),
                        },
                    )
                )
            except GroveError:
                pass  # lost a create race / transient outage: intent-only
        EVENTS.record(
            ("Node", "", node_name),
            TYPE_NORMAL,
            REASON_NODE_DRAINING,
            "drain requested: node cordoned; evicting its gangs whole,"
            " budget-checked",
        )
        METRICS.inc("node_drains_requested_total")
        return {"name": node_name, "drain": DRAIN_DRAINING}

    def uncordon(self, node_name: str) -> Optional[dict]:
        """Return the node to service: clear the cordon and drop any drain
        intent. Returns the wire row or None when the node is unknown."""
        node = self.cluster.node(node_name)
        if node is None:
            return None
        node.cordoned = False
        try:
            self.store.delete("NodeDrain", "", node_name)
        except GroveError:
            pass  # absent or transient outage; cordon flag is cleared
        EVENTS.record(
            ("Node", "", node_name),
            TYPE_NORMAL,
            REASON_NODE_UNCORDONED,
            "node uncordoned; schedulable again",
        )
        return {"name": node_name, "drain": ""}

    # -- surfacing ---------------------------------------------------------

    def states(self) -> Dict[str, str]:
        """node name -> Draining|Drained (absent = not draining); feeds the
        GET /nodes drain column."""
        return {
            d.metadata.name: d.spec.get("state", DRAIN_DRAINING)
            for d in self.store.scan("NodeDrain")
        }

    def drain_state(self, node_name: str) -> str:
        d = self.store.get("NodeDrain", "", node_name, readonly=True)
        return d.spec.get("state", DRAIN_DRAINING) if d is not None else ""

    def next_deadline(self) -> Optional[float]:
        """Drains in flight progress on ticks; a denied eviction (quiet
        window, backoff) needs the harness to keep virtual time moving.
        One second is the drain's retry cadence."""
        for d in self.store.scan("NodeDrain"):
            if d.spec.get("state") == DRAIN_DRAINING:
                return self.store.clock.now() + 1.0
        return None

    # -- tick --------------------------------------------------------------

    def tick(self) -> int:
        """One drain round over every persisted intent. Returns actions
        taken (evictions + completions) so harness quiescence sees drain
        work as progress."""
        # per-tick disruption gauges (breaker state, tokens, per-PCS
        # disrupted counts) — a no-op while the broker is un-armed
        self.broker.export_gauges()
        intents = sorted(
            self.store.scan("NodeDrain"), key=lambda d: d.metadata.name
        )
        if not intents:
            return 0
        actions = 0
        with TRACER.span("drain.tick", nodes=len(intents)) as span:
            for intent in intents:
                actions += self._drain_node(intent)
            span.set("actions", actions)
        return actions

    def _drain_node(self, intent) -> int:
        node_name = intent.metadata.name
        node = self.cluster.node(node_name)
        if node is None:
            # node left the cluster: the drain is moot
            try:
                self.store.delete("NodeDrain", "", node_name)
            except GroveError:
                pass
            return 1
        # re-assert the cordon level-triggered: a failover may have rebuilt
        # the Node objects from infra state without the cordon flag
        node.cordoned = True
        gangs = self._bound_gangs(node_name)
        if not gangs:
            if intent.spec.get("state") != DRAIN_DRAINED:
                fresh = self.store.get("NodeDrain", "", node_name)
                if fresh is not None:
                    fresh.spec = dict(
                        fresh.spec,
                        state=DRAIN_DRAINED,
                        drainedAt=self.store.clock.now(),
                    )
                    try:
                        self.store.update(fresh, bump_generation=False)
                    except GroveError:
                        return 0  # retry next tick
                EVENTS.record(
                    ("Node", "", node_name),
                    TYPE_NORMAL,
                    REASON_NODE_DRAINED,
                    "no bound pods remain; node drained (still cordoned"
                    " until uncordon)",
                )
                METRICS.inc("node_drains_completed_total")
                return 1
            return 0
        evicted = 0
        for key in gangs:
            gang = self.store.get("PodGang", key[0], key[1], readonly=True)
            if gang is None:
                continue
            cond = get_condition(
                gang.status.conditions, COND_PODGANG_SCHEDULED
            )
            if cond is None or not cond.is_true():
                continue  # already being disrupted/re-placed; wait
            if not self.broker.grant([gang], "drain"):
                # budget/quiet-window/breaker denial for THIS gang: keep
                # walking — other sets' gangs on the node may still be
                # drainable (a budget-0 set must not starve its neighbors);
                # the denied gang retries next tick
                continue
            pre_placed, planned = self._trial_preplacement(gang)
            self._evict_gang_whole(gang, node_name, pre_placed)
            if not pre_placed:
                # terminate-and-requeue fallback: pace re-admission like a
                # node-failure termination, and feed the storm breaker
                self.monitor.hold_gang(key)
                self.broker.note_failure(
                    reason=f"drained gang {key[0]}/{key[1]} has no placement"
                    " on the remaining nodes"
                )
            self.drained_gangs.append(
                {
                    "namespace": key[0],
                    "gang": key[1],
                    "node": node_name,
                    "pre_placed": pre_placed,
                    "planned_nodes": planned,
                    "at": self.store.clock.now(),
                }
            )
            evicted += 1
        return evicted

    # -- internals ---------------------------------------------------------

    def _bound_gangs(self, node_name: str) -> List[GangKey]:
        """Gangs with >=1 pod bound to the node, deterministic order."""
        out = set()
        for (ns, pod_name), bound in list(self.cluster.bindings.items()):
            if bound != node_name:
                continue
            pod = self.store.get("Pod", ns, pod_name, readonly=True)
            if pod is None:
                continue
            gang_name = pod.metadata.labels.get(namegen.LABEL_PODGANG)
            if gang_name:
                out.add((ns, gang_name))
        return sorted(out)

    def _gang_spec(self, gang) -> dict:
        """Whole-gang solver spec from the CR (the drain analogue of the
        scheduler's _encode_pending, without recovery pins — the entire
        gang relocates, nothing anchors it). One shared implementation
        with the what-if engine (solver/introspect.py), so a hypothetical
        drain and a real drain judge relocation identically."""
        from grove_tpu.solver.introspect import gang_spec_from_cr

        return gang_spec_from_cr(self.store, self.scheduler, gang)

    def _trial_preplacement(self, gang) -> Tuple[bool, List[str]]:
        """Trial-solve the whole gang on the remaining schedulable nodes
        with its own bound usage credited back (it is about to be evicted
        everywhere). Returns (placement exists, planned node list)."""
        nodes = [n for n in self.cluster.nodes if n.schedulable]
        if not nodes:
            return False, []
        free = self.cluster.node_free_all(nodes)
        trial_free = {name: dict(caps) for name, caps in free.items()}
        for group in gang.spec.pod_groups:
            for ref in group.pod_references:
                bound = self.cluster.bindings.get((ref.namespace, ref.name))
                if bound is None or bound not in trial_free:
                    continue  # on the drained (cordoned) node: not credited
                pod = self.store.get(
                    "Pod", ref.namespace, ref.name, readonly=True
                )
                if pod is None:
                    continue
                caps = trial_free[bound]
                for r, q in pod.spec.total_requests().items():
                    caps[r] = caps.get(r, 0.0) + q
        spec = self._gang_spec(gang)
        with TRACER.span(
            "drain.trial", gang=spec["name"], nodes=len(nodes)
        ) as span:
            result, problem = self.scheduler._solve_batch(
                nodes, [spec], trial_free
            )
            admitted = bool(result.admitted[0])
            span.set("admitted", admitted)
        if not admitted:
            return False, []
        planned: List[str] = []
        assignments = result.assignments(problem)
        for _group, node_names in sorted(
            assignments.get(spec["name"], {}).items()
        ):
            planned.extend(node_names)
        return True, planned

    def _evict_gang_whole(self, gang, node_name: str, pre_placed: bool) -> None:
        message = (
            f"node {node_name} draining; gang evicted whole"
            + (
                " (placement on remaining nodes verified before eviction)"
                if pre_placed
                else " (no placement on remaining nodes: terminate-and-"
                "requeue under backoff)"
            )
        )
        self.scheduler._evict_victim(
            gang,
            {"name": f"drain/{node_name}"},
            disruption_reason="Drained",
            sched_reason="Drained",
            event_reason=REASON_GANG_DRAINED,
            message=message,
            metric="gang_drains_total",
        )
