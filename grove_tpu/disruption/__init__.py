"""Voluntary-disruption layer (docs/robustness.md).

PR 4 gave the control plane an involuntary-failure story (node loss, gang
rescue); this package is the VOLUNTARY counterpart: every disruptor that
chooses to evict — node drain, priority preemption, quota reclaim, rolling
update — consults one ``DisruptionBroker`` that enforces per-PodCliqueSet
``disruptionBudget``s and a cluster-wide disruption-storm circuit breaker,
and the ``NodeDrainController`` runs the cordon → budget-checked,
trial-solved, gang-whole eviction workflow.
"""

from grove_tpu.disruption.broker import (
    VOLUNTARY_REASONS,
    DisruptionBroker,
)
from grove_tpu.disruption.drain import (
    DRAIN_DRAINED,
    DRAIN_DRAINING,
    NodeDrainController,
)

__all__ = [
    "DisruptionBroker",
    "NodeDrainController",
    "VOLUNTARY_REASONS",
    "DRAIN_DRAINING",
    "DRAIN_DRAINED",
]
