"""DisruptionBroker: the single gate every voluntary disruptor consults.

Four disruptors can evict a scheduled gang on purpose — node drain
(disruption/drain.py), priority preemption and quota reclaim
(solver/scheduler.py), and rolling update (podcliqueset/components/
rollingupdate.py). Before this broker existed they acted independently, so
concurrent disruptors could stack evictions on one workload (drain takes a
gang while a reclaim takes its sibling) and a misbehaving loop could storm
the cluster with evictions faster than the solver re-admits them — exactly
the churn/goodput collapse the scheduling-policy literature flags
(Tesserae, arXiv 2508.04953; fragmentation/starvation, arXiv 2512.10980).

Two mechanisms, one ``grant()`` call:

- **Per-PodCliqueSet budget** (``spec.template.disruptionBudget``): at most
  ``maxUnavailableGangs`` of a set's gangs may be unavailable when a
  voluntary disruption is granted (involuntary failures count toward the
  tally — a set already degraded by a node loss doesn't also get drained),
  plus an optional ``quietWindow`` pacing consecutive grants per set.
- **Cluster-wide storm circuit breaker**: a token bucket on granted
  voluntary evictions per virtual-time window. Exhausting it — or repeated
  post-disruption placement failures reported via ``note_failure()`` —
  OPENS the breaker: every voluntary disruption is denied
  (``DisruptionThrottled``) until a quiet window with no disruption
  pressure passes, then it closes (``BreakerClosed``).

Inertness guard rail (same contract as the quota subsystem): with no
``disruptionBudget`` configured anywhere and no drain ever requested, the
broker is INERT — ``grant()`` returns True without consuming tokens,
recording state, or emitting anything, so admissions and solve order stay
byte-identical to a broker-less control plane (A/B pinned by
``make drain-smoke`` and tests/test_disruption.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from grove_tpu.api import names as namegen
from grove_tpu.api.meta import get_condition
from grove_tpu.api.types import (
    COND_PODGANG_DISRUPTION_TARGET,
    COND_PODGANG_SCHEDULED,
)
from grove_tpu.observability.events import (
    EVENTS,
    REASON_BREAKER_CLOSED,
    REASON_BREAKER_OPEN,
    REASON_DISRUPTION_THROTTLED,
    TYPE_NORMAL,
    TYPE_WARNING,
)
from grove_tpu.observability.metrics import METRICS
from grove_tpu.observability.tracing import TRACER

# DisruptionTarget reasons that mark a VOLUNTARY disruption (the budget
# invariant counts these; involuntary NodeFailure counts toward the
# unavailable tally but never against the voluntary ledger)
VOLUNTARY_REASONS = (
    "Drained",
    "PreemptedByHigherPriority",
    "QuotaReclaimed",
    "RollingUpdate",
)

PCSKey = Tuple[str, str]  # (namespace, PodCliqueSet name)


class DisruptionBroker:
    """Budget + breaker arbiter over one store/cluster pair.

    All state is in-memory except what the store already carries (gang
    conditions); after a leader failover the budget check is immediately
    exact again (it recounts from conditions) while breaker tokens restart
    full — a fresh leader should not inherit a storm verdict it cannot
    re-derive.
    """

    def __init__(
        self,
        store,
        *,
        bucket_capacity: float = 12.0,
        refill_per_second: float = 0.5,
        close_after: float = 30.0,
    ) -> None:
        self.store = store
        # token bucket (virtual time): capacity evictions of burst, then
        # refill_per_second sustained; exhaustion opens the breaker
        self.bucket_capacity = float(bucket_capacity)
        self.refill_per_second = float(refill_per_second)
        self.close_after = float(close_after)
        self._tokens = self.bucket_capacity
        self._last_refill: Optional[float] = None
        self._open_since: Optional[float] = None
        # per-PCS quiet-window ledger
        self._last_grant: Dict[PCSKey, float] = {}
        # armed the first time a drain is requested; budgets arm implicitly
        self._armed = False

    # -- activation (the inertness guard rail) ---------------------------

    def arm(self) -> None:
        """Engage the breaker machinery explicitly — the drain controller
        arms on the first drain request; budgets arm via active()."""
        self._armed = True

    def active(self) -> bool:
        """True once any disruptionBudget exists or a drain was requested.
        While False every check short-circuits to 'allow' with zero state
        touched (byte-identical admissions, the A/B contract)."""
        if self._armed:
            return True
        for pcs in self.store.scan("PodCliqueSet"):
            if pcs.spec.template.disruption_budget is not None:
                self._armed = True  # sticky: budgets may come and go
                return True
        return False

    # -- budget bookkeeping ----------------------------------------------

    def _owner_pcs_key(self, gang) -> Optional[PCSKey]:
        name = gang.metadata.labels.get(namegen.LABEL_PART_OF)
        if not name:
            return None
        return (gang.metadata.namespace, name)

    def _budget_of(self, pcs_key: PCSKey):
        pcs = self.store.get(
            "PodCliqueSet", pcs_key[0], pcs_key[1], readonly=True
        )
        if pcs is None:
            return None
        return pcs.spec.template.disruption_budget

    def unavailable_gangs(
        self, pcs_key: PCSKey, excluding: Optional[set] = None
    ) -> int:
        """Gangs of the set currently NOT Scheduled=True — any cause. This
        is the tally a voluntary request is budget-checked against: a set
        degraded by a node loss must not also lose gangs to a drain.
        ``excluding`` drops the request's own victims from the count — a
        victim that is ALREADY unavailable (rolling update picking a downed
        replica first) doesn't reduce availability twice."""
        ns, name = pcs_key
        n = 0
        for gang in self.store.scan(
            "PodGang", ns, {namegen.LABEL_PART_OF: name}
        ):
            if excluding and (ns, gang.metadata.name) in excluding:
                continue
            cond = get_condition(
                gang.status.conditions, COND_PODGANG_SCHEDULED
            )
            if cond is None or not cond.is_true():
                n += 1
        return n

    def voluntarily_disrupted_gangs(self, pcs_key: PCSKey) -> int:
        """Gangs of the set unavailable due to a VOLUNTARY disruption —
        the per-tick invariant the chaos harness and drain smoke assert
        never exceeds maxUnavailableGangs."""
        ns, name = pcs_key
        n = 0
        for gang in self.store.scan(
            "PodGang", ns, {namegen.LABEL_PART_OF: name}
        ):
            sched = get_condition(
                gang.status.conditions, COND_PODGANG_SCHEDULED
            )
            if sched is not None and sched.is_true():
                continue
            dt = get_condition(
                gang.status.conditions, COND_PODGANG_DISRUPTION_TARGET
            )
            if dt is not None and dt.is_true() and dt.reason in VOLUNTARY_REASONS:
                n += 1
        return n

    # -- breaker ----------------------------------------------------------

    @property
    def breaker_open(self) -> bool:
        return self._open_since is not None

    def _refill(self, now: float) -> None:
        if self._last_refill is None:
            self._last_refill = now
            return
        dt = max(0.0, now - self._last_refill)
        self._tokens = min(
            self.bucket_capacity, self._tokens + dt * self.refill_per_second
        )
        self._last_refill = now

    def _open(self, now: float, why: str) -> None:
        if self._open_since is not None:
            return
        self._open_since = now
        EVENTS.record(
            ("DisruptionBroker", "", "cluster"),
            TYPE_WARNING,
            REASON_BREAKER_OPEN,
            f"disruption-storm circuit breaker opened: {why}; all voluntary"
            f" disruptions denied until {self.close_after:g}s of quiet",
        )
        METRICS.inc("disruption_breaker_opens_total")
        from grove_tpu.observability.flightrec import FLIGHTREC

        if FLIGHTREC.enabled:
            # a breaker open IS an incident: ship the telemetry that led
            # to it (the eviction storm's commits/events/spans), not just
            # the event saying it happened
            FLIGHTREC.trigger("breaker-open", why)

    def _maybe_close(self, now: float) -> None:
        # fixed cooldown from OPENING, deliberately not from the last
        # denied request: a patiently retrying drain polls every tick, and
        # counting those denials as "pressure" would hold the breaker open
        # forever. A storm that persists past the cooldown just re-opens it
        # on the next exhaustion — a bounded duty cycle, not a latch.
        if self._open_since is None:
            return
        if now - self._open_since < self.close_after:
            return
        self._open_since = None
        self._tokens = self.bucket_capacity  # fresh window after the storm
        EVENTS.record(
            ("DisruptionBroker", "", "cluster"),
            TYPE_NORMAL,
            REASON_BREAKER_CLOSED,
            f"quiet window ({self.close_after:g}s) elapsed; breaker closed",
        )

    def note_failure(self, weight: float = 2.0, reason: str = "") -> None:
        """Report a post-disruption failure (a drained gang with no
        placement, a rescue that fell through): drains the bucket faster
        than a clean eviction, so repeated failures open the breaker even
        at a low eviction rate."""
        if not self.active():
            return
        now = self.store.clock.now()
        self._refill(now)
        self._tokens -= weight
        if self._tokens <= 0.0:
            self._tokens = 0.0
            self._open(now, reason or "repeated placement failures")
        METRICS.set("disruption_tokens", self._tokens)

    # -- the gate ----------------------------------------------------------

    def would_allow(self, gang, now: Optional[float] = None) -> bool:
        """Pure check (no state touched): used by disruptors to FILTER
        candidate victims before running expensive trial solves. A later
        grant() may still deny if the world moved."""
        if not self.active():
            return True
        now = self.store.clock.now() if now is None else now
        if self.breaker_open:
            # closing is grant()'s job; a pure check must not mutate
            if now - self._open_since < self.close_after:
                return False
        pcs_key = self._owner_pcs_key(gang)
        if pcs_key is None:
            return True
        budget = self._budget_of(pcs_key)
        if budget is None:
            return True
        cap = budget.max_unavailable_gangs or 0
        me = {(gang.metadata.namespace, gang.metadata.name)}
        if self.unavailable_gangs(pcs_key, excluding=me) + 1 > cap:
            return False
        if budget.quiet_window is not None:
            last = self._last_grant.get(pcs_key)
            if last is not None and now - last < budget.quiet_window:
                return False
        return True

    def grant(self, gangs: List, source: str) -> bool:
        """All-or-nothing grant for one disruptor's victim set: every gang
        must clear the breaker, its set's budget (counting the OTHER gangs
        of this very request against the same budget), and its set's quiet
        window — or nothing is granted. On success the tokens/ledgers are
        committed; the caller must actually evict."""
        if not self.active():
            return True
        now = self.store.clock.now()
        with TRACER.span(
            "disruption.grant", source=source, victims=len(gangs)
        ) as span:
            ok = self._grant(gangs, source, now)
            span.set("granted", ok)
            return ok

    def _grant(self, gangs: List, source: str, now: float) -> bool:
        self._maybe_close(now)
        if self.breaker_open:
            for gang in gangs:
                EVENTS.record(
                    (
                        "PodGang",
                        gang.metadata.namespace,
                        gang.metadata.name,
                    ),
                    TYPE_WARNING,
                    REASON_DISRUPTION_THROTTLED,
                    f"{source} denied: disruption-storm breaker is open",
                )
            METRICS.inc("disruption_throttled_total", len(gangs))
            return False
        self._refill(now)
        if self._tokens < len(gangs):
            self._open(
                now,
                f"voluntary-eviction budget exhausted ({source} asked for"
                f" {len(gangs)} eviction(s), {self._tokens:.1f} token(s)"
                " left)",
            )
            for gang in gangs:
                EVENTS.record(
                    (
                        "PodGang",
                        gang.metadata.namespace,
                        gang.metadata.name,
                    ),
                    TYPE_WARNING,
                    REASON_DISRUPTION_THROTTLED,
                    f"{source} denied: eviction storm (breaker opened)",
                )
            METRICS.inc("disruption_throttled_total", len(gangs))
            METRICS.set("disruption_tokens", self._tokens)
            return False
        # budget check with the REQUEST's own victims counted: two gangs of
        # one budget-1 set in a single victim set must be denied together —
        # while victims already unavailable on their own (downed replica
        # being rolled) are excluded from the base tally, not counted twice
        victim_keys = {
            (g.metadata.namespace, g.metadata.name) for g in gangs
        }
        extra: Dict[PCSKey, int] = {}
        for gang in gangs:
            pcs_key = self._owner_pcs_key(gang)
            if pcs_key is None:
                continue
            budget = self._budget_of(pcs_key)
            if budget is None:
                continue
            cap = budget.max_unavailable_gangs or 0
            pending = extra.get(pcs_key, 0)
            if (
                self.unavailable_gangs(pcs_key, excluding=victim_keys)
                + pending
                + 1
                > cap
            ):
                self._deny_budget(gang, pcs_key, source, cap)
                return False
            if budget.quiet_window is not None:
                last = self._last_grant.get(pcs_key)
                if last is not None and now - last < budget.quiet_window:
                    EVENTS.record(
                        (
                            "PodGang",
                            gang.metadata.namespace,
                            gang.metadata.name,
                        ),
                        TYPE_WARNING,
                        REASON_DISRUPTION_THROTTLED,
                        f"{source} denied: quiet window"
                        f" ({budget.quiet_window:g}s) of"
                        f" {pcs_key[0]}/{pcs_key[1]} still running",
                    )
                    METRICS.inc("disruption_throttled_total")
                    return False
            extra[pcs_key] = pending + 1
        # commit
        self._tokens -= len(gangs)
        for pcs_key in extra:
            self._last_grant[pcs_key] = now
        METRICS.inc(f"voluntary_disruptions_total/{source}", len(gangs))
        METRICS.set("disruption_tokens", self._tokens)
        return True

    def _deny_budget(
        self, gang, pcs_key: PCSKey, source: str, cap: int
    ) -> None:
        EVENTS.record(
            ("PodGang", gang.metadata.namespace, gang.metadata.name),
            TYPE_WARNING,
            REASON_DISRUPTION_THROTTLED,
            f"{source} denied: disruptionBudget of {pcs_key[0]}/{pcs_key[1]}"
            f" (maxUnavailableGangs={cap}) would be exceeded",
        )
        METRICS.inc("disruption_throttled_total")

    # -- observability -----------------------------------------------------

    def export_gauges(self) -> None:
        """Per-tick gauges (only once armed — an inert broker exports
        nothing): breaker state, tokens, and per-budgeted-PCS disruption
        counts."""
        if not self._armed:
            return
        now = self.store.clock.now()
        self._maybe_close(now)
        self._refill(now)
        METRICS.set("disruption_breaker_open", 1.0 if self.breaker_open else 0.0)
        METRICS.set("disruption_tokens", self._tokens)
        for pcs in self.store.scan("PodCliqueSet"):
            if pcs.spec.template.disruption_budget is None:
                continue
            key = (pcs.metadata.namespace, pcs.metadata.name)
            METRICS.set(
                f"pcs_disrupted_gangs/{key[0]}/{key[1]}",
                self.voluntarily_disrupted_gangs(key),
            )
