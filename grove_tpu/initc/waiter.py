"""Pod-side startup-ordering waiter (grove-initc equivalent).

Re-host of /root/reference/operator/initc/internal/wait.go:110-275: an init
step that blocks the pod's main containers until every parent clique has at
least minAvailable Ready pods. Like the reference, it observes only pods
carrying its own `grove.io/podgang` label (the downward-API-provided gang
name, wait.go:76-90) and maps pods to parent cliques by name prefix
(wait.go:240-265).

In the simulator the kubelet calls `is_ready_to_start` each tick instead of
running a blocking informer; `Waiter` keeps the blocking-CLI shape for a real
deployment (it polls the same predicate).
"""

from __future__ import annotations

import sys
from typing import Dict, List

from grove_tpu.api import names as namegen
from grove_tpu.api.pod import is_ready
from grove_tpu.runtime.errors import ERR_TRANSPORT, GroveError
from grove_tpu.runtime.store import Store


def parent_ready_counts(
    store: Store, namespace: str, podgang: str, parent_pclqs: List[str]
) -> Dict[str, int]:
    pods = store.list("Pod", namespace, {namegen.LABEL_PODGANG: podgang})
    counts = {p: 0 for p in parent_pclqs}
    for pod in pods:
        if not is_ready(pod):
            continue
        # exact pod→clique mapping via the podclique label (the reference
        # prefix-matches, wait.go:240-265, but picks exactly one parent;
        # the label avoids prefix collisions between clique names)
        parent = pod.metadata.labels.get(namegen.LABEL_PODCLIQUE)
        if parent in counts:
            counts[parent] += 1
    return counts


def is_ready_to_start(store: Store, namespace: str, waiter_config: Dict) -> bool:
    """waiter_config = {"podcliques": [{"pclq": fqn, "min_available": n}...],
    "podgang": name} — the initcontainer args contract
    (initc/cmd/opts/options.go)."""
    deps = waiter_config.get("podcliques", [])
    if not deps:
        return True
    podgang = waiter_config.get("podgang", "")
    counts = parent_ready_counts(
        store, namespace, podgang, [d["pclq"] for d in deps]
    )
    return all(counts[d["pclq"]] >= int(d["min_available"]) for d in deps)


class Waiter:
    """Blocking form for real-pod usage: poll until ready (wait.go:110-164)."""

    def __init__(self, store: Store, namespace: str, waiter_config: Dict) -> None:
        self.store = store
        self.namespace = namespace
        self.config = waiter_config

    def wait(self, poll_interval: float = 1.0, timeout: float = 3600.0) -> bool:
        # wall-clock deadline, NOT an iteration count: a black-holed
        # apiserver makes each probe itself block for the transport timeout,
        # and counting only sleep intervals would overshoot `timeout` by the
        # ratio of the two
        deadline = self.store.clock.now() + timeout
        while True:
            if ready_or_transport_down(self.store, self.namespace, self.config):
                return True
            if self.store.clock.now() >= deadline:
                return False
            self.store.clock.sleep(poll_interval)


def ready_or_transport_down(store: Store, namespace: str, config: Dict) -> bool:
    """is_ready_to_start, surviving TRANSIENT apiserver outages: transport
    failures read as not-ready-yet (retry until the caller's deadline — the
    reference's informer client reconnects the same way); every other error
    (forbidden, not found, bad request) is permanent and re-raises so the
    init container fails fast with the real diagnosis."""
    try:
        return is_ready_to_start(store, namespace, config)
    except GroveError as e:
        if e.code != ERR_TRANSPORT:
            raise
        print(
            f"grove-tpu-initc: apiserver unavailable ({e.code}); retrying",
            file=sys.stderr,
        )
        return False
