"""grove-tpu-initc: deployable pod-side startup-ordering waiter.

The container-runnable form of the reference's grove-initc binary
(/root/reference/operator/initc/): parses repeated
``--podcliques=<fqn>:<minAvailable>`` flags (initc/cmd/opts/options.go),
reads the pod's namespace + podgang name from downward-API files
(initc/internal/wait.go:76-90), then blocks on a pod WATCH filtered by the
``grove.io/podgang`` label until every parent clique has >= minAvailable
Ready pods (wait.go:110-164, readiness predicate :267-275). Exit code 0
unblocks the main containers.

    python -m grove_tpu.initc \
        --apiserver http://operator:8080 \
        --pod-info-dir /etc/grove/pod-info \
        --podcliques my-set-0-prefill:2 --podcliques my-set-0-router:1

Connection: the apiserver URL comes from --apiserver or GROVE_APISERVER
(the in-cluster-config analogue of wait.go:166-187's SA-token client).
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
from typing import Dict, List

from grove_tpu.api import names as namegen
from grove_tpu.initc.waiter import ready_or_transport_down
from grove_tpu.runtime.errors import GroveError


def parse_podclique_flag(values: List[str]) -> List[Dict]:
    """--podcliques=<fqn>:<minAvailable>, repeated (options.go contract)."""
    deps = []
    for raw in values:
        fqn, sep, min_str = raw.rpartition(":")
        if not sep or not fqn or not min_str.isdigit():
            raise ValueError(
                f"--podcliques expects <pclq-fqn>:<minAvailable>, got {raw!r}"
            )
        deps.append({"pclq": fqn, "min_available": int(min_str)})
    return deps


def read_pod_info(pod_info_dir: str) -> Dict[str, str]:
    """Downward-API file contract (wait.go:76-90): the operator injects a
    volume exposing metadata.namespace and the grove.io/podgang label."""
    out = {}
    for key in ("namespace", "podgang"):
        path = os.path.join(pod_info_dir, key)
        with open(path) as f:
            out[key] = f.read().strip()
    return out


def wait_for_parents(
    store,
    namespace: str,
    podgang: str,
    deps: List[Dict],
    timeout: float = 3600.0,
    poll_interval: float = 5.0,
) -> bool:
    """Watch-driven wait: recheck on every pod event of the gang (the
    reference's informer handlers, wait.go:189-237); the poll interval is
    only a safety net against missed events."""
    config = {"podcliques": deps, "podgang": podgang}
    wake = threading.Event()

    def on_event(ev) -> None:
        if (
            ev.kind == "Pod"
            and ev.obj.metadata.labels.get(namegen.LABEL_PODGANG) == podgang
        ):
            wake.set()

    store.subscribe(on_event)
    deadline = store.clock.now() + timeout
    while True:
        if ready_or_transport_down(store, namespace, config):
            return True
        if store.clock.now() >= deadline:
            return False
        wake.clear()
        wake.wait(poll_interval)
    # unreachable


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="grove-tpu-initc", description=__doc__)
    parser.add_argument(
        "--podcliques",
        action="append",
        default=[],
        metavar="FQN:MIN",
        help="parent clique and its minAvailable; repeatable",
    )
    parser.add_argument(
        "--apiserver",
        default=os.environ.get("GROVE_APISERVER", ""),
        help="apiserver base URL (or GROVE_APISERVER)",
    )
    parser.add_argument(
        "--pod-info-dir",
        default="/etc/grove/pod-info",
        help="downward-API mount with namespace/podgang files",
    )
    parser.add_argument("--timeout", type=float, default=3600.0)
    parser.add_argument("--poll-interval", type=float, default=5.0)
    args = parser.parse_args(argv)

    try:
        deps = parse_podclique_flag(args.podcliques)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if not deps:
        print("grove-tpu-initc: no parent cliques; nothing to wait for")
        return 0
    if not args.apiserver:
        print(
            "grove-tpu-initc: --apiserver (or GROVE_APISERVER) is required",
            file=sys.stderr,
        )
        return 2
    try:
        info = read_pod_info(args.pod_info_dir)
    except OSError as e:
        print(f"grove-tpu-initc: pod-info read failed: {e}", file=sys.stderr)
        return 2

    from grove_tpu.cluster.client import HttpStore

    store = HttpStore(args.apiserver, watch_kinds=("Pod",)).start()
    try:
        ok = wait_for_parents(
            store,
            info["namespace"],
            info["podgang"],
            deps,
            timeout=args.timeout,
            poll_interval=args.poll_interval,
        )
    except GroveError as e:
        # permanent apiserver rejection (forbidden / not found / bad
        # request): a misconfiguration, not a timeout — distinct diagnosis
        # and exit code so operators can tell the two apart from logs
        print(
            f"grove-tpu-initc: apiserver rejected the wait ({e.code}): {e}",
            file=sys.stderr,
        )
        return 2
    finally:
        store.stop()
    if ok:
        print("grove-tpu-initc: all parent cliques ready; starting")
        return 0
    print(
        f"grove-tpu-initc: timed out after {args.timeout}s waiting for parents",
        file=sys.stderr,
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
